"""Encode one consumer group's packing problem into the bucketed int32
tensors the device kernel (``ops/assignment.py:pack_group``) consumes.

Layered on the SAME bucketing rules as the placement family
(``models/problem.py``): the partition-row axis and the consumer-column
axis both pad to multiples of 8 (``_pad8``), so the program-store bucket
contract (kalint KA009's runtime half) covers the groups programs with the
codes it already has ("p" rows, "n" columns, "b" sweep batch). Ids appear
only here — everything downstream works in index space, exactly like the
broker encode.

Weight domain: base weight = column value + 1 (an owned partition always
occupies capacity, so idle partitions still balance by count), then the
whole problem — weights AND capacities — right-shifts just enough that the
largest sweep scale cannot overflow int32 (device/host parity is exact
integer equality, so the domain must be shared). The shift is recorded on
the encoding for the envelope's load fractions.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..io.base import ConsumerGroupState
from ..models.problem import _pad8

#: Scaled totals stay under this (int32 headroom for the load accumulator).
_TOTAL_LIMIT = 1 << 30
#: Per-weight scale products stay under this (the int32 multiply itself).
_MULT_LIMIT = (1 << 31) - 1


@dataclass
class GroupEncoding:
    """One group's packing problem, canonicalized to dense index space."""

    group: str
    rows: List[Tuple[str, int]]   # row -> (topic, partition), sorted
    members: List[str]            # column -> member id, sorted; columns
                                  # >= len(real members) are the sweep's
                                  # synthetic extras ("<group>-extra-N")
    real_members: int             # columns backed by actual group members
    weights: np.ndarray           # (P_pad,) int32 base weights (0 on pads)
    capacities: np.ndarray        # (C_pad,) int32 (0 on pad columns)
    current: np.ndarray           # (P_pad,) int32 consumer column or -1
    proc_order: np.ndarray        # (P_pad,) int32 rows by (-weight, row)
    p: int
    c: int                        # usable columns (real + extras)
    p_pad: int
    c_pad: int
    weight_kind: str
    shift: int                    # right-shift applied to weights AND caps
    total_weight: int             # sum of base weights (post-shift)

    def alive(self, consumers: Optional[int] = None) -> np.ndarray:
        """(C_pad,) liveness mask for a candidate count: the first
        ``consumers`` columns (default: every usable column)."""
        k = self.c if consumers is None else min(consumers, self.c_pad)
        mask = np.zeros(self.c_pad, dtype=bool)
        mask[:k] = True
        return mask


def encode_group(
    state: ConsumerGroupState,
    partitions: Optional[Mapping[str, Sequence[int]]] = None,
    weight: str = "lag",
    weight_values: Optional[Mapping[Tuple[str, int], float]] = None,
    max_consumers: Optional[int] = None,
    max_scale_pct: int = 100,
    capacity_headroom: float = 1.25,
) -> GroupEncoding:
    """Canonicalize one group.

    ``partitions`` widens the row universe beyond what the group state
    mentions (topics the group subscribes to but has never committed for);
    ``weight_values`` supplies the column for ``weight != "lag"``
    (throughput sweeps feed the traffic hook's byte rates through here);
    ``max_consumers`` reserves columns past the real membership for the
    autoscale sweep's larger candidates (deterministic
    ``<group>-extra-N`` ids, default capacity); ``max_scale_pct`` is the
    largest weight scale any sweep over this encoding will apply — the
    overflow guard shifts the whole domain to keep int32 exact under it.
    """
    if weight not in ("lag", "throughput"):
        raise ValueError(f"unknown weight column {weight!r}")
    if weight == "throughput" and weight_values is None:
        raise ValueError(
            "weight='throughput' needs weight_values (the traffic "
            "column); only 'lag' is carried by the group state itself"
        )
    universe = {
        (t, int(p))
        for t, per in state.assignment.items()
        for p in per
    } | {
        (t, int(p))
        for t, per in state.lags.items()
        for p in per
    }
    if partitions:
        universe |= {
            (t, int(p)) for t, parts in partitions.items() for p in parts
        }
    rows = sorted(universe)
    p = len(rows)
    p_pad = _pad8(p)

    members = sorted(
        dict.fromkeys(m.member_id for m in state.members)
    )
    real_members = len(members)
    cap_of = {m.member_id: float(m.capacity) for m in state.members}
    c = max(real_members, int(max_consumers or 0), 1)
    c_pad = _pad8(c)
    for i in range(real_members, c):
        members.append(f"{state.group}-extra-{i - real_members}")

    # Base weights: the chosen column + 1, integer.
    base: List[int] = []
    for t, part in rows:
        if weight == "lag":
            v = int(state.lags.get(t, {}).get(part, 0))
        else:
            v = int(round(float(weight_values.get((t, part), 0.0))))
        base.append(max(v, 0) + 1)
    total = sum(base)

    # Capacity resolution: declared estimates where present; EVERY
    # undeclared capacity — a real member without an estimate AND the
    # sweep's synthetic extra columns — gets the fair share of total base
    # weight at the real member count times the headroom knob
    # (``KA_GROUPS_CAPACITY_HEADROOM``), exactly as the knob documents.
    # Constant across sweep candidates: "how many consumers do I need"
    # only makes sense against absolute capacity.
    default_cap = max(
        int(-(-total * max(capacity_headroom, 1.0) // max(real_members, 1))),
        1,
    )
    caps: List[int] = []
    for m in members:
        est = cap_of.get(m, 0.0)
        caps.append(int(round(est)) if est > 0 else default_cap)

    # Overflow guard: shift weights AND capacities until the largest sweep
    # scale keeps every int32 intermediate exact.
    max_scale = max(int(max_scale_pct), 100)
    shift = 0
    max_w = max(base, default=1)
    max_cap = max(caps, default=1)
    while (
        ((total >> shift) * max_scale) // 100 >= _TOTAL_LIMIT
        or (max_w >> shift) * max_scale >= _MULT_LIMIT
        or (max_cap >> shift) >= _TOTAL_LIMIT
    ):
        shift += 1

    weights = np.zeros(p_pad, dtype=np.int32)
    for row, w in enumerate(base):
        weights[row] = max(w >> shift, 1)
    capacities = np.zeros(c_pad, dtype=np.int32)
    for col in range(c):
        capacities[col] = max(caps[col] >> shift, 1)

    col_of = {m: i for i, m in enumerate(members)}
    current = np.full(p_pad, -1, dtype=np.int32)
    for row, (t, part) in enumerate(rows):
        owner = state.assignment.get(t, {}).get(part)
        if owner is not None:
            current[row] = col_of.get(owner, -1)

    order = sorted(range(p), key=lambda r: (-int(weights[r]), r))
    proc_order = np.array(
        order + list(range(p, p_pad)), dtype=np.int32
    )
    return GroupEncoding(
        group=state.group,
        rows=rows,
        members=members,
        real_members=real_members,
        weights=weights,
        capacities=capacities,
        current=current,
        proc_order=proc_order,
        p=p,
        c=c,
        p_pad=p_pad,
        c_pad=c_pad,
        weight_kind=weight,
        shift=shift,
        total_weight=int(weights[:p].sum()),
    )


def decode_plan(
    enc: GroupEncoding, assigned: Sequence[int]
) -> Dict[str, Dict[int, Optional[str]]]:
    """(P_pad,) consumer columns -> ``{topic: {partition: member_id}}``
    over the real rows (column -1 decodes to ``None`` — an unplaceable
    row, only possible when no consumer is alive)."""
    out: Dict[str, Dict[int, Optional[str]]] = {}
    for row, (t, part) in enumerate(enc.rows):
        col = int(assigned[row])
        out.setdefault(t, {})[part] = (
            enc.members[col] if 0 <= col < len(enc.members) else None
        )
    return out
