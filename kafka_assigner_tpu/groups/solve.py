"""Consumer-group plan + autoscale-sweep pipelines (ISSUE 13).

Ingest (backend hook / explicit synthetic opt-in) → :mod:`.encode` →
on-device packing through ``parallel/whatif.py``'s store-backed dispatch →
decode to a sticky rebalance plan / cost curve, with the host greedy
packing oracle (``solvers/greedypack.py``) as the parity pin AND the
crash fallback: a device solve that dies mid-request re-runs here —
same plan bytes, by the parity contract.

Every envelope this module builds is BYTE-STABLE for identical inputs:
no timestamps, no elapsed times, keys emitted sorted — two identical
``ka-groups`` runs (or two identical daemon ``/groups/*`` calls over an
unchanged cache) produce identical bytes, smoke- and test-pinned.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..errors import SolveError
from ..obs.trace import span
from .encode import GroupEncoding, decode_plan, encode_group
from .model import GROUPS_SCHEMA_VERSION, synthetic_group_state


def load_group_states(
    backend,
    partitions,
    groups: Optional[Sequence[str]] = None,
    synthetic: bool = False,
) -> Tuple[dict, bool]:
    """Resolve the packing inputs: ``(states {group: state}, groups_real)``.

    ``synthetic=True`` is the EXPLICIT opt-in for the deterministic
    synthetic family (derived from ``partitions`` — the caller's cached
    topic universe); otherwise the backend hook serves real state or
    refuses loudly (``io/base.py:fetch_consumer_groups`` contract —
    never synthetic-as-real)."""
    if synthetic:
        names = list(groups) if groups else ["synthetic"]
        return (
            {g: synthetic_group_state(g, partitions) for g in names},
            False,
        )
    states = backend.fetch_consumer_groups(groups)
    return dict(states), bool(
        getattr(backend, "supports_groups", lambda: False)()
    )


def parse_int_list(value, default_csv: Optional[str] = None):
    """Normalize a counts/scales input: a list of ints, a comma-separated
    string (flags and query params; blank entries — trailing commas —
    forgiven), or the default CSV when ``value`` is None (``None`` default
    → ``None``). One parser for the CLI and the daemon, so the two
    surfaces cannot drift on what they accept. Raises ``ValueError`` on
    junk."""
    if value is None:
        if default_csv is None:
            return None
        value = default_csv
    if isinstance(value, str):
        value = [v for v in value.split(",") if v.strip()]
    if not isinstance(value, list):
        raise ValueError(
            f"expected a list or CSV of integers, got {value!r}"
        )
    return [int(v) for v in value]


def build_group_bodies(
    states: dict,
    groups_real: bool,
    part_map,
    kind: str,
    weight: str,
    weight_values,
    scales: Sequence[int],
    headroom: float,
    max_candidates: int,
    counts: Optional[Sequence[int]] = None,
    solver: str = "device",
    fallback: str = "greedy",
    probe=None,
) -> Tuple[Dict[str, dict], Dict[str, bool]]:
    """The per-group orchestration both surfaces share (the CLI's
    ``_dispatch_groups`` and the daemon's ``groups_request``): row
    universe → candidate counts → fan-out cap → encode → envelope, per
    group in sorted order. Returns ``(bodies, degraded_by_group)``.

    ``probe`` (the daemon's ``daemon:solver-crash`` chaos seam) runs
    before each group's device build; an :class:`InjectedSolverCrash`
    from it re-runs that group on the packing oracle under
    ``fallback="greedy"`` (marked ``solver: greedy-fallback``) or maps to
    :class:`SolveError` under ``fallback="raise"`` — identical policy to
    a crash inside the dispatch itself. Counters are deliberately NOT
    emitted here: each surface owns its own accounting (the CLI's global
    counters, the supervisor's cluster-labeled ones), derived from the
    returned bodies."""
    from ..faults.inject import InjectedSolverCrash

    bodies: Dict[str, dict] = {}
    degraded_by_group: Dict[str, bool] = {}
    for g in sorted(states):
        st = states[g]
        universe = group_partition_universe(st, part_map)
        if kind == "sweep":
            counts_g = list(counts) if counts else default_counts(
                len(st.members), len(scales), max_candidates
            )
            if len(counts_g) * len(scales) > max_candidates:
                raise ValueError(
                    f"sweep fan-out {len(counts_g) * len(scales)} "
                    f"exceeds KA_GROUPS_MAX_CANDIDATES={max_candidates}; "
                    "narrow counts/scales or raise the knob"
                )
            enc = encode_group(
                st, partitions=universe, weight=weight,
                weight_values=weight_values,
                max_consumers=max(counts_g), max_scale_pct=max(scales),
                capacity_headroom=headroom,
            )

            def builder(sv, enc=enc, counts_g=counts_g):
                return group_sweep_envelope(
                    enc, counts_g, scales, groups_real,
                    solver=sv, fallback=fallback,
                )
        else:
            enc = encode_group(
                st, partitions=universe, weight=weight,
                weight_values=weight_values, capacity_headroom=headroom,
            )

            def builder(sv, enc=enc):
                return group_plan_envelope(
                    enc, groups_real, solver=sv, fallback=fallback,
                )
        try:
            if probe is not None:
                probe()
            body, degraded = builder(solver)
        except InjectedSolverCrash as e:
            if fallback != "greedy":
                raise SolveError(
                    f"groups solve crashed in-request "
                    f"({type(e).__name__}: {e})"
                ) from e
            body, _ = builder("greedy")
            body["solver"] = "greedy-fallback"
            degraded = True
        bodies[g] = body
        degraded_by_group[g] = degraded
    return bodies, degraded_by_group


def subscribed_partitions(states: dict, part_map) -> dict:
    """The union of every requested group's row universe — what a
    ``weight="throughput"`` traffic fetch should cover (backend I/O
    proportional to the packing problem, not the cluster)."""
    out: Dict[str, list] = {}
    for st in states.values():
        out.update(group_partition_universe(st, part_map))
    return out


def group_partition_universe(state, part_map) -> dict:
    """The row universe for one group: the cluster's partition lists
    (``part_map``, from the metadata cache) restricted to the topics the
    group SUBSCRIBES to (mentions in its assignment or lag maps) — so a
    group whose committed offsets cover only part of a topic still packs
    the topic's every partition, without dragging unrelated topics into
    its problem. This is the reconciliation the ``ConsumerGroupState``
    contract promises (io/base.py)."""
    subscribed = set(state.assignment) | set(state.lags)
    return {
        t: part_map[t] for t in sorted(subscribed) if t in part_map
    }


def _member_view(enc: GroupEncoding, load) -> List[dict]:
    """The envelope's member table over the REAL membership columns."""
    out = []
    for col in range(enc.c):
        cap = int(enc.capacities[col])
        out.append({
            "member": enc.members[col],
            "capacity": cap,
            "load": int(load[col]),
            "load_frac": round(int(load[col]) / max(cap, 1), 4),
        })
    return out


def _host_pack(enc: GroupEncoding, alive, scale_pct: int = 100):
    """The oracle run in the device tuple's shape (the fallback lane)."""
    from ..solvers.greedypack import pack_consumers, scale_weights

    w = scale_weights([int(x) for x in enc.weights], scale_pct, enc.p)
    res = pack_consumers(
        w, [int(x) for x in enc.capacities],
        [int(x) for x in enc.current], [int(x) for x in enc.proc_order],
        [bool(x) for x in alive], enc.p,
    )
    return (
        np.asarray(res.assigned, dtype=np.int32),
        np.asarray(res.load, dtype=np.int32),
        res.moved,
        res.overflowed,
        not res.feasible,
    )


def group_plan_envelope(
    enc: GroupEncoding,
    groups_real: bool,
    solver: str = "device",
    fallback: str = "greedy",
) -> Tuple[dict, bool]:
    """One group's sticky, movement-minimizing rebalance plan body.

    ``solver="device"`` dispatches the packing kernel (program-store
    warm); ``"greedy"`` runs the host oracle directly. A crashed device
    solve falls back to the oracle when ``fallback="greedy"``
    (``groups.solve_fallbacks``; plan bytes unchanged by the parity pin)
    or re-raises as :class:`SolveError` under ``fallback="raise"`` —
    the strict-policy lane. Returns ``(body, degraded)``."""
    from ..parallel.whatif import pack_group_on_device

    alive = enc.alive(enc.c if enc.real_members == 0 else enc.real_members)
    degraded = False
    used = solver
    with span("groups/plan"):
        if solver == "device":
            try:
                assigned, load, moved, overflowed, infeasible = (
                    pack_group_on_device(
                        enc.weights, enc.capacities, enc.current,
                        enc.proc_order, alive, enc.p,
                    )
                )
            except (ValueError, KeyError):
                raise  # malformed inputs are client errors, not crashes
            except Exception as e:
                if fallback != "greedy":
                    raise SolveError(
                        f"groups packing solve crashed "
                        f"({type(e).__name__}: {e})"
                    ) from e
                degraded = True
                used = "greedy-fallback"
                assigned, load, moved, overflowed, infeasible = _host_pack(
                    enc, alive
                )
        else:
            used = "greedy"
            assigned, load, moved, overflowed, infeasible = _host_pack(
                enc, alive
            )
    plan = {
        t: {str(p): m for p, m in sorted(per.items())}
        for t, per in sorted(decode_plan(enc, assigned).items())
    }
    body = {
        "schema_version": GROUPS_SCHEMA_VERSION,
        "kind": "groups-plan",
        "group": enc.group,
        "groups_real": groups_real,
        "weight": enc.weight_kind,
        "solver": used,
        "members": _member_view(enc, load),
        "plan": plan,
        "moves": int(moved),
        "overflowed": int(overflowed),
        "feasible": not bool(infeasible),
        "partitions": enc.p,
        "total_weight": enc.total_weight,
        "weight_shift": enc.shift,
    }
    return body, degraded


def group_sweep_envelope(
    enc: GroupEncoding,
    counts: Sequence[int],
    scale_pcts: Sequence[int],
    groups_real: bool,
    solver: str = "device",
    fallback: str = "greedy",
) -> Tuple[dict, bool]:
    """The autoscale cost curve for one group: every (consumer count ×
    lag-scale) candidate evaluated as ONE batched device fan-out.
    Returns ``(body, degraded)``; candidates are emitted sorted by
    (scale, consumers), and ``recommended_consumers`` answers the
    headline question — the smallest candidate count that packs feasibly
    at the LOWEST swept scale (None when none does)."""
    from ..parallel.whatif import evaluate_group_candidates

    counts = sorted({int(k) for k in counts if int(k) >= 1})
    scale_pcts = sorted({max(int(s), 1) for s in scale_pcts})
    if not counts or not scale_pcts:
        raise ValueError("sweep needs at least one count and one scale")
    if max(counts) > enc.c:
        # Columns past enc.c are PAD columns (capacity 0, no member id):
        # letting a candidate mark one alive would score feasibility
        # against a consumer that does not exist.
        raise ValueError(
            f"candidate count {max(counts)} exceeds the encoding's "
            f"usable consumer columns ({enc.c}); re-encode with "
            f"max_consumers={max(counts)}"
        )
    cand = [(s, k) for s in scale_pcts for k in counts]
    alive_masks = np.zeros((len(cand), enc.c_pad), dtype=bool)
    for i, (_s, k) in enumerate(cand):
        alive_masks[i, :k] = True
    scales = np.array([s for s, _k in cand], dtype=np.int32)

    degraded = False
    used = solver
    with span("groups/sweep"):
        if solver == "device":
            try:
                moved, overflowed, infeasible, load = (
                    evaluate_group_candidates(
                        enc.weights, enc.capacities, enc.current,
                        enc.proc_order, alive_masks, scales, enc.p,
                    )
                )
            except (ValueError, KeyError):
                raise  # malformed inputs are client errors, not crashes
            except Exception as e:
                if fallback != "greedy":
                    raise SolveError(
                        f"groups autoscale sweep crashed "
                        f"({type(e).__name__}: {e})"
                    ) from e
                degraded = True
                used = "greedy-fallback"
                moved, overflowed, infeasible, load = _host_sweep(
                    enc, alive_masks, scales
                )
        else:
            used = "greedy"
            moved, overflowed, infeasible, load = _host_sweep(
                enc, alive_masks, scales
            )
    candidates = []
    for i, (s, k) in enumerate(cand):
        caps = enc.capacities[:k].astype(np.int64)
        row_load = np.asarray(load[i][:k], dtype=np.int64)
        frac = float(
            (row_load / np.maximum(caps, 1)).max()
        ) if k else 0.0
        candidates.append({
            "consumers": k,
            "scale_pct": s,
            "feasible": not bool(infeasible[i]),
            "moved": int(moved[i]),
            "overflowed": int(overflowed[i]),
            "max_load_frac": round(frac, 4),
        })
    base_scale = scale_pcts[0]
    feasible_at_base = sorted(
        c["consumers"] for c in candidates
        if c["scale_pct"] == base_scale and c["feasible"]
    )
    body = {
        "schema_version": GROUPS_SCHEMA_VERSION,
        "kind": "groups-sweep",
        "group": enc.group,
        "groups_real": groups_real,
        "weight": enc.weight_kind,
        "solver": used,
        "candidates": candidates,
        "recommended_consumers": (
            feasible_at_base[0] if feasible_at_base else None
        ),
        "counts": counts,
        "scales_pct": scale_pcts,
        "partitions": enc.p,
        "total_weight": enc.total_weight,
        "weight_shift": enc.shift,
    }
    return body, degraded


def _host_sweep(enc: GroupEncoding, alive_masks, scales):
    """Oracle fallback for the whole candidate batch (slow lane — only
    taken when the device sweep crashed)."""
    moved, overflowed, infeasible, loads = [], [], [], []
    for i in range(len(alive_masks)):
        _a, load, m, o, inf = _host_pack(
            enc, alive_masks[i], int(scales[i])
        )
        moved.append(m)
        overflowed.append(o)
        infeasible.append(inf)
        loads.append(load)
    return (
        np.asarray(moved, dtype=np.int64),
        np.asarray(overflowed, dtype=np.int64),
        np.asarray(infeasible, dtype=bool),
        np.stack(loads),
    )


def default_counts(
    real_members: int, n_scales: int, max_candidates: int
) -> List[int]:
    """The sweep's default candidate counts: 1..2× the current membership
    (at least 1..4), truncated so counts × scales stays inside the
    fan-out cap (``KA_GROUPS_MAX_CANDIDATES``)."""
    top = max(2 * max(real_members, 1), 4)
    counts = list(range(1, top + 1))
    budget = max(max_candidates // max(n_scales, 1), 1)
    return counts[:budget]


def throughput_weights(backend, partitions) -> Dict[Tuple[str, int], float]:
    """The throughput weight column: per-partition produced-byte rates
    through the PR 11 traffic hook (real where the backend has meters,
    the deterministic synthetic series elsewhere — the envelope's
    ``weight`` field names the column either way)."""
    stats = backend.fetch_partition_traffic(
        {t: sorted(parts) for t, parts in partitions.items()}
    )
    return {
        (t, int(p)): float(tr.in_bytes)
        for t, per in stats.items()
        for p, tr in per.items()
    }
