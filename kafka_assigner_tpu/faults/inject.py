"""Deterministic, seedable fault injection for the cluster-I/O and solve
paths — the harness that drives the resilience layer (ISSUE 5).

The reference tool can only be failure-tested against a real, misbehaving
ZooKeeper quorum; dynamic-reconfiguration work (arXiv:1602.03770,
arXiv:2206.11170) treats metadata churn *during* the plan computation as the
common case, so this repro injects those races hermetically, at the exact
protocol seams where production sees them. Faults are injected CLIENT-side,
inside ``io/zkwire.py``'s socket handling, so one process (the CLI, the
chaos soak, a unit test) reproduces a byte-exact failure schedule with no
cooperation from the server.

Fault taxonomy (``FAULT_KINDS``), one per failure class the tentpole names:

========== ================ ==============================================
kind       scope            effect at the hook
========== ================ ==============================================
blackhole  connect          the connect attempt raises ConnectionRefused
expire     handshake        the ConnectResponse is rewritten to the
                            session-expired form (timeOut=0, sessionId=0)
drop       reply            the session socket is closed mid-frame and the
                            read raises ConnectionReset
trunc      reply            the reply frame is truncated (arg = bytes
                            kept; default half), desyncing the decoder
slow       reply            the reply is delayed ``arg`` seconds (default
                            0.05) before the client sees it
nonode     reply            the reply's error field is rewritten to
                            KeeperException.NoNode — a znode deleted
                            between ``getChildren`` and ``getData``
crash      solve            the TPU solver raises ``InjectedSolverCrash``
                            before dispatch (stands in for a compile
                            failure / device OOM)
crash      warmup           the ingest-overlapped warm-up thread raises
                            ``InjectedWarmupCrash`` before it touches the
                            program store (the warm-up must degrade to the
                            cold path, byte-identically — ISSUE 6)
drop       write            a reassignment write raises ConnectionReset
                            BEFORE the backend applies it — the engine must
                            read the state back and resubmit, never blindly
                            replay (ISSUE 7 write-safety rule)
lost       write            the write is ACKED but never applied (a quorum
                            member crashed after the ack) — the convergence
                            poll must time out; the old assignment stays
                            complete, never half-moved
stall      converge         one convergence poll observes frozen state (the
                            controller is busy); the engine must retry with
                            backoff, not declare failure
crash      wave             the execution engine dies at a wave boundary
                            (``InjectedExecCrash`` — the chaos stand-in for
                            kill -9 between waves); the journal must resume
                            the run to a byte-identical final state
drop       watch            a watch notification is discarded before the
                            daemon processes it — the periodic full-resync
                            escape hatch must reconverge the cache (ISSUE 8)
expire     session          the daemon's ZooKeeper session expires mid-
                            request — re-establishment + watch re-arm + a
                            bounded resync, serving stale-marked responses
                            meanwhile
stall      resync           one daemon resync attempt dies mid-flight
                            (``InjectedResyncStall``); retried with backoff
                            while responses stay degraded, never an error
solver-crash daemon         the solve crashes inside a served request
                            (``InjectedSolverCrash``); the request degrades
                            to the greedy fallback in isolation
crash      dispatch         one coalesced device dispatch of the batched
                            solve dispatcher crashes mid-batch
                            (``InjectedSolverCrash``) — only that batch's
                            jobs degrade, each per-job (ISSUE 14)
stall      dispatch         the dispatcher stalls ``arg`` seconds before a
                            coalesced dispatch — visible as queue wait and
                            watchdog overrun, never a hang
verdict-flap controller     one controller evaluation's verdict is flipped
                            (recommend⇄hold) — the hysteresis gate must
                            reset its confirmation streak, never act on a
                            flapping objective (ISSUE 15)
exec-crash controller       the controller's supervised forward execution
                            dies at a wave boundary (``InjectedExecCrash``)
                            — abort-to-rollback must restore the
                            byte-identical pre-action assignment and open
                            the controller breaker
regress    controller       the post-move re-score reads as a health
                            regression (achieved worse than projected) —
                            the same rollback path fires and the breaker
                            opens
========== ================ ==============================================

Spec grammar (``KA_FAULTS_SPEC``): semicolon-separated events
``scope[@cluster]:index=kind[:arg]`` — the fault fires the ``index``-th
time that scope's hook runs (0-based, per-scope counters), e.g.::

    KA_FAULTS_SPEC='reply:3=drop;reply:6=nonode;connect:0=blackhole'

``@cluster`` (ISSUE 9) addresses one cluster of the multi-cluster daemon:
``session:expire@west`` is spelled ``session@west:1=expire`` and fires only
when the ``west`` supervisor consults the hook, at ``west``'s OWN per-scope
index — so a schedule can blackout cluster A while cluster B's hooks stay
untouched (the bulkhead chaos rows). Clusterless events keep the legacy
global per-scope counter, byte-identical to every historical schedule.

or the single word ``random``: a schedule drawn from
``random.Random(KA_FAULTS_SEED)`` with per-hook probability
``KA_FAULTS_RATE`` over the first :data:`RANDOM_HORIZON` indexes of each
scope (the chaos soak's mode; same seed ⇒ same schedule, byte-for-byte).

Activation: :func:`install` (programmatic, wins) or the ``KA_FAULTS_SPEC``
knob (read via :func:`active_injector`, cached per (spec, seed) so the wire
client and the solver see one coherent schedule). A malformed spec is
ignored LOUDLY and injection stays off — the house rule for every knob.
Every fired fault prints one stderr line and bumps the ``faults.injected``
(+ ``faults.injected.<kind>``) counters, so a run report accounts for the
schedule it survived.
"""
from __future__ import annotations

import random
import struct
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..obs import flight
from ..obs.metrics import counter_add

#: Scopes (hook sites) and the kinds each accepts.
FAULT_SCOPES: Dict[str, Tuple[str, ...]] = {
    "connect": ("blackhole",),
    "handshake": ("expire",),
    "reply": ("drop", "trunc", "slow", "nonode"),
    "solve": ("crash",),
    "warmup": ("crash",),
    "write": ("drop", "lost"),
    "converge": ("stall",),
    "wave": ("crash",),
    # The daemon seams (ISSUE 8): a lost watch notification, a session
    # expiry landing mid-request, a stalled resync attempt, and a solver
    # crash inside a served request — each consulted by the resident
    # assigner daemon (`daemon/service.py`), never by the one-shot CLI.
    "watch": ("drop",),
    "session": ("expire",),
    "resync": ("stall",),
    "daemon": ("solver-crash",),
    # The batched solve dispatcher (ISSUE 14): consulted once per coalesced
    # device dispatch, ON the dispatcher thread — a crash must fail only
    # that batch's jobs (each degrades per-job), a stall must surface as
    # queue wait, never a hang.
    "dispatch": ("crash", "stall"),
    # The autonomous rebalance controller (ISSUE 15): three seams, each
    # consulted with its OWN per-kind counter (`controller_point`) —
    # verdict-flap flips one evaluation's verdict (hysteresis must hold),
    # exec-crash kills the supervised forward execution at a wave boundary
    # (abort-to-rollback must restore the pre-action bytes), regress makes
    # the post-move re-score read as a health regression (same rollback
    # path, breaker opens).
    "controller": ("verdict-flap", "exec-crash", "regress"),
    # The fleet scheduler (ISSUE 20): lease-expire sweeps every live
    # admission lease at a prune point (a crashed holder's TTL elapsing,
    # compressed to now — the fleet must hand the slot on, and the stale
    # holder's release must degrade to a loud no-op), ledger-torn makes
    # one ledger load read as externally damaged (accounting restarts
    # empty, loudly — never a crash, never silent reuse of torn bytes),
    # recovery-crash kills a startup-recovery resume at a wave boundary
    # (the journal stays in-progress; the NEXT boot's scan must converge).
    "fleet": ("lease-expire", "ledger-torn", "recovery-crash"),
}
FAULT_KINDS = tuple(k for kinds in FAULT_SCOPES.values() for k in kinds)

#: ``random`` mode draws events over this many indexes per scope — enough to
#: cover any realistic mode-3 run against the test fixtures while keeping the
#: schedule finite and printable.
RANDOM_HORIZON: Dict[str, int] = {
    "connect": 3, "handshake": 3, "reply": 64, "solve": 2, "warmup": 2,
    "write": 8, "converge": 8, "wave": 4,
    "watch": 8, "session": 4, "resync": 4, "daemon": 4, "dispatch": 4,
    "controller": 4, "fleet": 4,
}

#: The scope iteration order of :func:`random_schedule`. Frozen EXPLICITLY —
#: new scopes append at the end (never alphabetical insertion), so a
#: pre-existing seed keeps drawing the exact same events for the scopes it
#: already covered. (A ``sorted(FAULT_SCOPES)`` walk would have reshuffled
#: every historical schedule the moment ``converge`` landed before
#: ``handshake``.)
RANDOM_ORDER: Tuple[str, ...] = (
    "connect", "handshake", "reply", "solve", "warmup",
    "write", "converge", "wave",
    "watch", "session", "resync", "daemon",
    "dispatch",
    "controller",
    "fleet",
)

ERR_NONODE = -101


class FaultSpecError(ValueError):
    """``KA_FAULTS_SPEC`` does not parse (unknown scope/kind, bad index)."""


class InjectedSolverCrash(RuntimeError):
    """The ``solve`` fault point fired — stands in for an XLA compile
    failure or device OOM (both surface as RuntimeError subclasses)."""


class InjectedWarmupCrash(RuntimeError):
    """The ``warmup`` fault point fired — stands in for anything killing the
    ingest-overlapped warm-up thread (store corruption, compile failure on
    the background thread). The contract under test: the solve must proceed
    on the cold path, byte-identically."""


class InjectedResyncStall(RuntimeError):
    """The ``resync`` fault point fired — one daemon resync attempt dies
    mid-flight (a flapping quorum during the re-read). The contract under
    test: the daemon retries with backoff, keeps serving STALE-MARKED
    responses meanwhile (``status: "degraded"``, never an error), and
    converges once an attempt succeeds."""


class InjectedExecCrash(RuntimeError):
    """The ``wave`` fault point fired — the execution engine "process" dies
    at a wave boundary (the deterministic stand-in for kill -9 between
    waves). Deliberately NOT mapped to a documented exit code: a killed
    process has no exit path, and the harnesses catch this class exactly
    where a supervisor would observe the dead process. The contract under
    test: the journal must resume the run to a byte-identical final state."""


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: fires the ``index``-th time ``scope``'s hook
    runs. ``arg`` is kind-specific (trunc: bytes kept; slow: seconds).
    ``cluster`` (None = any) addresses one cluster of the multi-cluster
    daemon: the event fires at that cluster's own per-scope index, only
    when the hook is consulted with a matching cluster."""

    scope: str
    index: int
    kind: str
    arg: Optional[float] = None
    cluster: Optional[str] = None

    def __str__(self) -> str:
        suffix = "" if self.arg is None else f":{self.arg:g}"
        at = "" if self.cluster is None else f"@{self.cluster}"
        return f"{self.scope}{at}:{self.index}={self.kind}{suffix}"


def parse_spec(
    spec: str, seed: int = 0, rate: float = 0.05
) -> List[FaultEvent]:
    """Parse a ``KA_FAULTS_SPEC`` value into a schedule. ``random`` draws a
    seed-deterministic schedule; anything else is the explicit event list."""
    spec = spec.strip()
    if spec == "random":
        return random_schedule(seed, rate)
    events: List[FaultEvent] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        head, eq, kind_arg = raw.partition("=")
        if not eq:
            raise FaultSpecError(
                f"fault event {raw!r} is not of the form "
                "scope[@cluster]:index=kind"
            )
        scope_part, _, idx_s = head.partition(":")
        scope, at, cluster = scope_part.partition("@")
        scope = scope.strip()
        cluster = cluster.strip() or None
        if at and cluster is None:
            raise FaultSpecError(
                f"empty cluster name after '@' in {raw!r}"
            )
        if cluster is not None and not all(
            c.isalnum() or c in "_.-" for c in cluster
        ):
            raise FaultSpecError(
                f"invalid cluster name {cluster!r} in {raw!r} "
                "(letters, digits, '_', '.', '-' only)"
            )
        if scope not in FAULT_SCOPES:
            raise FaultSpecError(
                f"unknown fault scope {scope!r} in {raw!r} "
                f"(expected one of {sorted(FAULT_SCOPES)})"
            )
        try:
            index = int(idx_s) if idx_s.strip() else 0
        except ValueError:
            raise FaultSpecError(
                f"fault index {idx_s!r} in {raw!r} is not an integer"
            ) from None
        if index < 0:
            raise FaultSpecError(f"fault index must be >= 0 in {raw!r}")
        kind, _, arg_s = kind_arg.partition(":")
        kind = kind.strip()
        if kind not in FAULT_SCOPES[scope]:
            raise FaultSpecError(
                f"fault kind {kind!r} is not valid for scope {scope!r} "
                f"(expected one of {FAULT_SCOPES[scope]})"
            )
        arg = None
        if arg_s.strip():
            try:
                arg = float(arg_s)
            except ValueError:
                raise FaultSpecError(
                    f"fault arg {arg_s!r} in {raw!r} is not a number"
                ) from None
        events.append(FaultEvent(scope, index, kind, arg, cluster))
    return events


def random_schedule(seed: int, rate: float) -> List[FaultEvent]:
    """A seed-deterministic randomized schedule: each (scope, index) slot up
    to :data:`RANDOM_HORIZON` fires with probability ``rate``, the kind drawn
    uniformly from the scope's kinds. Same seed ⇒ identical schedule."""
    rng = random.Random(int(seed))
    events: List[FaultEvent] = []
    for scope in RANDOM_ORDER:
        kinds = FAULT_SCOPES[scope]
        for index in range(RANDOM_HORIZON[scope]):
            if rng.random() < rate:
                events.append(FaultEvent(scope, index, rng.choice(kinds)))
    return events


class FaultInjector:
    """One live schedule: per-scope hook counters plus the fired-event log.

    Hook methods are called from the wire client's socket paths (possibly on
    the ingest producer thread) and from the solver; each consults the
    schedule at the scope's current index and fires at most one event. The
    same instance must serve every hook of a run so the counters stay
    coherent — :func:`active_injector` caches per (spec, seed).
    """

    def __init__(self, events: List[FaultEvent]) -> None:
        self.schedule: Tuple[FaultEvent, ...] = tuple(events)
        self._events = {
            (e.scope, e.cluster, e.index): e for e in events
        }
        self._counts: Dict[str, int] = {}
        #: Per-(scope, cluster) counters for @cluster-addressed events —
        #: a cluster-scoped event fires at that cluster's OWN index, so
        #: schedules stay deterministic however the daemon interleaves its
        #: supervisors' hooks.
        self._cluster_counts: Dict[Tuple[str, str], int] = {}
        self.fired: List[FaultEvent] = []

    def _next(
        self, scope: str, cluster: Optional[str] = None
    ) -> Optional[FaultEvent]:
        i = self._counts.get(scope, 0)
        self._counts[scope] = i + 1
        ev = self._events.get((scope, None, i))
        if ev is not None:
            # A clusterless (global-index) event claims this consult; the
            # per-cluster index is deliberately NOT consumed — a @cluster
            # event colliding with a global one fires at that cluster's
            # next consult instead of being silently lost.
            return ev
        if cluster is not None:
            key = (scope, cluster)
            j = self._cluster_counts.get(key, 0)
            self._cluster_counts[key] = j + 1
            ev = self._events.get((scope, cluster, j))
        return ev

    def _fire(self, ev: FaultEvent) -> None:
        self.fired.append(ev)
        counter_add("faults.injected")
        counter_add(f"faults.injected.{ev.kind}")
        # Flight-recorder correlation (ISSUE 10): a chaos post-mortem diffs
        # the recorder's `fault` events against the schedule it injected —
        # no-op outside a daemon (the recorder is never enabled).
        flight.record(
            "fault", ev.cluster, spec=str(ev), scope=ev.scope,
            fault_kind=ev.kind,
        )
        print(f"kafka-assigner: fault injected: {ev}", file=sys.stderr)

    # -- hooks -------------------------------------------------------------

    def connect_attempt(self) -> None:
        """Called before each socket connect attempt; ``blackhole`` refuses."""
        ev = self._next("connect")
        if ev is not None and ev.kind == "blackhole":
            self._fire(ev)
            raise ConnectionRefusedError(
                "injected fault: connect blackhole"
            )

    def filter_handshake(self, frame: bytes) -> bytes:
        """Called with each ConnectResponse frame; ``expire`` rewrites it to
        the session-expired form the real server sends (timeOut=0)."""
        ev = self._next("handshake")
        if ev is not None and ev.kind == "expire":
            self._fire(ev)
            return (
                struct.pack(">iiq", 0, 0, 0)
                + struct.pack(">i", 16) + b"\x00" * 16
            )
        return frame

    def filter_reply(self, frame: bytes, sock) -> bytes:
        """Called with each in-session reply frame (serial and pipelined);
        may delay, corrupt, or kill the read according to the schedule."""
        ev = self._next("reply")
        if ev is None:
            return frame
        if ev.kind == "slow":
            self._fire(ev)
            time.sleep(ev.arg if ev.arg is not None else 0.05)
            return frame
        if ev.kind == "trunc":
            self._fire(ev)
            keep = int(ev.arg) if ev.arg is not None else len(frame) // 2
            return frame[:max(0, keep)]
        if ev.kind == "drop":
            self._fire(ev)
            if sock is not None:
                try:
                    sock.close()
                except OSError:  # kalint: disable=KA008 -- socket already dead; the injected reset below is the signal
                    pass
            raise ConnectionResetError(
                "injected fault: socket dropped mid-frame"
            )
        if ev.kind == "nonode":
            self._fire(ev)
            # ReplyHeader = xid(4) + zxid(8) + err(4); rewrite err, drop the
            # body (a real NoNode reply carries none).
            return frame[:12] + struct.pack(">i", ERR_NONODE)
        return frame

    def solve_attempt(self) -> None:
        """Called at the top of each batched TPU solve; ``crash`` raises."""
        ev = self._next("solve")
        if ev is not None and ev.kind == "crash":
            self._fire(ev)
            raise InjectedSolverCrash(
                "injected fault: TPU solver crash (compile failure / OOM "
                "stand-in)"
            )

    def warmup_attempt(self) -> None:
        """Called at the top of the ingest warm-up thread; ``crash`` raises
        (the thread's degradation handler is what's under test)."""
        ev = self._next("warmup")
        if ev is not None and ev.kind == "crash":
            self._fire(ev)
            raise InjectedWarmupCrash(
                "injected fault: warm-up thread crash (store/compile "
                "failure stand-in)"
            )

    def backend_reply(self, missing_exc=None):
        """Backend-level twin of :meth:`filter_reply` for metadata adapters
        that never see raw frames (the kazoo client, the Kafka AdminClient):
        the SAME ``reply`` scope and schedule fire regardless of backend,
        with each kind mapped onto the adapter's failure surface — ``slow``
        delays the op, ``drop``/``trunc`` become a connection loss, and
        ``nonode`` becomes the adapter's missing-entity error
        (``missing_exc``, default the wire client's ``NoNodeError``; the
        AdminClient passes ``KeyError``, its unknown-topic class)."""
        ev = self._next("reply")
        if ev is None:
            return
        if ev.kind == "slow":
            self._fire(ev)
            time.sleep(ev.arg if ev.arg is not None else 0.05)
            return
        if ev.kind in ("drop", "trunc"):
            self._fire(ev)
            raise ConnectionResetError(
                "injected fault: backend connection lost mid-read"
            )
        if ev.kind == "nonode":
            self._fire(ev)
            if missing_exc is None:
                from ..io.zkwire import NoNodeError as missing_exc
            raise missing_exc("injected fault: entity vanished mid-read")

    def write_attempt(self) -> Optional[str]:
        """Called by each backend's reassignment-write path (the ISSUE 7
        write seam). ``drop`` raises before the write applies — the engine
        must read back and resubmit, never blindly replay. ``lost`` returns
        ``"lost"``: the backend acks the write but never applies it (the
        caller skips the apply), so the convergence poll must time out with
        the OLD assignment still complete."""
        ev = self._next("write")
        if ev is None:
            return None
        if ev.kind == "drop":
            self._fire(ev)
            raise ConnectionResetError(
                "injected fault: reassignment write dropped before apply"
            )
        if ev.kind == "lost":
            self._fire(ev)
            return "lost"
        return None

    def converge_poll(self) -> bool:
        """Called once per convergence-state read; a ``stall`` event freezes
        that one poll (the backend reports no progress), so the engine's
        retry/backoff loop — not its failure path — is what's exercised."""
        ev = self._next("converge")
        if ev is not None and ev.kind == "stall":
            self._fire(ev)
            return True
        return False

    def wave_boundary(self) -> None:
        """Called by the execution engine between waves; ``crash`` raises
        :class:`InjectedExecCrash` — the kill-between-waves stand-in the
        resume contract is proven against."""
        ev = self._next("wave")
        if ev is not None and ev.kind == "crash":
            self._fire(ev)
            raise InjectedExecCrash(
                "injected fault: execution engine killed at a wave boundary"
            )

    # -- daemon seams (ISSUE 8) --------------------------------------------

    def watch_delivery(self, cluster: Optional[str] = None) -> bool:
        """Called by the daemon per received watch notification; a ``drop``
        event makes the daemon DISCARD it (a notification lost between the
        quorum and the client) — the periodic full-resync escape hatch, not
        the watch, must then reconverge the cache. ``cluster`` is the
        consulting supervisor's cluster name (``@cluster`` addressing)."""
        ev = self._next("watch", cluster)
        if ev is not None and ev.kind == "drop":
            self._fire(ev)
            return True
        return False

    def session_check(self, cluster: Optional[str] = None) -> bool:
        """Called by the daemon at the top of each served request; an
        ``expire`` event tells the daemon to kill its own ZooKeeper session
        NOW (the deterministic stand-in for a server-side session expiry
        landing mid-request) — re-establishment, watch re-arm and the
        bounded resync are what's under test. ``@cluster`` addressing
        blackouts one supervisor while the others' requests stay clean."""
        ev = self._next("session", cluster)
        if ev is not None and ev.kind == "expire":
            self._fire(ev)
            return True
        return False

    def resync_attempt(self, cluster: Optional[str] = None) -> None:
        """Called at the top of each daemon resync pass; ``stall`` raises
        :class:`InjectedResyncStall` — the daemon must retry with backoff
        and serve stale-marked responses meanwhile, never an error."""
        ev = self._next("resync", cluster)
        if ev is not None and ev.kind == "stall":
            self._fire(ev)
            raise InjectedResyncStall(
                "injected fault: daemon resync attempt stalled"
            )

    def dispatch_attempt(self, cluster: Optional[str] = None) -> None:
        """Called by the batched solve dispatcher once per coalesced device
        dispatch, on the dispatcher thread (ISSUE 14). ``crash`` raises
        :class:`InjectedSolverCrash` into THAT batch only — every job in it
        degrades per-job (whatif rows re-run solo, plans fall back through
        their own crash handling) while other batches, other clusters and
        the dispatcher thread itself survive. ``stall`` sleeps ``arg``
        seconds (default 0.05) before the dispatch — the stall shows up as
        queue wait (``daemon.solve.queue_ms``) and watchdog overrun,
        never a hang."""
        ev = self._next("dispatch", cluster)
        if ev is None:
            return
        if ev.kind == "crash":
            self._fire(ev)
            raise InjectedSolverCrash(
                "injected fault: coalesced solve dispatch crashed mid-batch"
            )
        if ev.kind == "stall":
            self._fire(ev)
            time.sleep(ev.arg if ev.arg is not None else 0.05)

    def controller_point(self, kind: str,
                         cluster: Optional[str] = None) -> bool:
        """Called by the autonomous rebalance controller (ISSUE 15) at its
        three seams, each identified by the KIND it consults for:
        ``verdict-flap`` once per evaluation (a firing flips that
        evaluation's verdict — the hysteresis gate must absorb it),
        ``exec-crash`` once per forward-execution wave boundary (raises
        :class:`InjectedExecCrash` mid-loop — abort-to-rollback must
        restore the pre-action assignment bytes), ``regress`` once per
        post-move re-score (a firing makes the achieved score read as a
        regression — same rollback path, controller breaker opens).

        Unlike the single-seam scopes, each kind keeps its OWN consult
        counter, so ``controller:1=exec-crash`` means "the second wave
        boundary" regardless of how many evaluations ran before it. The
        schedule still keys events ``(scope, cluster, index)``, so one
        schedule can carry at most one controller event per index."""
        key = f"controller.{kind}"
        i = self._counts.get(key, 0)
        self._counts[key] = i + 1
        ev = self._events.get(("controller", None, i))
        if ev is not None and ev.kind != kind:
            ev = None
        if ev is None and cluster is not None:
            ckey = (key, cluster)
            j = self._cluster_counts.get(ckey, 0)
            self._cluster_counts[ckey] = j + 1
            ev = self._events.get(("controller", cluster, j))
            if ev is not None and ev.kind != kind:
                ev = None
        if ev is None:
            return False
        self._fire(ev)
        if kind == "exec-crash":
            raise InjectedExecCrash(
                "injected fault: controller forward execution killed at a "
                "wave boundary"
            )
        return True

    def fleet_point(self, kind: str,
                    cluster: Optional[str] = None) -> bool:
        """Called by the fleet scheduler (ISSUE 20) at its three seams,
        each identified by the KIND it consults for: ``lease-expire``
        once per lease-prune sweep (a firing expires every live lease as
        if its holder stopped heartbeating `KA_FLEET_LEASE_TTL` ago — the
        next admission wins the slot, and the stale holder's own release
        degrades to a loud no-op), ``ledger-torn`` once per ledger load
        (a firing makes the read report external damage — accounting
        restarts empty, loudly), ``recovery-crash`` once per startup-
        recovery wave boundary (raises :class:`InjectedExecCrash` — the
        resumed journal stays in-progress and the NEXT boot retries).

        Like ``controller_point``, each kind keeps its OWN consult
        counter, so ``fleet:1=recovery-crash`` means "the second recovery
        wave boundary" regardless of how many prune sweeps ran first."""
        key = f"fleet.{kind}"
        i = self._counts.get(key, 0)
        self._counts[key] = i + 1
        ev = self._events.get(("fleet", None, i))
        if ev is not None and ev.kind != kind:
            ev = None
        if ev is None and cluster is not None:
            ckey = (key, cluster)
            j = self._cluster_counts.get(ckey, 0)
            self._cluster_counts[ckey] = j + 1
            ev = self._events.get(("fleet", cluster, j))
            if ev is not None and ev.kind != kind:
                ev = None
        if ev is None:
            return False
        self._fire(ev)
        if kind == "recovery-crash":
            raise InjectedExecCrash(
                "injected fault: fleet startup-recovery resume killed at "
                "a wave boundary"
            )
        return True

    def daemon_solve(self, cluster: Optional[str] = None) -> None:
        """Called at the daemon's per-request solve dispatch boundary;
        ``solver-crash`` raises :class:`InjectedSolverCrash` — the request
        must degrade to the greedy fallback in isolation (other requests,
        other clusters, and the daemon itself, unaffected)."""
        ev = self._next("daemon", cluster)
        if ev is not None and ev.kind == "solver-crash":
            self._fire(ev)
            raise InjectedSolverCrash(
                "injected fault: solver crash inside a served daemon request"
            )


#: Programmatic override (tests) — wins over the env knob when set.
_INSTALLED: Optional[FaultInjector] = None
#: Env-built injector cache keyed by (spec, seed): the wire client and the
#: solver construct lazily but must share one schedule's counters.
_ENV_CACHE: Optional[Tuple[Tuple[str, int], Optional[FaultInjector]]] = None


def install(injector: Optional[FaultInjector]) -> None:
    """Install an injector programmatically (None uninstalls); overrides the
    ``KA_FAULTS_SPEC`` knob until :func:`reset`."""
    global _INSTALLED
    _INSTALLED = injector


def reset() -> None:
    """Forget the installed injector and the env cache: the next
    :func:`active_injector` call starts a fresh schedule (fresh counters).
    The chaos soak calls this between runs."""
    global _INSTALLED, _ENV_CACHE
    _INSTALLED = None
    _ENV_CACHE = None


def active_injector() -> Optional[FaultInjector]:
    """The injector for the current process, or None (the fast path: one
    global read). Env-driven construction follows the knob house rule — a
    malformed ``KA_FAULTS_SPEC`` warns on stderr and injection stays OFF."""
    if _INSTALLED is not None:
        return _INSTALLED
    from ..utils.env import env_float, env_int, env_str

    spec = env_str("KA_FAULTS_SPEC")
    if not spec:
        return None
    seed = env_int("KA_FAULTS_SEED")
    global _ENV_CACHE
    if _ENV_CACHE is not None and _ENV_CACHE[0] == (spec, seed):
        return _ENV_CACHE[1]
    injector: Optional[FaultInjector] = None
    try:
        injector = FaultInjector(
            parse_spec(spec, seed, env_float("KA_FAULTS_RATE"))
        )
    except FaultSpecError as e:
        print(
            f"kafka-assigner: ignoring malformed KA_FAULTS_SPEC ({e}); "
            "fault injection disabled",
            file=sys.stderr,
        )
    _ENV_CACHE = ((spec, seed), injector)
    return injector


def controller_fault(kind: str, cluster: Optional[str] = None) -> bool:
    """The controller's per-kind fault consult (ISSUE 15): returns True
    when the scheduled ``controller`` event of this ``kind`` fired
    (``verdict-flap``/``regress``); ``exec-crash`` raises
    :class:`InjectedExecCrash` instead. No-op False without an active
    injector."""
    inj = active_injector()
    if inj is None:
        return False
    return inj.controller_point(kind, cluster)


def fleet_fault(kind: str, cluster: Optional[str] = None) -> bool:
    """The fleet scheduler's per-kind fault consult (ISSUE 20): returns
    True when the scheduled ``fleet`` event of this ``kind`` fired
    (``lease-expire``/``ledger-torn``); ``recovery-crash`` raises
    :class:`InjectedExecCrash` instead. No-op False without an active
    injector."""
    inj = active_injector()
    if inj is None:
        return False
    return inj.fleet_point(kind, cluster)


def fault_point(scope: str, cluster: Optional[str] = None) -> None:
    """Generic crash-style fault point for non-wire call sites (``solve`` in
    the TPU solver, ``warmup`` in the ingest warm-up thread, ``wave`` at the
    execution engine's wave boundaries). ``cluster`` forwards the daemon
    supervisor's cluster name for ``@cluster``-addressed schedules. No-op
    without an active injector."""
    inj = active_injector()
    if inj is None:
        return
    if scope == "solve":
        inj.solve_attempt()
    elif scope == "warmup":
        inj.warmup_attempt()
    elif scope == "wave":
        inj.wave_boundary()
    elif scope == "resync":
        inj.resync_attempt(cluster)
    elif scope == "daemon":
        inj.daemon_solve(cluster)
    elif scope == "dispatch":
        inj.dispatch_attempt(cluster)
