"""Deterministic fault-injection harness (ISSUE 5 tentpole).

The wire client (``io/zkwire.py``) and the TPU solver consult this package
at well-defined fault points; with no injector active every hook is a single
``None`` check. See :mod:`kafka_assigner_tpu.faults.inject` for the fault
taxonomy, the ``KA_FAULTS_*`` knobs, and the spec grammar.
"""
from .inject import (  # noqa: F401
    FAULT_KINDS,
    FAULT_SCOPES,
    FaultEvent,
    FaultInjector,
    FaultSpecError,
    InjectedSolverCrash,
    active_injector,
    fault_point,
    fleet_fault,
    install,
    parse_spec,
    reset,
)
