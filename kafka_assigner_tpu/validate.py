"""Up-front feasibility validation.

The reference only discovers infeasibility mid-solve, throwing "Partition N
could not be fully assigned!" halfway through printing
(``KafkaAssignmentStrategy.java:183-184``), with a documented caveat that
unequal rack sizes can break it (``:29-30``). These checks run *before*
solving and name the structural cause; the solver's hard error remains the
backstop for anything the necessary conditions don't catch.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Mapping, Sequence, Set


@dataclass
class FeasibilityIssue:
    topic: str
    severity: str  # "error" (provably infeasible) | "warning" (at risk)
    message: str


def validate_topic_feasibility(
    topic: str,
    n_partitions: int,
    replication_factor: int,
    brokers: Set[int],
    rack_assignment: Mapping[int, str],
) -> List[FeasibilityIssue]:
    """Necessary-condition checks for one topic's solve."""
    issues: List[FeasibilityIssue] = []
    n = len(brokers)
    if n == 0 or n_partitions == 0:
        return issues
    rf = replication_factor
    racks: Dict[str, int] = {}
    for b in brokers:
        rack = rack_assignment.get(b)
        racks[str(b) if rack is None else rack] = (
            racks.get(str(b) if rack is None else rack, 0) + 1
        )
    n_racks = len(racks)
    if rf > n_racks:
        issues.append(
            FeasibilityIssue(
                topic, "error",
                f"replication factor {rf} exceeds rack count {n_racks}: each "
                "replica of a partition must land on a distinct rack "
                "(KafkaAssignmentStrategy.java:17-24)",
            )
        )
        return issues
    cap = math.ceil(n_partitions * rf / n)
    # Total placeable replicas respecting rack exclusivity: each rack can take
    # at most min(size * cap, P) replicas.
    placeable = sum(min(size * cap, n_partitions) for size in racks.values())
    if placeable < n_partitions * rf:
        issues.append(
            FeasibilityIssue(
                topic, "error",
                f"rack capacities cannot host P*RF={n_partitions * rf} "
                f"replicas (max placeable {placeable} with per-node cap "
                f"{cap}): racks are too unbalanced "
                "(KafkaAssignmentStrategy.java:29-30)",
            )
        )
    elif rf == n_racks:
        smallest = min(racks.values())
        if smallest * cap < n_partitions:
            issues.append(
                FeasibilityIssue(
                    topic, "error",
                    f"RF equals rack count, so every rack must carry every "
                    f"partition, but the smallest rack ({smallest} brokers x "
                    f"cap {cap}) cannot hold {n_partitions} partitions",
                )
            )
    # Saturation warning: the greedy/auction first-fit is known to strand
    # replicas when capacity slack is near zero.
    slack = n * cap - n_partitions * rf
    if not any(i.severity == "error" for i in issues) and slack < max(1, n // 100):
        issues.append(
            FeasibilityIssue(
                topic, "warning",
                f"capacity slack is only {slack} replica slots; first-fit "
                "placement may fail on skewed current assignments",
            )
        )
    return issues


def validate_cluster_feasibility(
    topic_assignments: Sequence,
    brokers: Set[int],
    rack_assignment: Mapping[int, str],
    desired_replication_factor: int = -1,
) -> List[FeasibilityIssue]:
    """Validate every (topic, current) pair before a reassignment run."""
    from .assigner import infer_topic_rf

    issues: List[FeasibilityIssue] = []
    for topic, current in topic_assignments:
        try:
            rf = infer_topic_rf(topic, current, desired_replication_factor)
        except ValueError as e:
            # Non-uniform replica lists: report as a structural issue instead
            # of aborting the whole validation pass.
            issues.append(FeasibilityIssue(topic, "error", str(e)))
            continue
        if rf <= 0:
            continue
        issues.extend(
            validate_topic_feasibility(
                topic, len(current), rf, brokers, rack_assignment
            )
        )
    return issues
