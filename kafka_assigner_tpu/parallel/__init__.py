from .mesh import build_mesh, scenario_sharding
from .whatif import evaluate_removal_scenarios

__all__ = ["build_mesh", "scenario_sharding", "evaluate_removal_scenarios"]
