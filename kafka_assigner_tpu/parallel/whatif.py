"""Batched what-if sweeps: evaluate many candidate cluster changes at once.

The reference evaluates exactly one scenario per process run (the operator
passes ``--broker_hosts_to_remove`` and eyeballs the resulting JSON). Here a
scenario is a row in a liveness-mask matrix; the whole sweep is one
``vmap``-ed, mesh-sharded solve (BASELINE config 5: 256 candidate broker
removals over a 1k-broker cluster across a v5e-8 slice).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from ..models.problem import (
    batch_bucket,
    encode_cluster,
    encode_topic_group,
)


def _topic_rfs(items, replication_factor):
    """Per-topic RF: the desired override, else inferred from each topic's
    own replica lists (clusters routinely mix RFs) with the assigner's
    uniformity assertion — a topic with non-uniform replica lists raises
    instead of silently adopting an arbitrary partition's RF. Topics with no
    partitions are skipped by callers (rf <= 0 contributes nothing)."""
    from ..assigner import infer_topic_rf

    return [
        infer_topic_rf(topic, cur, replication_factor) for topic, cur in items
    ]


@dataclass
class ScenarioResult:
    """Outcome metrics for one candidate change."""

    removed: Tuple[int, ...]
    moved_replicas: int
    feasible: bool
    max_node_load: int


def evaluate_removal_scenarios(
    topic_assignments: Mapping[str, Mapping[int, Sequence[int]]],
    brokers: Set[int],
    rack_assignment: Mapping[int, str],
    scenarios: Sequence[Sequence[int]],
    replication_factor: int = -1,
    mesh=None,
) -> List[ScenarioResult]:
    """For each candidate broker-removal set, solve the full cluster
    reassignment and report movement/feasibility/load metrics.

    ``mesh``: optional ``jax.sharding.Mesh`` — scenario rows are sharded
    across its ``scenarios`` axis so the sweep spreads over every chip; on a
    single device the same program runs unsharded.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    from ..ops.assignment import whatif_sweep_jit

    all_items = list(topic_assignments.items())
    all_rfs = _topic_rfs(all_items, replication_factor)
    # Topics with no partitions contribute nothing to any scenario.
    items = [it for it, r in zip(all_items, all_rfs) if r > 0 and it[1]]
    topic_rfs = [r for it, r in zip(all_items, all_rfs) if r > 0 and it[1]]
    if not items:
        return []
    rf = max(topic_rfs)
    cluster = encode_cluster(rack_assignment, brokers)
    encs, currents, jhashes, p_reals = encode_topic_group(
        items, rack_assignment, brokers, topic_rfs, cluster=cluster
    )
    rfs = np.zeros(currents.shape[0], dtype=np.int32)
    rfs[: len(topic_rfs)] = topic_rfs

    enc0 = encs[0]
    broker_to_idx = cluster.broker_to_idx
    s_real = len(scenarios)
    s_pad = batch_bucket(s_real)
    alive = np.zeros((s_pad, enc0.n_pad), dtype=bool)
    alive[:, : enc0.n] = True
    for s, removed in enumerate(scenarios):
        for b in removed:
            idx = broker_to_idx.get(int(b))
            if idx is None:
                raise ValueError(f"scenario {s}: unknown broker {b}")
            alive[s, idx] = False

    from .mesh import fetch_global, put_sharded

    if mesh is not None:
        alive_dev = put_sharded(alive, mesh, PartitionSpec("scenarios", None))
    else:
        alive_dev = jnp.asarray(alive)

    moved, infeasible, max_load = map(
        np.array,  # forced copy: the rescue pass below mutates these rows
        fetch_global(
            whatif_sweep_jit(
                jnp.asarray(currents),
                jnp.asarray(enc0.rack_idx),
                jnp.asarray(jhashes),
                jnp.asarray(p_reals),
                alive_dev,
                n=enc0.n,
                rf=rf,
                rfs=jnp.asarray(rfs),
                r_cap=enc0.r_cap,
            )
        ),
    )
    # The sweep runs the fast wave only (an in-graph fallback would execute
    # for every vmapped scenario); a raised flag can mean "fast packing
    # stranded" rather than true infeasibility, so re-run just the flagged
    # scenarios with the full fallback chain — matching what the actual
    # solver would do for that scenario.
    flagged = [s for s in range(s_real) if infeasible[s]]
    if flagged:
        sub = np.zeros((batch_bucket(len(flagged)), enc0.n_pad), dtype=bool)
        for i, s in enumerate(flagged):
            sub[i] = alive[s]
        moved2, infeasible2, max_load2 = jax.device_get(
            whatif_sweep_jit(
                jnp.asarray(currents),
                jnp.asarray(enc0.rack_idx),
                jnp.asarray(jhashes),
                jnp.asarray(p_reals),
                jnp.asarray(sub),
                n=enc0.n,
                rf=rf,
                wave_mode="auto",
                rfs=jnp.asarray(rfs),
                r_cap=enc0.r_cap,
            )
        )
        for i, s in enumerate(flagged):
            moved[s] = moved2[i]
            infeasible[s] = infeasible2[i]
            max_load[s] = max_load2[i]
    return [
        ScenarioResult(
            removed=tuple(sorted(int(b) for b in scenarios[s])),
            moved_replicas=int(moved[s]),
            feasible=not bool(infeasible[s]),
            max_node_load=int(max_load[s]),
        )
        for s in range(s_real)
    ]


def rank_decommission_candidates(
    topic_assignments: Mapping[str, Mapping[int, Sequence[int]]],
    brokers: Set[int],
    rack_assignment: Mapping[int, str],
    candidates: Optional[Sequence[int]] = None,
    replication_factor: int = -1,
    mesh=None,
) -> List[ScenarioResult]:
    """Rank single-broker removals by disruption (feasible first, then fewest
    moved replicas) — the fleet-scale question the reference can only answer
    one process run at a time."""
    cands = sorted(candidates) if candidates is not None else sorted(brokers)
    results = evaluate_removal_scenarios(
        topic_assignments, brokers, rack_assignment,
        [[c] for c in cands], replication_factor, mesh,
    )
    return sorted(
        results, key=lambda r: (not r.feasible, r.moved_replicas, r.removed)
    )
