"""Batched what-if sweeps: evaluate many candidate cluster changes at once.

The reference evaluates exactly one scenario per process run (the operator
passes ``--broker_hosts_to_remove`` and eyeballs the resulting JSON). Here a
scenario is a row in a liveness-mask matrix; the whole sweep is one
``vmap``-ed, mesh-sharded solve (BASELINE config 5: 256 candidate broker
removals over a 1k-broker cluster across a v5e-8 slice).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from ..models.problem import (
    batch_bucket,
    encode_cluster,
    encode_topic_group,
)
from ..obs.metrics import counter_add, gauge_set
from ..obs.trace import span


#: Sweep entry points routed through the persistent program store, same
#: scheme as solvers/tpu.py:_PROGRAM_SPECS (the fan-out programs are cached
#: and warm-started too — a daemon answering interactive what-if queries
#: must not pay a cold compile on its first ranking). Mesh-sharded dispatches
#: bypass the store inside the wrapper (sharding-specific executables).
_SWEEP_SPECS = {
    "whatif_sweep": (
        "whatif_sweep_jit",
        ("n", "rf", "wave_mode", "r_cap"),
        (("b", "p", None), ("n",), ("b",), ("b",), (None, "n")),
    ),
    "whatif_subset_sweep": (
        "whatif_subset_sweep_jit",
        ("n", "rf", "r_cap"),
        ((None, "p", "p", None), ("n",), (None, "p"), (None, "p"),
         (None, "n")),
    ),
    # Consumer-group packing (ISSUE 13): the second workload family rides
    # the same store-backed dispatch — partition rows on the "p" bucket,
    # consumer columns on the "n" bucket, sweep batch on "b".
    "group_pack": (
        "pack_group_jit",
        (),
        (("p",), ("n",), ("p",), ("p",), ("n",)),
    ),
    "group_sweep": (
        "group_pack_sweep_jit",
        (),
        (("p",), ("n",), ("p",), ("p",), ("b", "n"), ("b",)),
    ),
}


def _sweep_program(name: str):
    """Store-backed wrapper for a sweep entry (plain jit when the store layer
    is unavailable — the sweep must not depend on the optimization)."""
    from ..ops import assignment as ops
    from ..solvers.tpu import _warn_once

    attr, statics, axes = _SWEEP_SPECS[name]
    jit_fn = getattr(ops, attr)
    try:
        from ..utils.programstore import BucketContract, wrap_jit

        return wrap_jit(name, jit_fn, statics, BucketContract(axes))
    except Exception as e:
        _warn_once(f"kafka-assigner: program store unavailable ({e})")
        return jit_fn


def _active_dispatch_broker():
    """The daemon's coalescing SolveDispatcher, when the calling thread is
    a daemon request thread running under ``dispatch_scope`` (ISSUE 14).
    None everywhere else — the one-shot CLI, the ``KA_DISPATCH=0`` lock
    path, and library embedders keep their direct dispatch, byte-for-byte.
    Imported lazily: ``parallel/`` must not depend on ``daemon/`` at
    import time."""
    try:
        from ..daemon.dispatch import active_broker
    except Exception:  # pragma: no cover - packaging subset without daemon/
        return None
    return active_broker()


#: Per-request token for chunked giant-sweep jobs (ISSUE 19): memory-
#: budgeted blocks ride the dispatcher queue one at a time but must never
#: pack with each other — two budget-sized blocks concatenated are exactly
#: the slab the chunking exists to avoid — so each request's chunks get a
#: unique statics tag.
_chunk_token = itertools.count(1)


def _submit_coalesced(entry, shared, statics, rows, n_rows, pad, call,
                      cluster=None):
    """Route batch-axis rows through the installed dispatcher, with
    per-job failure isolation: a mid-batch solver crash (another request's
    rows may share the batch) retries THIS job's rows solo ONCE on the
    calling thread; a second failure propagates to the caller's own
    degradation path (greedy fallback / policy handling). ``shared`` +
    ``statics`` fingerprint the compatibility class (the non-batch-axis
    operands every packed job must agree on — content-hashed, so two
    CLUSTERS whose encodings agree pack together); neither is hashed when
    no dispatcher is routing. Returns the sliced output arrays, or None
    when no dispatcher is routing (caller runs its direct path)."""
    import sys

    broker = _active_dispatch_broker()
    if broker is None:
        return None
    from ..daemon.dispatch import batch_key

    key = batch_key(entry, shared, statics)
    try:
        res = broker.submit_rows(
            entry, key, rows, n_rows, pad, call, cluster=cluster
        )
    except Exception as e:
        counter_add("dispatch.solo_fallbacks")
        print(
            f"kafka-assigner: coalesced {entry} batch failed "
            f"({type(e).__name__}: {e}); re-running this request's "
            f"{n_rows} row(s) solo",
            file=sys.stderr,
        )
        total = batch_bucket(n_rows)
        if total > n_rows:
            pad_rows = pad(total - n_rows)
            padded = {
                name: np.concatenate([rows[name], pad_rows[name]], axis=0)
                for name in rows
            }
        else:
            padded = rows
        outs = call(padded)
        return tuple(np.asarray(a)[:n_rows] for a in outs)
    return res


def _topic_rfs(items, replication_factor):
    """Per-topic RF: the desired override, else inferred from each topic's
    own replica lists (clusters routinely mix RFs) with the assigner's
    uniformity assertion — a topic with non-uniform replica lists raises
    instead of silently adopting an arbitrary partition's RF. Topics with no
    partitions are skipped by callers (rf <= 0 contributes nothing)."""
    from ..assigner import infer_topic_rf

    return [
        infer_topic_rf(topic, cur, replication_factor) for topic, cur in items
    ]


@dataclass
class ScenarioResult:
    """Outcome metrics for one candidate change."""

    removed: Tuple[int, ...]
    moved_replicas: int
    feasible: bool
    max_node_load: int


def _topic_stats(currents: np.ndarray, p_reals, rfs, rack_idx, n):
    """Host-side per-topic facts the incremental sweep composes from.

    Returns (clean (B,), loads (B, n), max_load (B,)) where ``clean[t]``
    certifies that topic t's input assignment reproduces itself under ANY
    scenario whose brokers it doesn't host and whose capacity bound covers
    ``max_load[t]``: every real row has exactly rf live entries (no dead/
    unknown brokers, no short rows), no duplicate broker in a row, and no
    rack repeated in a row. For such a topic sticky re-accepts everything
    (per-node kept count <= cap ⇔ all per-slot gates pass), no orphans
    exist, no waves run — placement IS the input, zero movement.
    """
    b, p_pad, w = currents.shape
    rows = np.arange(p_pad)[None, :] < np.asarray(p_reals)[:, None]  # (B,P)
    ent = currents  # (B, P, W) broker index or -1
    pos = ent >= 0
    count = pos.sum(axis=2)  # (B, P)
    full = np.where(rows, count == np.asarray(rfs)[:, None], True).all(axis=1)
    dup = np.zeros((b, p_pad), dtype=bool)
    rackdup = np.zeros((b, p_pad), dtype=bool)
    rk = np.where(pos, np.asarray(rack_idx)[np.maximum(ent, 0)], -1)
    for i in range(w):
        for j in range(i + 1, w):
            both = pos[:, :, i] & pos[:, :, j]
            dup |= both & (ent[:, :, i] == ent[:, :, j])
            rackdup |= both & (rk[:, :, i] == rk[:, :, j])
    clean = (
        full
        & ~np.where(rows, dup, False).any(axis=1)
        & ~np.where(rows, rackdup, False).any(axis=1)
    )
    loads = np.zeros((b, n), dtype=np.int64)
    flat = ent[pos & rows[:, :, None]]
    topic_of = np.broadcast_to(
        np.arange(b)[:, None, None], ent.shape
    )[pos & rows[:, :, None]]
    np.add.at(loads, (topic_of, flat), 1)
    return clean, loads, loads.max(axis=1)


def _rescue_flagged(
    flagged, alive, currents, rack_idx, jhashes, p_reals, rfs, n, rf, r_cap,
    moved, infeasible, max_load,
):
    """Re-run flagged scenarios through the FULL auto-chain sweep and write
    the results back in place.

    The fast-only sweep (dense or incremental) raises its infeasible flag
    for both true infeasibility and fast-leg strandings; this shared rescue
    resolves the difference identically for both paths — matching what the
    actual solver would do for that scenario."""
    import jax
    import jax.numpy as jnp

    whatif_sweep_jit = _sweep_program("whatif_sweep")

    counter_add("whatif.rescued", len(flagged))

    def _rescue_call(rows):
        with span("whatif/rescue", hist="whatif.dispatch_ms"):
            return tuple(
                np.asarray(a) for a in jax.device_get(
                    whatif_sweep_jit(
                        jnp.asarray(currents), jnp.asarray(rack_idx),
                        jnp.asarray(jhashes), jnp.asarray(p_reals),
                        jnp.asarray(rows["alive"]),
                        n=n, rf=rf, wave_mode="auto", rfs=jnp.asarray(rfs),
                        r_cap=r_cap,
                    )
                )
            )

    def _rescue_pad(k):
        block = np.zeros((k, alive.shape[1]), dtype=bool)
        block[:, :n] = True
        return {"alive": block}

    # Coalesced rescue (ISSUE 19): on a daemon request thread the
    # flagged-subset re-solve becomes a typed row job — concurrent
    # requests' rescue rows over byte-identical encodings pack into one
    # full-chain dispatch instead of serializing behind each other. The
    # "rescue" statics tag keeps these rows out of the fast-only "dense"
    # compatibility class: the full auto-chain sweep is a DIFFERENT
    # compiled program, so packing across the two would be unsound.
    routed = _submit_coalesced(
        "whatif_sweep",
        (currents, rack_idx, jhashes, p_reals, rfs),
        ("rescue", n, rf, r_cap),
        {"alive": np.array([alive[s] for s in flagged])}, len(flagged),
        _rescue_pad, _rescue_call,
    )
    if routed is not None:
        moved2, infeasible2, max_load2 = routed
    else:
        sub = np.zeros(
            (batch_bucket(len(flagged)), alive.shape[1]), dtype=bool
        )
        for i, s in enumerate(flagged):
            sub[i] = alive[s]
        moved2, infeasible2, max_load2 = _rescue_call({"alive": sub})
    for i, s in enumerate(flagged):
        moved[s] = moved2[i]
        infeasible[s] = infeasible2[i]
        max_load[s] = max_load2[i]


def _evaluate_incremental(
    currents, jhashes, p_reals, rfs, cluster, alive, scenarios, s_real,
    rf, r_cap, b_real, mesh=None,
):
    """Incremental sweep: solve only the (scenario, topic) pairs whose
    outcome can differ from the input.

    Placement has no cross-topic dependency, so a scenario's metrics
    decompose per topic; a topic that hosts none of the removed brokers and
    is *clean* under the scenario's capacity bound (``_topic_stats``)
    provably reproduces its input — zero movement, unchanged loads. At
    BASELINE config 5 that is ~87% of all (scenario, topic) work. The full
    sweep remains the oracle: differential-pinned on randomized clusters
    (``tests/test_whatif.py``), and this path declines (returns None) when
    the affected fraction makes it unprofitable.

    Scenarios whose fast-leg pair solve strands re-run through the FULL
    auto-chain sweep, exactly like the non-incremental rescue.
    """
    import jax
    import jax.numpy as jnp

    from ..models.problem import _pad8

    whatif_subset_sweep_jit = _sweep_program("whatif_subset_sweep")

    n = cluster.n
    clean, loads_t, maxload_t = _topic_stats(
        currents[:b_real], p_reals[:b_real], rfs[:b_real], cluster.rack_idx, n
    )
    base_load = loads_t.sum(axis=0)  # (n,)
    pr = np.asarray(p_reals[:b_real], dtype=np.int64)
    rft = np.asarray(rfs[:b_real], dtype=np.int64)
    affected = []  # per scenario: array of affected topic rows
    for s in range(s_real):
        ridx = np.where(~alive[s, :n])[0]
        n_alive = n - len(ridx)
        if n_alive <= 0:
            return None  # degenerate; let the full sweep report it
        caps = -(-(pr * rft) // n_alive)  # per-topic ceil(P*RF/N_alive)
        hosts = (
            loads_t[:, ridx].sum(axis=1) > 0
            if len(ridx)
            else np.zeros(b_real, dtype=bool)
        )
        affected.append(np.where(hosts | ~clean | (maxload_t > caps))[0])
    # 8-granular pad (not power-of-2): the bucket feeds the profitability
    # gate, and a pow2 jump (34 -> 64) would decline sweeps that are
    # profitably ~1/3 affected. Distinct t_pad buckets recompile the subset
    # program; 8-granularity bounds that the same way the partition axis is
    # bounded (models/problem.py:_pad8).
    t_pad = _pad8(max((len(a) for a in affected), default=1), floor=8)
    if 3 * t_pad > b_real:
        return None  # mostly-affected scenarios: the dense program wins

    s_pad = alive.shape[0]
    p_pad, w = currents.shape[1], currents.shape[2]
    sc = np.full((s_pad, t_pad, p_pad, w), -1, dtype=np.int32)
    sj = np.zeros((s_pad, t_pad), dtype=np.int32)
    sp = np.zeros((s_pad, t_pad), dtype=np.int32)
    srf = np.full((s_pad, t_pad), rf, dtype=np.int32)
    for s, tops in enumerate(affected):
        if len(tops):
            sc[s, : len(tops)] = currents[tops]
            sj[s, : len(tops)] = jhashes[tops]
            sp[s, : len(tops)] = p_reals[tops]
            srf[s, : len(tops)] = rfs[tops]
    if mesh is not None:
        # Scenario-axis sharding, exactly like the dense fleet path: each
        # device solves its scenarios' affected topics; host composition is
        # unchanged. (The caller only offers a mesh whose scenario axis
        # divides s_pad.)
        from jax.sharding import PartitionSpec

        from .mesh import fetch_global, put_sharded

        def shard(a, spec):
            return put_sharded(np.asarray(a), mesh, spec)

        s4 = PartitionSpec("scenarios", None, None, None)
        s2 = PartitionSpec("scenarios", None)
        outs = whatif_subset_sweep_jit(
            shard(sc, s4), jnp.asarray(cluster.rack_idx),
            shard(sj, s2), shard(sp, s2), shard(alive, s2),
            n=n, rf=rf, rfs=shard(srf, s2), r_cap=r_cap,
        )
        moved_s, infeas_s, loads_s = map(np.asarray, fetch_global(outs))
    else:
        # The incremental sweep's operands are almost all PER-SCENARIO
        # (subset tensors, jhashes, p counts, per-row RFs, alive masks) —
        # only the rack encoding and the static bucket shapes are shared,
        # so concurrent requests whose buckets agree coalesce into one
        # subset dispatch even ACROSS clusters (ISSUE 14).
        def _subset_rows(rows):
            return tuple(
                np.asarray(a) for a in jax.device_get(
                    whatif_subset_sweep_jit(
                        jnp.asarray(rows["sc"]),
                        jnp.asarray(cluster.rack_idx),
                        jnp.asarray(rows["sj"]), jnp.asarray(rows["sp"]),
                        jnp.asarray(rows["alive"]),
                        n=n, rf=rf, rfs=jnp.asarray(rows["srf"]),
                        r_cap=r_cap,
                    )
                )
            )

        def _subset_pad(k):
            block = np.zeros((k, alive.shape[1]), dtype=bool)
            block[:, :n] = True
            return {
                "sc": np.full((k, t_pad, p_pad, w), -1, dtype=np.int32),
                "sj": np.zeros((k, t_pad), dtype=np.int32),
                "sp": np.zeros((k, t_pad), dtype=np.int32),
                "srf": np.full((k, t_pad), rf, dtype=np.int32),
                "alive": block,
            }

        routed = _submit_coalesced(
            "whatif_subset_sweep",
            (cluster.rack_idx,),
            ("subset", n, rf, r_cap, t_pad, p_pad, w, alive.shape[1]),
            {"sc": sc[:s_real], "sj": sj[:s_real], "sp": sp[:s_real],
             "srf": srf[:s_real], "alive": np.array(alive[:s_real])},
            s_real, _subset_pad, _subset_rows,
        )
        if routed is not None:
            moved_s, infeas_s, loads_s = routed
        else:
            moved_s, infeas_s, loads_s = _subset_rows(
                {"sc": sc, "sj": sj, "sp": sp, "srf": srf, "alive": alive}
            )
    moved = np.zeros(s_real, dtype=np.int64)
    infeasible = np.zeros(s_real, dtype=bool)
    load_vec = np.repeat(base_load[None, :], s_real, axis=0)
    for s, tops in enumerate(affected):
        moved[s] = int(moved_s[s])
        infeasible[s] = bool(infeas_s[s])
        load_vec[s] += loads_s[s][:n] - loads_t[tops].sum(axis=0)
    max_load = load_vec.max(axis=1) if n else np.zeros(s_real, dtype=np.int64)

    flagged = [s for s in range(s_real) if infeasible[s]]
    if flagged:
        _rescue_flagged(
            flagged, alive, currents, cluster.rack_idx, jhashes, p_reals,
            rfs, n, rf, r_cap, moved, infeasible, max_load,
        )
    return [
        ScenarioResult(
            removed=tuple(sorted(int(b) for b in scenarios[s])),
            moved_replicas=int(moved[s]),
            feasible=not bool(infeasible[s]),
            max_node_load=int(max_load[s]),
        )
        for s in range(s_real)
    ]


def evaluate_removal_scenarios(
    topic_assignments: Mapping[str, Mapping[int, Sequence[int]]],
    brokers: Set[int],
    rack_assignment: Mapping[int, str],
    scenarios: Sequence[Sequence[int]],
    replication_factor: int = -1,
    mesh=None,
) -> List[ScenarioResult]:
    """For each candidate broker-removal set, solve the full cluster
    reassignment and report movement/feasibility/load metrics.

    ``mesh``: optional ``jax.sharding.Mesh`` — scenario rows are sharded
    across its ``scenarios`` axis so the sweep spreads over every chip; on a
    single device the same program runs unsharded.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    whatif_sweep_jit = _sweep_program("whatif_sweep")

    if mesh is not None and _active_dispatch_broker() is not None:
        # Daemon request thread under the coalescing dispatcher
        # (ISSUE 14): run unsharded. Mesh-sharded dispatches bypass the
        # persistent program store (sharding-specific executables) and
        # cannot pack rows across requests — on the serving plane the
        # parallelism axis is request concurrency through the bucketed,
        # store-warm programs, not intra-request sharding. The one-shot
        # CLI (no dispatcher) keeps its auto-mesh; sharded == unsharded is
        # test-pinned either way.
        mesh = None

    all_items = list(topic_assignments.items())
    all_rfs = _topic_rfs(all_items, replication_factor)
    # Topics with no partitions contribute nothing to any scenario.
    items = [it for it, r in zip(all_items, all_rfs) if r > 0 and it[1]]
    topic_rfs = [r for it, r in zip(all_items, all_rfs) if r > 0 and it[1]]
    if not items:
        return []
    rf = max(topic_rfs)
    cluster = encode_cluster(rack_assignment, brokers)
    encs, currents, jhashes, p_reals = encode_topic_group(
        items, rack_assignment, brokers, topic_rfs, cluster=cluster
    )
    rfs = np.zeros(currents.shape[0], dtype=np.int32)
    rfs[: len(topic_rfs)] = topic_rfs

    enc0 = encs[0]
    broker_to_idx = cluster.broker_to_idx
    s_real = len(scenarios)
    s_pad = batch_bucket(s_real)
    if mesh is not None:
        # The sharded sweep splits the scenario axis across the mesh, so the
        # padded batch must tile it (a 4-scenario bucket on an 8-way mesh is
        # otherwise a hard jax error). Padding rows are all-alive no-op
        # solves; results past s_real are discarded.
        m = mesh.shape.get("scenarios", 1)
        s_pad = ((s_pad + m - 1) // m) * m
    alive = np.zeros((s_pad, enc0.n_pad), dtype=bool)
    alive[:, : enc0.n] = True
    for s, removed in enumerate(scenarios):
        for b in removed:
            idx = broker_to_idx.get(int(b))
            if idx is None:
                raise ValueError(f"scenario {s}: unknown broker {b}")
            alive[s, idx] = False

    from ..utils.env import env_bool, env_int

    # Fan-out telemetry: scenario count (the sweep's work unit) and the
    # padded batch width actually dispatched (the fan-out the device sees).
    counter_add("whatif.scenarios", s_real)
    gauge_set("whatif.fanout", int(s_pad))

    if env_bool("KA_WHATIF_INCREMENTAL"):
        # With a mesh, offer it to the incremental path only when its
        # scenario axis divides the padded batch (same constraint the dense
        # sharded path has); otherwise run the incremental sweep unsharded —
        # at ~1/8th the device work it usually still wins.
        inc_mesh = mesh
        if mesh is not None and s_pad % mesh.shape.get("scenarios", 1) != 0:
            inc_mesh = None
        with span("whatif/incremental"):
            res = _evaluate_incremental(
                currents, jhashes, p_reals, rfs, cluster, alive, scenarios,
                s_real, rf, enc0.r_cap, len(items), mesh=inc_mesh,
            )
        if res is not None:
            counter_add("whatif.incremental_sweeps")
            return res

    from .mesh import fetch_global, put_sharded

    # Scenario-axis memory chunking: the vmapped sweep materializes
    # per-scenario solver state (S, B, P_pad, RF)-shaped — at the giant
    # 200k-partition topic a 256-scenario sweep would be multi-GB. Chunk S
    # so one dispatch's state stays under ~KA_WHATIF_MEMBUDGET int32
    # elements (default 2^28 = 1 GiB of int32); each chunk reuses one
    # compiled program (chunks share the padded shape).
    per_scenario = max(
        1, currents.shape[0] * currents.shape[1] * max(rf, 1)
    )
    budget = env_int("KA_WHATIF_MEMBUDGET")
    s_chunk = max(1, budget // per_scenario)
    if mesh is not None:
        m = mesh.shape.get("scenarios", 1)
        s_chunk = max(m, (s_chunk // m) * m)  # keep chunks mesh-tileable

    def sweep_block(alive_block):
        with span("whatif/dispatch", hist="whatif.dispatch_ms"):
            if mesh is not None:
                alive_dev = put_sharded(
                    alive_block, mesh, PartitionSpec("scenarios", None)
                )
            else:
                alive_dev = jnp.asarray(alive_block)
            return map(
                np.array,  # forced copy: the rescue pass below mutates rows
                fetch_global(
                    whatif_sweep_jit(
                        jnp.asarray(currents),
                        jnp.asarray(enc0.rack_idx),
                        jnp.asarray(jhashes),
                        jnp.asarray(p_reals),
                        alive_dev,
                        n=enc0.n,
                        rf=rf,
                        rfs=jnp.asarray(rfs),
                        r_cap=enc0.r_cap,
                    )
                ),
            )

    def _dense_rows(rows):
        with span("whatif/dispatch", hist="whatif.dispatch_ms"):
            return tuple(
                np.array(a) for a in jax.device_get(
                    whatif_sweep_jit(
                        jnp.asarray(currents),
                        jnp.asarray(enc0.rack_idx),
                        jnp.asarray(jhashes),
                        jnp.asarray(p_reals),
                        jnp.asarray(rows["alive"]),
                        n=enc0.n,
                        rf=rf,
                        rfs=jnp.asarray(rfs),
                        r_cap=enc0.r_cap,
                    )
                )
            )

    def _dense_pad(k):
        block = np.zeros((k, enc0.n_pad), dtype=bool)
        block[:, :enc0.n] = True
        return {"alive": block}

    routed = None
    if mesh is None and s_pad <= s_chunk:
        # The coalescing route (ISSUE 14): only the scenario masks are
        # per-request; the topic tensors and statics are the compatibility
        # class, so concurrent rankings over byte-identical encodings —
        # same cluster, or different clusters whose caches agree — pack
        # into one dispatch on the same bucketed batch programs the store
        # already holds.
        routed = _submit_coalesced(
            "whatif_sweep",
            (currents, enc0.rack_idx, jhashes, p_reals, rfs),
            ("dense", enc0.n, rf, enc0.r_cap),
            {"alive": np.array(alive[:s_real])}, s_real,
            _dense_pad, _dense_rows,
        )
    if routed is not None:
        moved, infeasible, max_load = routed
    elif s_pad <= s_chunk:
        moved, infeasible, max_load = sweep_block(alive)
    else:
        # Fixed-size blocks (last one padded all-alive) so every dispatch
        # hits the same compiled program. On a daemon request thread each
        # block becomes a typed dispatcher job (ISSUE 19): between blocks
        # the dispatcher serves other queued groups, so a giant sweep no
        # longer monopolizes the device against a storm of small requests.
        # A per-request token in the statics keeps chunk jobs from packing
        # with each other — two memory-budgeted blocks concatenated would
        # be exactly the slab the chunking exists to avoid — and the
        # power-of-two floor keeps every block on a bucket the program
        # store already holds (zero dispatcher padding, zero new keys).
        route_chunks = mesh is None and _active_dispatch_broker() is not None
        token = 0
        if route_chunks:
            s_chunk = 1 << (s_chunk.bit_length() - 1)
            token = next(_chunk_token)
        blocks = []
        for lo in range(0, s_pad, s_chunk):
            block = np.ones((s_chunk, alive.shape[1]), dtype=bool)
            block[:, enc0.n:] = False
            chunk_rows = alive[lo:lo + s_chunk]
            block[: len(chunk_rows)] = chunk_rows
            chunk_routed = _submit_coalesced(
                "whatif_sweep",
                (currents, enc0.rack_idx, jhashes, p_reals, rfs),
                ("chunk", enc0.n, rf, enc0.r_cap, token, lo),
                {"alive": block}, s_chunk,
                _dense_pad, _dense_rows,
            ) if route_chunks else None
            blocks.append(
                tuple(chunk_routed) if chunk_routed is not None
                else tuple(sweep_block(block))
            )
        moved, infeasible, max_load = (
            np.concatenate([b[i] for b in blocks])[:s_pad]
            for i in range(3)
        )
    # The sweep runs the fast wave only (an in-graph fallback would execute
    # for every vmapped scenario); a raised flag can mean "fast packing
    # stranded" rather than true infeasibility — the shared rescue re-runs
    # just the flagged scenarios with the full fallback chain.
    flagged = [s for s in range(s_real) if infeasible[s]]
    if flagged:
        _rescue_flagged(
            flagged, alive, currents, enc0.rack_idx, jhashes, p_reals, rfs,
            enc0.n, rf, enc0.r_cap, moved, infeasible, max_load,
        )
    return [
        ScenarioResult(
            removed=tuple(sorted(int(b) for b in scenarios[s])),
            moved_replicas=int(moved[s]),
            feasible=not bool(infeasible[s]),
            max_node_load=int(max_load[s]),
        )
        for s in range(s_real)
    ]


def pack_group_on_device(
    weights: np.ndarray,
    capacities: np.ndarray,
    current: np.ndarray,
    proc_order: np.ndarray,
    alive: np.ndarray,
    p_real: int,
):
    """One group's packing solve through the store-backed dispatch
    (``ops/assignment.py:pack_group``). Returns host arrays
    ``(assigned, load, moved, overflowed, infeasible)`` — the same tuple
    the host oracle (``solvers/greedypack.py``) computes, cell-for-cell
    (the parity pin). The ``solve`` fault scope fires here, exactly like
    the placement solver's dispatch, so the chaos matrix can crash this
    family's device solve deterministically."""
    import jax
    import jax.numpy as jnp

    from ..faults.inject import fault_point

    pack_group_jit = _sweep_program("group_pack")

    fault_point("solve")
    counter_add("groups.dispatches")
    with span("groups/dispatch", hist="whatif.dispatch_ms"):
        return jax.device_get(
            pack_group_jit(
                jnp.asarray(weights), jnp.asarray(capacities),
                jnp.asarray(current), jnp.asarray(proc_order),
                jnp.asarray(alive), jnp.int32(p_real),
            )
        )


def evaluate_group_candidates(
    weights: np.ndarray,
    capacities: np.ndarray,
    current: np.ndarray,
    proc_order: np.ndarray,
    alive_masks: np.ndarray,   # (S_real, C_pad) bool
    scale_pcts,                # (S_real,) int
    p_real: int,
):
    """The autoscale sweep's device half: ALL candidate (consumer count ×
    lag scenario) rows in ONE batched dispatch — the batch axis pads to
    the power-of-two bucket (inert all-dead, scale-100 rows) so the
    program store serves every sweep size from a handful of programs, and
    per-candidate recompiles are structurally impossible (the acceptance
    bar the compile counters pin). Returns per-candidate host arrays
    ``(moved (S,), overflowed (S,), infeasible (S,), load (S, C_pad))``
    trimmed to the real candidates."""
    import jax
    import jax.numpy as jnp

    from ..faults.inject import fault_point

    group_sweep_jit = _sweep_program("group_sweep")

    s_real = len(alive_masks)
    s_pad = batch_bucket(s_real)

    counter_add("groups.candidates", s_real)
    fault_point("solve")

    def _sweep_rows(rows):
        counter_add("groups.dispatches")
        gauge_set("groups.fanout", int(len(rows["alive"])))
        with span("groups/dispatch", hist="whatif.dispatch_ms"):
            moved, overflowed, infeasible, load = jax.device_get(
                group_sweep_jit(
                    jnp.asarray(weights), jnp.asarray(capacities),
                    jnp.asarray(current), jnp.asarray(proc_order),
                    jnp.asarray(rows["alive"]), jnp.asarray(rows["scales"]),
                    jnp.int32(p_real),
                )
            )
        return (
            np.asarray(moved), np.asarray(overflowed),
            np.asarray(infeasible), np.asarray(load),
        )

    def _sweep_pad(k):
        return {
            "alive": np.zeros((k, alive_masks.shape[1]), dtype=bool),
            "scales": np.full(k, 100, dtype=np.int32),
        }

    # Candidate rows coalesce across concurrent requests whose group
    # tensors agree (ISSUE 14) — the padded batch stays on the power-of-two
    # bucket either way, so the program store serves both routes from the
    # same handful of programs.
    routed = _submit_coalesced(
        "group_sweep",
        (weights, capacities, current, proc_order),
        ("group", int(p_real), int(alive_masks.shape[1])),
        {"alive": np.asarray(alive_masks, dtype=bool),
         "scales": np.asarray(scale_pcts, dtype=np.int32)},
        s_real, _sweep_pad, _sweep_rows,
    )
    if routed is not None:
        return routed
    alive = np.zeros((s_pad, alive_masks.shape[1]), dtype=bool)
    alive[:s_real] = alive_masks
    scales = np.full(s_pad, 100, dtype=np.int32)
    scales[:s_real] = np.asarray(scale_pcts, dtype=np.int32)
    moved, overflowed, infeasible, load = _sweep_rows(
        {"alive": alive, "scales": scales}
    )
    return (
        moved[:s_real], overflowed[:s_real],
        infeasible[:s_real], load[:s_real],
    )


def rank_decommission_candidates(
    topic_assignments: Mapping[str, Mapping[int, Sequence[int]]],
    brokers: Set[int],
    rack_assignment: Mapping[int, str],
    candidates: Optional[Sequence[int]] = None,
    replication_factor: int = -1,
    mesh=None,
) -> List[ScenarioResult]:
    """Rank single-broker removals by disruption (feasible first, then fewest
    moved replicas) — the fleet-scale question the reference can only answer
    one process run at a time."""
    cands = sorted(candidates) if candidates is not None else sorted(brokers)
    results = evaluate_removal_scenarios(
        topic_assignments, brokers, rack_assignment,
        [[c] for c in cands], replication_factor, mesh,
    )
    return sorted(
        results, key=lambda r: (not r.feasible, r.moved_replicas, r.removed)
    )
