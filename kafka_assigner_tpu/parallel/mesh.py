"""Device-mesh construction and sharding specs.

The reference is a single JVM thread with no parallel execution of any kind
(SURVEY.md §2 parallelism table). The tpu-native framework scales on two axes:

- ``scenarios`` — the data-parallel axis: independent what-if solves
  (candidate broker removals, RF changes) spread across chips; collectives
  ride ICI within a slice.
- ``part`` — the long-axis analogue of sequence parallelism: the partition
  dimension of the (P × N) eligibility/cost tensors is sharded so one giant
  topic's solve fits and scales; XLA inserts the all-gathers/psums the
  blockwise reductions need (the role ring-attention's collectives play for
  sequence length, SURVEY.md §5).

Multi-host (DCN) runs initialize ``jax.distributed`` first
(:func:`initialize_distributed`) and then build the same mesh over the global
device list.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def build_mesh(
    n_scenarios_axis: Optional[int] = None,
    n_part_axis: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(scenarios, part)`` mesh over the available devices.

    Defaults to all devices on the scenario (data-parallel) axis — the right
    layout for what-if fleets on a single slice. Pass ``n_part_axis > 1`` to
    carve devices for partition-axis sharding of very large single topics.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_scenarios_axis is None:
        n_scenarios_axis = len(devs) // n_part_axis
    if n_scenarios_axis * n_part_axis != len(devs):
        raise ValueError(
            f"mesh {n_scenarios_axis}x{n_part_axis} != {len(devs)} devices"
        )
    grid = np.array(devs).reshape(n_scenarios_axis, n_part_axis)
    return Mesh(grid, ("scenarios", "part"))


def scenario_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for per-scenario arrays: split the leading axis across the
    ``scenarios`` mesh axis, replicate everything else."""
    return NamedSharding(mesh, PartitionSpec("scenarios"))


def put_sharded(array, mesh: Mesh, spec: PartitionSpec):
    """Place host data onto a (possibly multi-process) mesh sharding.

    Single-process: a plain ``device_put``. Multi-process (one process per
    host, mesh spanning all hosts): every process holds the full host array,
    so ``make_array_from_callback`` hands each addressable device its slice —
    the standard way to feed a DCN-spanning mesh without a distributed
    filesystem.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(array, sharding)
    return jax.make_array_from_callback(
        array.shape, sharding, lambda idx: array[idx]
    )


def fetch_global(x):
    """Materialize a (possibly cross-process-sharded) array on every host."""
    if jax.process_count() == 1:
        return jax.device_get(x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX (one process per host, DCN between hosts).

    The reference has no multi-process story at all — one JVM, one thread
    (``KafkaAssignmentGenerator.java:301-303``). For fleet-scale what-if
    sweeps across TPU slices, call this once per process before building a
    mesh; XLA then routes intra-slice collectives over ICI and inter-slice
    traffic over DCN. No-op when jax.distributed is already initialized.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise
