"""On-device assignment kernels: vectorized sticky fill, wave-auction orphan
spread, and exact leadership ordering — the TPU-native re-formulation of the
reference's sequential greedy (``KafkaAssignmentStrategy.java:101-302``).

Design notes (tpu-first, not a translation):

- **Sticky fill** (reference: round-robin iterators over TreeMaps, ``:101-131``)
  becomes a static loop over replica slots; within a slot every partition's
  re-acceptance test is evaluated in parallel, and per-node capacity
  arbitration uses a sort-based *rank among same-node requests* — partitions
  in ascending order win first, exactly the TreeMap iteration tie-break.
- **Orphan spread** (reference: per-partition first-fit scans, ``:162-186``)
  becomes a *wave auction* under ``lax.while_loop``: every deficient partition
  bids for its best (lowest topic-rotated position, ``:188-200``) eligible
  node simultaneously; per-node winners are the lowest partition rows within
  remaining capacity; losers rebid next wave. Node loads grow monotonically,
  so each wave the globally lowest-row bid always lands → guaranteed progress,
  and a partition with a deficit and no eligible node is *provably* infeasible
  (eligibility only shrinks), matching the reference's hard failure ``:183-184``.
- **Leadership ordering** (reference: least-seen counter scan with first-
  minimum-in-rotated-order tie-break, ``:202-302``) is replicated *bit-for-bit*:
  "first strict minimum in rotated scan order" ≡ argmin of the lexicographic
  key ``count * m + rotated_pos`` over the remaining candidates (m = number of
  remaining candidates, rotation start = abs(hash) % m, ``:263-278``). The
  cross-partition counter dependency is carried through ``lax.scan``.

All shapes are static (padded buckets); all control flow is ``lax`` — nothing
here falls back to the host inside ``jit``.
"""
from __future__ import annotations

import sys
from types import MappingProxyType
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.env import env_int, knob_default

# Plain int (not a jax array): module import must not initialize a backend.
BIG = 0x3FFFFFFF


class AssignState(NamedTuple):
    """Carried solver state (functional equivalent of the reference's mutable
    Node/Rack objects, ``KafkaAssignmentStrategy.java:307-355``)."""

    acc_nodes: jnp.ndarray   # (P, RF) accepted broker index per slot, -1 empty
    acc_count: jnp.ndarray   # (P,)   number accepted per partition
    node_load: jnp.ndarray   # (N+1,) replicas per node (+1 scratch row)
    deficit: jnp.ndarray     # (P,)   replicas still to place
    infeasible: jnp.ndarray  # ()     bool: some partition cannot be completed


def default_alive(rack_idx: jnp.ndarray, n: int) -> jnp.ndarray:
    """(N_pad,) liveness mask for the no-scenario case: the first n real
    nodes are alive, padding rows are not."""
    return jnp.arange(rack_idx.shape[0], dtype=jnp.int32) < n


#: Above this (P_pad * N_pad) product the dense wave leg is demoted to last
#: resort in multi-leg chains (see spread_orphans): 2^27 elements ~ 128M —
#: an order of magnitude above any per-topic mask the 2000-topic headline
#: builds (104 x 5120 ~ 0.5M), an order below the giant-topic shape where
#: the dense wave measured 355 s warm (1e9-element masks per wave).
DENSE_MASK_BUDGET = knob_default("KA_DENSE_MASK_BUDGET")

#: Per-wave drain divisor for the quota-balance leg (see _wave_body): each
#: NODE offers ceil(headroom / QUOTA_WAVE_TARGET) slots per wave and each
#: rack receives demand proportional to its summed allowance, so nodes stay
#: evenly filled within racks and racks drain in parallel at rates
#: proportional to their headroom — rack-level fill stays even (the
#: property that lets the balance family solve exactly-saturated instances)
#: while the wave count collapses from O(orphans / racks) to
#: ~O(log(cap) / log(T/(T-1))) ≈ 25 at the giant replace-100 shape (T=4).
#: Env-overridable for measurement (KA_QUOTA_WAVE_TARGET, trace-time read
#: like dense_mask_budget).
QUOTA_WAVE_TARGET = knob_default("KA_QUOTA_WAVE_TARGET")


def quota_wave_target() -> int:
    # kalint: disable=KA016 -- deliberate trace-time read (chain _fresh_solve -> ... -> _wave_body): the persistent program store keys executables on trace-time knob values so a mid-process flip re-keys, and the in-process jit cache contract (clear_caches) is documented at dense_mask_budget
    return env_int("KA_QUOTA_WAVE_TARGET")


def quota_endgame_headroom() -> int:
    # kalint: disable=KA016 -- deliberate trace-time read (chain _fresh_solve -> ... -> _hybrid_quota_body): program-store keys include trace-time knob values (see dense_mask_budget for the jit-cache contract)
    return env_int("KA_QUOTA_ENDGAME")

#: Endgame handoff for the quota-balance leg: once every rack's headroom is
#: at or below this, the hybrid body switches (lax.cond on the traced
#: headroom — monotone, so the switch is one-way) from proportional-quota
#: drain to the node-per-wave balance wave. Eager-mode wave traces show the
#: proportional drain is even through the bulk but can paint the last few
#: slots into a rack-exclusivity corner that the cautious node-per-wave
#: endgame (empirically corner-free on the saturated instances) avoids; the
#: tail it hands over is <= r_cap * QUOTA_ENDGAME_HEADROOM slots, so the
#: node-per-wave waves it costs are bounded and small. Env-overridable for
#: measurement (KA_QUOTA_ENDGAME, trace-time read like dense_mask_budget).
QUOTA_ENDGAME_HEADROOM = knob_default("KA_QUOTA_ENDGAME")


def dense_mask_budget() -> int:
    """The giant-shape gate, env-overridable (``KA_DENSE_MASK_BUDGET``) so
    tests can exercise the budget-flipped wave machinery on small instances
    (the ``KA_WHATIF_MEMBUDGET`` treatment, VERDICT r4 item 6).

    Read at TRACE time: the value is baked into compiled programs, and the
    jit cache keys on shapes/statics only — a mid-process change requires
    ``jax.clear_caches()`` to take effect (tests do; production sets it at
    process start or never).
    """
    # kalint: disable=KA016 -- deliberate trace-time read (chain _fresh_solve -> ... -> spread_orphans): the freeze is the documented contract above, and the persistent program store re-keys on trace-time knob values
    return env_int("KA_DENSE_MASK_BUDGET")

# Below this partition-bucket size the (P, P) same-key-before-me count beats a
# stable argsort in _requests_rank (CPU-XLA microbench, round 1: ~3x at P=128,
# crossover between 256 and 512; a 256x256 bool matrix is 64KB — L2-resident —
# while argsort pays fixed sort overhead per call). Revisit if bucket sizes or
# backends change; both paths compute the identical quantity.
RANK_QUADRATIC_MAX_P = 256


def _requests_rank(pick: jnp.ndarray, valid: jnp.ndarray, sentinel: int) -> jnp.ndarray:
    """Rank of each valid request among requests for the same node, in
    ascending partition-row order — the vectorized stand-in for 'TreeMap
    iteration order decides who hits the capacity gate first'.

    Rank = count of earlier rows with the same key. For the common partition
    buckets a (P, P) same-key-before-me count is several times cheaper than a
    stable argsort (this runs once per sticky slot and once per wave); the
    argsort path covers giant single-topic buckets where O(P^2) would blow
    up. Both compute the identical quantity.
    """
    p = pick.shape[0]
    keys = jnp.where(valid, pick, sentinel)
    if p <= RANK_QUADRATIC_MAX_P:
        rows = jnp.arange(p, dtype=jnp.int32)
        same_before = (keys[None, :] == keys[:, None]) & (
            rows[None, :] < rows[:, None]
        )
        return jnp.sum(same_before, axis=1, dtype=jnp.int32)
    order = jnp.argsort(keys, stable=True)
    sorted_keys = keys[order]
    first = jnp.searchsorted(sorted_keys, sorted_keys, side="left")
    rank_sorted = jnp.arange(p, dtype=jnp.int32) - first.astype(jnp.int32)
    return jnp.zeros(p, dtype=jnp.int32).at[order].set(rank_sorted)


def _accept_batch(
    state: AssignState, cand: jnp.ndarray, accept: jnp.ndarray
) -> AssignState:
    """Record one accepted replica per accepting partition (functional
    ``Node.accept`` + ``Rack.accept``, ``KafkaAssignmentStrategy.java:326-331``)."""
    p, rf = state.acc_nodes.shape
    n_scratch = state.node_load.shape[0] - 1
    slot_onehot = jnp.arange(rf, dtype=jnp.int32)[None, :] == state.acc_count[:, None]
    write = slot_onehot & accept[:, None]
    acc_nodes = jnp.where(write, cand[:, None], state.acc_nodes)
    acc_count = state.acc_count + accept.astype(jnp.int32)
    node_load = state.node_load.at[jnp.where(accept, cand, n_scratch)].add(1)
    deficit = state.deficit - accept.astype(jnp.int32)
    return state._replace(
        acc_nodes=acc_nodes, acc_count=acc_count, node_load=node_load, deficit=deficit
    )


def _acc_racks(state: AssignState, rack_idx: jnp.ndarray) -> jnp.ndarray:
    """(P, RF) rack id of each accepted replica, -1 for empty slots."""
    return jnp.where(
        state.acc_nodes >= 0, rack_idx[jnp.maximum(state.acc_nodes, 0)], -1
    )


def _candidate_ok(
    state: AssignState,
    cand: jnp.ndarray,
    rack_idx: jnp.ndarray,
    rf,  # static int or traced per-topic scalar
    alive: jnp.ndarray,
) -> jnp.ndarray:
    """Per-partition acceptability of candidate nodes, sans capacity:
    node exists and is alive in this scenario, not already holding the
    partition, rack not already used
    (``Node.canAccept`` ∧ ``Rack.canAccept``, ``:320-324, 346-348``)."""
    safe = jnp.maximum(cand, 0)
    exists = (cand >= 0) & alive[safe]
    dup_node = jnp.any(state.acc_nodes == cand[:, None], axis=1)
    cand_rack = rack_idx[safe]
    dup_rack = jnp.any(_acc_racks(state, rack_idx) == cand_rack[:, None], axis=1)
    under_rf = state.acc_count < rf
    return exists & ~dup_node & ~dup_rack & under_rf


def sticky_fill(
    current: jnp.ndarray,   # (P, L) broker index or -1
    rack_idx: jnp.ndarray,  # (N_pad,)
    rf: int,
    cap: jnp.ndarray,       # scalar int32
    n: int,                 # real node count (scratch row = n)
    p_real: jnp.ndarray | None = None,  # real partition count; padded rows get no deficit
    alive: jnp.ndarray | None = None,   # (N_pad,) scenario liveness; default: first n
    rf_actual: jnp.ndarray | None = None,  # traced per-topic RF <= rf (mixed-RF sweeps)
    width: int | None = None,  # static slot width > rf = reference-compat
                               # unbounded sticky retention (RF decrease)
) -> AssignState:
    """Vectorized sticky fill (``fillNodesFromAssignment``, ``:101-131``).

    Slot-by-slot (the round-robin pass order: slot 0 of every partition is
    offered before any slot 1, so leader replicas win sticky capacity before
    followers); within a slot, ascending partition rows win capacity ties.

    Default divergence from the reference, on purpose: a partition never
    keeps more than ``rf`` replicas. The reference's sticky fill has no
    per-partition limit (``:320-324``), which on an RF decrease emits
    non-uniform replica lists (see greedy.py header); by default the TPU
    solver clamps to the requested RF. Passing ``width`` (>= max(rf, L))
    opts into the reference's exact unbounded retention
    (``KA_RF_DECREASE_COMPAT=1``): acceptance is bounded only by the slot
    array — physically <= L current entries per partition — reproducing the
    reference byte-for-byte on RF-decrease inputs too.
    """
    p, hist_width = current.shape
    n_pad = rack_idx.shape[0]
    if p_real is None:
        p_real = jnp.int32(p)
    if alive is None:
        alive = jnp.arange(n_pad, dtype=jnp.int32) < n
    if rf_actual is None:
        rf_actual = jnp.int32(rf)
    w = rf if width is None else width
    # Retention bound: requested RF (default clamp) or the slot width, which
    # never binds (compat: the reference has no per-partition limit at all).
    retain = rf_actual if width is None else jnp.int32(w)
    deficit = jnp.where(
        jnp.arange(p, dtype=jnp.int32) < p_real, rf_actual, 0
    ).astype(jnp.int32)
    state = AssignState(
        acc_nodes=jnp.full((p, w), -1, dtype=jnp.int32),
        acc_count=jnp.zeros(p, dtype=jnp.int32),
        node_load=jnp.zeros(n + 1, dtype=jnp.int32),
        deficit=deficit,
        infeasible=jnp.asarray(False),
    )
    for s in range(hist_width):  # static unroll: historical RF, small
        cand = current[:, s]
        ok = _candidate_ok(state, cand, rack_idx, retain, alive)
        rank = _requests_rank(cand, ok, n)
        load = state.node_load[jnp.maximum(cand, 0)]
        accept = ok & (load + rank < cap)
        state = _accept_batch(state, cand, accept)
    return state


def _wave_body_dense(
    rack_idx: jnp.ndarray,
    pos_fn,  # () -> (N_pad,) rotated positions; evaluated INSIDE the body so
             # the O(N) rank/where ops only run when a dense wave actually
             # iterates (rare — it's the fallback leg), not once per topic
    cap: jnp.ndarray,
    n: int,
    alive: jnp.ndarray,
    r_cap: int,
):
    """Dense-eligibility wave: every deficient partition bids for its best
    eligible node over an explicit (P × N) mask. O(P·N) per wave — the
    fallback when the fast rack-factored wave strands (its different packing
    can dead-end near saturation where this one does not, and vice versa the
    dense one is too slow to be the common path at 5k-broker scale)."""

    def body(state: AssignState) -> AssignState:
        pos = pos_fn()
        p = state.acc_nodes.shape[0]
        rows = jnp.arange(p, dtype=jnp.int32)[:, None]

        assigned = (
            jnp.zeros((p, n + 1), dtype=bool)
            .at[jnp.broadcast_to(rows, state.acc_nodes.shape),
                jnp.where(state.acc_nodes >= 0, state.acc_nodes, n)]
            .set(True)[:, :n]
        )
        acc_racks = _acc_racks(state, rack_idx)
        rack_used = (
            jnp.zeros((p, r_cap + 1), dtype=bool)
            .at[jnp.broadcast_to(rows, acc_racks.shape),
                jnp.where(acc_racks >= 0, acc_racks, r_cap)]
            .set(True)
        )
        rack_blocked = jnp.take(rack_used, rack_idx[:n], axis=1)
        under_cap = ((state.node_load[:n] < cap) & alive[:n])[None, :]
        eligible = ~assigned & ~rack_blocked & under_cap & (state.deficit > 0)[:, None]

        score = jnp.where(eligible, pos[None, :n], BIG)
        pick = jnp.argmin(score, axis=1).astype(jnp.int32)
        has_choice = jnp.any(eligible, axis=1)
        valid = (state.deficit > 0) & has_choice
        infeasible = state.infeasible | jnp.any((state.deficit > 0) & ~has_choice)

        rank = _requests_rank(pick, valid, n)
        load = state.node_load[jnp.maximum(pick, 0)]
        accept = valid & (load + rank < cap)
        state = _accept_batch(state, pick, accept)
        return state._replace(infeasible=infeasible)

    return body


class Segments(NamedTuple):
    """Cluster-wide handout order for the fast/balance waves: live nodes
    sorted by (rack, live-rank), with per-rack [start, end) segment bounds.

    Depends only on (rack_idx, alive) — NOT on the topic or the wave — so it
    is computed once per batched solve (or per what-if scenario) and shared
    by every topic's wave loop. A topic's rotated probing order within a rack
    is a *rotation* of that rack's segment (see ``_wave_body``), so no
    per-topic or per-wave sort exists anywhere: the round-2 CPU profile
    showed per-wave argsort + a 2*n_pad-wide top_k dominating the whole
    solve (~1ms per wave at 5k brokers); this machinery replaces them with a
    per-wave O(N) cumsum and O(r_cap) bookkeeping.
    """

    order: jnp.ndarray        # (n,) node indices, live sorted by (rack, rank)
    sorted_key: jnp.ndarray   # (n,) rack * n_pad + live-rank (BIG for dead)
    sorted_rank: jnp.ndarray  # (n,) live-rank in sorted order (BIG for dead)
    seg_start: jnp.ndarray    # (r_cap,)
    seg_end: jnp.ndarray      # (r_cap,)


def cluster_segments(
    rack_idx: jnp.ndarray, n: int, alive: jnp.ndarray, r_cap: int
) -> Segments:
    """Build :class:`Segments` for one (cluster, liveness) pair."""
    n_pad = rack_idx.shape[0]
    alive_rank = jnp.cumsum(alive[:n].astype(jnp.int32)) - 1
    key = jnp.where(alive[:n], rack_idx[:n] * n_pad + alive_rank, BIG)
    order = jnp.argsort(key).astype(jnp.int32)
    sorted_key = key[order]
    alive_s = alive[:n][order]
    sorted_rack = jnp.where(alive_s, rack_idx[:n][order], jnp.int32(r_cap))
    sorted_rank = jnp.where(alive_s, alive_rank[order], BIG)
    rr = jnp.arange(r_cap, dtype=jnp.int32)
    seg_start = jnp.searchsorted(sorted_rack, rr, side="left").astype(jnp.int32)
    seg_end = jnp.searchsorted(sorted_rack, rr, side="right").astype(jnp.int32)
    return Segments(order, sorted_key, sorted_rank, seg_start, seg_end)


def _wave_body(
    rack_idx: jnp.ndarray,
    cap: jnp.ndarray,
    n: int,
    alive: jnp.ndarray,
    rf: int,
    r_cap: int,
    seg: Segments,
    start: jnp.ndarray,    # scalar: topic rotation start = abs(hash) % n_alive
    n_alive: jnp.ndarray,  # scalar: live node count
    balance: bool = False,
    slot_pack: bool = False,  # static: hand out SLOTS (headroom) per wave
                              # instead of one replica per node per wave —
                              # giant-shape wave-count collapse (see
                              # spread_orphans; output-changing, so gated on
                              # the same shape budget as the dense demotion)
    quota: bool = False,      # static, implies balance: slot-packed hand-out
                              # under a per-rack per-wave quota that keeps
                              # rack fills even (water-filling drain) — the
                              # even-fill-preserving slot-packed balance
                              # (VERDICT r4 item 4); see QUOTA_WAVE_TARGET
):
    """One auction wave over all deficient partitions.

    The eligible-node choice is factored through *racks* instead of a dense
    (P × N) matrix: rack exclusivity already subsumes the node-duplicate check
    (a node holding p occupies its rack for p), so a partition's first-fit
    node is "the min-rotated-position available node of the best unblocked
    rack".

    Rotation without sorting: within a rack's segment (live-rank ascending),
    the topic-rotated probing order is the segment rotated at the cut where
    live-rank reaches ``n_alive - start`` — every node at/after the cut has
    rotated position ``rank + start - n_alive`` (all smaller than ``start``),
    every node before it ``rank + start``. Both halves stay rank-ascending,
    so "the j-th available node in rotated order" is two searchsorted probes
    into the availability cumsum over the fixed segment order. Per wave the
    whole auction is one O(N) cumsum plus O(r_cap + P) bookkeeping.

    ``balance=True`` ranks candidate racks by *remaining capacity* instead of
    first-fit position (ties → lowest rack id). Capacity-greedy rack choice
    keeps rack fill levels even, which solves saturated *fresh* placements
    where every first-fit order (the reference's included) dead-ends.

    ``quota=True`` (implies balance) is the even-fill-preserving SLOT-PACKED
    balance: full slot-packing serializes rack consumption (the top-headroom
    rack absorbs everything, rack by rack) and measurably strands the
    exactly-saturated giant instance, while node-per-wave balance needs
    O(orphans / racks) waves (~1200 at the 200k-partition replace-100 shape,
    ~107 s warm). Quota mode drains every NODE in parallel at a bounded
    rate — per wave each node offers ``ceil(headroom / QUOTA_WAVE_TARGET)``
    slots — so nodes stay evenly filled within racks and relative rack
    fills stay even (the property that solves saturated instances). Demand
    is spread across each partition's eligible candidate racks in
    proportion to their summed allowances (requester rank mapped into the
    cumulative-allowance intervals), so each rack receives roughly what it
    can absorb; over-allowance bids simply rebid next wave. Placement
    differences vs the node-per-wave leg are within the solver's documented
    orphan-choice freedom (movement parity is leg-invariant and
    test-pinned).

    Correctness of top-K (K = RF+1 capped at r_cap): a partition blocks at
    most RF racks, so among the RF+1 globally-best rack candidates at least
    one is unblocked, and any rack outside the candidates has a worse
    position than all of them; when r_cap <= RF the candidate set is every
    rack id outright. Quota mode widens K (to r_cap, capped at
    max(RF+1, 16)) purely for demand spread; the RF+1 guarantee is a subset.
    """
    k = min(r_cap, max(rf + 1, 16)) if quota else min(rf + 1, r_cap)
    order, sorted_key, sorted_rank, seg_start, seg_end = seg
    n_pad = rack_idx.shape[0]
    rr = jnp.arange(r_cap, dtype=jnp.int32)
    # Per-rack rotation cut (loop-invariant per topic): first in-segment
    # index whose live-rank >= n_alive - start.
    cut = jnp.searchsorted(sorted_key, rr * n_pad + (n_alive - start)).astype(
        jnp.int32
    )

    def body(state: AssignState) -> AssignState:
        avail = alive[:n] & (state.node_load[:n] < cap)
        # Running count of available units in segment order: rack r's j-th
        # unit (in any contiguous span) is where the count reaches
        # span_base + j + 1. A unit is one NODE by default (each node takes
        # at most one replica per wave — the round-robin-flavored packing),
        # or one SLOT of headroom under slot_pack (a node with h headroom
        # absorbs h same-wave requesters; post-wave load still <= cap
        # because exactly the headroom is handed out).
        if quota:
            # Proportional drain at NODE granularity: each node offers
            # ceil(headroom / T) slots per wave, so nodes stay evenly
            # filled within racks (keeping the node-per-wave endgame's
            # throughput alive) and racks drain proportionally (keeping
            # rack fills even — the saturated-instance property).
            headroom_n = jnp.where(avail, cap - state.node_load[:n], 0)
            t_div = quota_wave_target()
            units = (headroom_n + t_div - 1) // t_div
        elif slot_pack:
            units = jnp.where(avail, cap - state.node_load[:n], 0)
        else:
            units = avail.astype(jnp.int32)
        ca = jnp.cumsum(units[order])
        ca_pad = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32), ca])
        base = ca_pad[seg_start]                  # (r_cap,)
        seg_avail = ca_pad[seg_end] - base        # per-rack available count
        cum_at_cut = ca_pad[cut]
        a_after = ca_pad[seg_end] - cum_at_cut    # available at/after the cut
        if balance:
            headroom = jnp.where(avail, cap - state.node_load[:n], 0)
            rack_room = (
                jnp.zeros((r_cap,), dtype=jnp.int32)
                .at[rack_idx[:n]]
                .add(headroom)
            )
            _, cand_racks = lax.top_k(rack_room, k)
            cand_racks = cand_racks.astype(jnp.int32)
            cand_ok = rack_room[cand_racks] > 0
        else:
            # Best rotated position per rack: first available at/after the
            # cut (wrapped half, positions rank+start-n_alive), else first
            # available before it (positions rank+start).
            t_first = jnp.where(a_after > 0, cum_at_cut + 1, base + 1)
            i_first = jnp.clip(jnp.searchsorted(ca, t_first), 0, n - 1)
            rack_best = jnp.where(
                seg_avail > 0, (sorted_rank[i_first] + start) % n_alive, BIG
            )
            neg_top, cand_racks = lax.top_k(-rack_best, k)
            cand_racks = cand_racks.astype(jnp.int32)
            cand_ok = -neg_top < BIG              # rack has an available node

        acc_racks = _acc_racks(state, rack_idx)  # (P, RF)
        blocked = jnp.any(
            cand_racks[None, :, None] == acc_racks[:, None, :], axis=2
        )  # (P, K)
        ok = ~blocked & cand_ok[None, :] & (state.deficit > 0)[:, None]
        has_choice = jnp.any(ok, axis=1)
        valid = (state.deficit > 0) & has_choice
        if quota:
            # Demand spread ∝ allowance share: requester q (q = rank among
            # this wave's valid requesters — DENSE 0..n_valid-1, so the mod
            # spread is exactly uniform; raw row indices alias with striped
            # cluster layouts and measurably starve the last rack) lands on
            # the eligible candidate whose cumulative-allowance interval
            # contains (q mod its total eligible allowance), so each rack
            # receives demand proportional to what it can absorb this wave.
            q_cand = jnp.where(ok, seg_avail[cand_racks][None, :], 0)
            cum_q = jnp.cumsum(q_cand, axis=1, dtype=jnp.int32)
            total_q = cum_q[:, -1]
            rank_valid = jnp.cumsum(valid.astype(jnp.int32)) - 1
            choice = jnp.where(
                valid, rank_valid % jnp.maximum(total_q, 1), 0
            )
            first_ok = jnp.argmax(cum_q > choice[:, None], axis=1)
        else:
            first_ok = jnp.argmax(ok, axis=1)     # (P,) candidate slot

        # Monotonicity ⇒ no eligible rack now means never again: infeasible.
        infeasible = state.infeasible | jnp.any((state.deficit > 0) & ~has_choice)

        # Rank among same-rack requesters (ascending partition rows), then
        # hand out that rack's j-th best available node in rotated order.
        # Rank 0 always lands, so every requested rack places at least one
        # replica per wave.
        pick_rack = jnp.where(valid, cand_racks[first_ok], jnp.int32(r_cap))
        j = _requests_rank(pick_rack, valid, r_cap)
        accept = valid & (j < seg_avail[cand_racks][first_ok])
        pick = jnp.clip(pick_rack, 0, r_cap - 1)
        wrapped = j >= a_after[pick]              # past the wrapped half
        target = jnp.where(
            wrapped,
            base[pick] + (j - a_after[pick]) + 1,
            cum_at_cut[pick] + j + 1,
        )
        slot = jnp.clip(jnp.searchsorted(ca, target), 0, n - 1)
        node = order[slot].astype(jnp.int32)
        state = _accept_batch(state, node, accept)
        return state._replace(infeasible=infeasible)

    return body


def _hybrid_quota_body(
    rack_idx: jnp.ndarray,
    cap: jnp.ndarray,
    n: int,
    alive: jnp.ndarray,
    rf: int,
    r_cap: int,
    seg: Segments,
    start: jnp.ndarray,
    n_alive: jnp.ndarray,
):
    """The even-fill-preserving slot-packed balance (the ``balance_quota``
    leg): proportional-quota waves (``_wave_body(quota=True)``) drain the
    bulk in ~log(cap) waves, then a one-way ``lax.cond`` hands the endgame
    (every rack at headroom <= QUOTA_ENDGAME_HEADROOM) to the node-per-wave
    balance wave, whose cautious top-headroom packing is what actually
    threads the last rack-exclusivity-constrained slots. See the constants'
    comments for the measured wave-count math."""
    quota_body = _wave_body(
        rack_idx, cap, n, alive, rf, r_cap, seg, start, n_alive,
        balance=True, quota=True,
    )
    endgame_body = _wave_body(
        rack_idx, cap, n, alive, rf, r_cap, seg, start, n_alive,
        balance=True,
    )

    def body(state: AssignState) -> AssignState:
        headroom = jnp.where(
            alive[:n] & (state.node_load[:n] < cap),
            cap - state.node_load[:n],
            0,
        )
        rack_room = (
            jnp.zeros((r_cap,), dtype=jnp.int32)
            .at[rack_idx[:n]]
            .add(headroom)
        )
        bulk = jnp.max(rack_room) > quota_endgame_headroom()
        return lax.cond(bulk, quota_body, endgame_body, state)

    return body


def _seq_fill(
    state: AssignState,
    rack_idx: jnp.ndarray,
    pos_fn,  # () -> (N_pad,) rotated positions (BIG for dead nodes)
    cap: jnp.ndarray,
    n: int,
    alive: jnp.ndarray,
) -> AssignState:
    """The reference's ``assignOrphans`` replicated exactly
    (``KafkaAssignmentStrategy.java:162-186``): partitions in ascending row
    order, each one filled COMPLETELY — probing nodes in topic-rotated order
    and taking the first acceptable — before the next partition starts.

    This is deliberately sequential (a ``lax.scan`` over partition rows with
    a static slot unroll), unlike the auction legs: one replica per
    partition per wave can dead-end on exactly-tight instances that
    sequential packing threads through, and vice versa — which is why BOTH
    families are in the chain. As the final leg it guarantees the chain
    solves every instance the reference solves, with the reference's own
    placements when reached.
    """
    pos_n = pos_fn()[:n]
    w = state.acc_nodes.shape[1]
    rows = jnp.arange(n, dtype=jnp.int32)

    def per_row(node_load, inp):
        nodes, count, deficit = inp
        infeasible = jnp.asarray(False)
        st = (nodes, count, deficit, node_load, infeasible)
        for _ in range(w):  # static: a row's deficit <= its slot width
            nodes, count, deficit, node_load, infeasible = st
            acc_racks = jnp.where(
                nodes >= 0, rack_idx[jnp.maximum(nodes, 0)], -1
            )
            rack_blocked = jnp.any(
                rack_idx[:n][:, None] == acc_racks[None, :], axis=1
            )
            dup = jnp.any(rows[:, None] == nodes[None, :], axis=1)
            eligible = (
                alive[:n] & (node_load[:n] < cap) & ~rack_blocked & ~dup
            )
            any_e = jnp.any(eligible)
            pick = jnp.argmin(jnp.where(eligible, pos_n, BIG)).astype(
                jnp.int32
            )
            ok = (deficit > 0) & any_e
            infeasible = infeasible | ((deficit > 0) & ~any_e)
            slot_onehot = jnp.arange(w, dtype=jnp.int32) == count
            nodes = jnp.where(slot_onehot & ok, pick, nodes)
            count = count + ok.astype(jnp.int32)
            node_load = node_load.at[jnp.where(ok, pick, jnp.int32(n))].add(1)
            deficit = deficit - ok.astype(jnp.int32)
            st = (nodes, count, deficit, node_load, infeasible)
        nodes, count, deficit, node_load, infeasible = st
        return node_load, (nodes, count, deficit, infeasible)

    node_load, (nodes, counts, deficits, infs) = lax.scan(
        per_row, state.node_load,
        (state.acc_nodes, state.acc_count, state.deficit),
    )
    return AssignState(
        acc_nodes=nodes, acc_count=counts, node_load=node_load,
        deficit=deficits, infeasible=state.infeasible | jnp.any(infs),
    )


#: Legal wave modes and the packing chain each one runs. Every leg restarts
#: from the post-sticky state; a later leg runs only if the previous stranded.
#:   "auto"    — fast → dense → balance → seq  (reassignments)
#:   "fresh"   — balance → fast → dense → seq  (from-scratch placements)
#:   "fast"    — fast only   (vmapped sweeps: lax.cond under vmap lowers to
#:               select and would run fallback legs for every batch element;
#:               callers re-run stranded elements in "auto")
#:   "dense"   — dense only  (first-fit probing order, simultaneous waves)
#:   "balance" — balance only (capacity-greedy rack choice)
#:   "seq"     — the reference's ``assignOrphans`` VERBATIM: partitions
#:               ascending, each filled completely via rotated first-fit
#:               before the next starts. Every other leg is a simultaneous
#:               auction (one replica per partition per wave), and on
#:               exactly-tight instances every auction order can strand
#:               where sequential packing succeeds — so "seq" as the final
#:               leg is what makes the default chains a TRUE superset of
#:               the reference: any instance greedy solves, the chain
#:               solves (identically, when it falls through to this leg).
#: MappingProxyType, not a plain dict: ``_resolve_wave_plan`` reads this
#: under jit trace, and kalint KA007 (rightly) flags mutable globals closed
#: over by traced code — a mid-process mutation would be silently baked into
#: every cached executable. The proxy makes the freeze real.
WAVE_MODES = MappingProxyType({
    "auto": ("fast", "dense", "balance", "seq"),
    "fresh": ("balance", "fast", "dense", "seq"),
    "fast": ("fast",),
    "dense": ("dense",),
    "balance": ("balance",),
    "seq": ("seq",),
    # Two-leg chains: identical output to "auto" whenever the fast leg (or
    # the chain's fallback) succeeds — which is every non-saturated case —
    # but compile one fewer while_loop body. Compile time is a first-class
    # cost on the deployment target (remote compile over the chip tunnel),
    # so the solver exposes the chain via KA_WAVE_MODE for measurement.
    "fast_balance": ("fast", "balance"),
    "fast_dense": ("fast", "dense"),
    # Measurement/test mode: the even-fill-preserving slot-packed balance
    # alone (no rescue legs) — proves the quota leg solves an instance
    # itself rather than falling through, and isolates its wave count for
    # on-chip timing. Production chains get it auto-inserted before every
    # node-per-wave balance leg at giant shapes (see spread_orphans).
    "balance_quota": ("balance_quota",),
})


def _resolve_wave_plan(
    wave_mode: str, n_pad: int, r_cap: int | None
) -> tuple[tuple[str, ...], int]:
    """Single source of truth for the wave chain's (legs, r_cap): validates
    ``wave_mode``, defaults ``r_cap`` (rack ids: reals < n, padded rows get
    n..2n_pad-ish; bound generously), and handles the int32 key-packing bound.
    ``spread_orphans`` and ``_hoisted_segments`` both resolve through here so
    the hoisted segment arrays can never be sized or gated differently from
    what the wave bodies expect."""
    if wave_mode not in WAVE_MODES:
        raise ValueError(
            f"unknown wave_mode {wave_mode!r}; expected one of {sorted(WAVE_MODES)}"
        )
    if r_cap is None:
        r_cap = 2 * n_pad
    legs = WAVE_MODES[wave_mode]
    # The fast/balance waves sort on (rack, live-rank) packed into int32 keys;
    # beyond this bound the packing would overflow. First-fit modes degrade to
    # dense; balance has no dense equivalent, so fail loudly rather than
    # silently change algorithm (clusters this size exceed any known Kafka
    # deployment — revisit with int64 keys if one appears).
    if n_pad * n_pad >= BIG:
        if wave_mode in ("balance", "balance_quota"):
            raise ValueError(
                f"wave_mode {wave_mode!r} packs (rack, live-rank) into int32 "
                f"keys, which overflows at n_pad={n_pad}"
            )
        if wave_mode != "seq":
            # seq does no key packing and must NOT degrade: it is the
            # reference-verbatim leg the RF-decrease compat mode's
            # three-backend byte parity rides on (solver_tuning).
            legs = ("dense", "seq") if len(legs) > 1 else ("dense",)
    return legs, r_cap


def spread_orphans(
    state: AssignState,
    rack_idx: jnp.ndarray,
    pos: jnp.ndarray,      # (N_pad,) rotated position per node index
    cap: jnp.ndarray,
    n: int,
    alive: jnp.ndarray | None = None,
    wave_mode: str = "auto",  # see WAVE_MODES
    r_cap: int | None = None,  # static rack-id bound (ProblemEncoding.r_cap);
                               # None = conservative 2*n_pad worst case
    seg: Segments | None = None,  # precomputed cluster_segments (batched
                                  # solves hoist it out of the topic scan)
    start: jnp.ndarray | None = None,    # topic rotation start (scalar)
    n_alive: jnp.ndarray | None = None,  # live node count (scalar)
) -> AssignState:
    """Wave-auction placement of all outstanding replicas
    (``getOrphanedReplicas`` + ``assignOrphans``, ``:133-186``).

    The fast path's packing (j-th requester → rack's j-th best node) can
    strand near saturation where dense first-fit does not; the capacity-greedy
    balance packing solves saturated instances where *every* first-fit order
    (the reference's included, ``KafkaAssignmentStrategy.java:29-30``)
    dead-ends. The chained modes report infeasible only when every leg fails.

    ``r_cap`` sizes every per-rack tensor. Placement decisions are invariant
    to it (any bound above the real rack count yields byte-identical output);
    the encoder's tight bucket (~16 for a 10-rack cluster) makes the per-rack
    ops negligible next to the 2*n_pad = 16384 worst case.

    ``start``/``n_alive`` drive the fast/balance rotation; callers that know
    them (the placement pipeline) pass them, otherwise they are derived from
    ``pos`` (the rotated-position array both were computed from). ``pos`` may
    be None when ``start``/``n_alive`` are given — the dense leg then derives
    the rotated positions lazily inside its wave body, so the O(N) rank ops
    only execute when a dense wave actually iterates.
    """
    if alive is None:
        alive = default_alive(rack_idx, n)
    rf = state.acc_nodes.shape[1]
    n_pad = rack_idx.shape[0]
    legs, r_cap = _resolve_wave_plan(wave_mode, n_pad, r_cap)
    # Giant-single-topic guard (static, shape-derived): the dense leg's
    # per-wave (P x N) eligibility/score is ~1e9 elements at the 200k x 5k
    # long-axis shape — measured 355 s warm on CPU when the exactly-
    # saturated replace-N instance strands the fast leg and dense burns its
    # wave budget before balance rescues (the reference's own first-fit
    # PROVABLY dead-ends on that instance, KafkaAssignmentStrategy.java:29-30,
    # so dense was doomed to strand too). Past the budget, dense demotes to
    # last resort; rack-factored legs are O(N + P) per wave. Leg ORDER is
    # within the solver's documented orphan-choice freedom (movement parity
    # is leg-invariant); normal shapes keep the reference-faithful order.
    p_pad = state.acc_nodes.shape[0]
    budget = dense_mask_budget()
    if len(legs) > 1 and "dense" in legs and p_pad * n_pad > budget:
        legs = tuple(l for l in legs if l != "dense") + ("dense",)

    def cond(state: AssignState) -> jnp.ndarray:
        return jnp.any(state.deficit > 0) & ~state.infeasible

    if pos is None and (start is None or n_alive is None):
        raise ValueError("spread_orphans needs pos, or start + n_alive")
    if any(leg in ("fast", "balance", "balance_quota") for leg in legs):
        if seg is None:
            seg = cluster_segments(rack_idx, n, alive, r_cap)
        if n_alive is None:
            n_alive = jnp.maximum(
                jnp.sum(alive[: max(n, 1)].astype(jnp.int32)), 1
            )
        if start is None:
            # pos = (alive_rank + start) % n_alive; the first live node has
            # alive_rank 0, so its position IS the rotation start.
            first_live = jnp.argmax(alive[:n]).astype(jnp.int32)
            start = pos[first_live]

    def pos_fn():
        if pos is not None:
            return pos
        alive_rank = jnp.cumsum(alive.astype(jnp.int32)) - 1
        return jnp.where(alive, (alive_rank + start) % n_alive, BIG)

    # Slot-packed FAST waves at giant shapes (same static budget as the
    # dense demotion above): handing out headroom SLOTS instead of one-
    # replica-per-node-per-wave collapses the wave count from
    # O(orphans / racks) to O(max deficit) — measured 27.6 s -> 1.1 s warm
    # at the 200k-partition expansion instance — while normal shapes keep
    # their byte-stable node-per-wave packing. The BALANCE leg stays
    # node-per-wave at every shape: its job is keeping rack fill levels
    # even, and slot-packing the top-headroom rack destroys exactly that
    # (measured: the exactly-saturated giant instance strands under a
    # slot-packed balance but solves under the node-per-wave one).
    slot_pack = bool(p_pad * n_pad > budget)
    bodies = {
        "fast": lambda: _wave_body(
            rack_idx, cap, n, alive, rf, r_cap, seg, start, n_alive,
            slot_pack=slot_pack,
        ),
        "dense": lambda: _wave_body_dense(rack_idx, pos_fn, cap, n, alive, r_cap),
        "balance": lambda: _wave_body(
            rack_idx, cap, n, alive, rf, r_cap, seg, start, n_alive,
            balance=True,
        ),
        "balance_slots": lambda: _wave_body(
            rack_idx, cap, n, alive, rf, r_cap, seg, start, n_alive,
            balance=True, slot_pack=True,
        ),
        "balance_quota": lambda: _hybrid_quota_body(
            rack_idx, cap, n, alive, rf, r_cap, seg, start, n_alive
        ),
    }
    # Giant FRESH placements: everything is an orphan and the leading
    # balance leg's node-per-wave hand-out needs ~cap waves (measured 151 s
    # for 200k x RF3 from scratch). A slot-packed balance tries first —
    # uniform fresh loads are exactly where packing a rack densely is safe —
    # with the node-per-wave balance (and the rest of the chain) unchanged
    # behind it for anything it strands.
    if slot_pack and legs and legs[0] == "balance":
        legs = ("balance_slots",) + legs
    # Even-fill-preserving slot-packed balance first at giant shapes: the
    # node-per-wave balance stays right behind it as the rescue (a stranded
    # leg restarts the next one from the post-sticky state), so this is a
    # pure wave-count win on instances quota solves — measured on the
    # exactly-saturated 200k-partition replace-100 showcase (the ~107-133 s
    # strand-then-rescue path, VERDICT r4 item 4).
    if slot_pack and "balance" in legs:
        out: list[str] = []
        for leg in legs:
            if leg == "balance":
                out.append("balance_quota")
            out.append(leg)
        legs = tuple(out)

    # Progress is ≥ 1 placement per wave while feasible (the rank-0 bid on any
    # requested rack/node always lands), so P*RF waves is a hard upper bound;
    # while_loop exits early via cond. The "seq" leg is a single sequential
    # pass, not a wave loop.
    def run_chain(chain) -> AssignState:
        if chain[0] == "seq":
            result = _seq_fill(state, rack_idx, pos_fn, cap, n, alive)
        else:
            result = lax.while_loop(cond, bodies[chain[0]](), state)
        if len(chain) == 1:
            return result
        return lax.cond(
            result.infeasible, lambda: run_chain(chain[1:]), lambda: result
        )

    return run_chain(legs)


def _hoisted_segments(
    rack_idx: jnp.ndarray,
    n: int,
    alive: jnp.ndarray,
    wave_mode: str,
    r_cap: int | None,
) -> Segments | None:
    """``cluster_segments`` when the wave chain has a fast/balance leg (and
    the key packing fits int32) — the batched solvers call this once outside
    their topic scan/vmap. Resolves (legs, r_cap) through the same
    ``_resolve_wave_plan`` as ``spread_orphans``, since the segment arrays are
    sized by r_cap and gated by the resolved legs."""
    legs, r_cap = _resolve_wave_plan(wave_mode, rack_idx.shape[0], r_cap)
    if not any(leg in ("fast", "balance", "balance_quota") for leg in legs):
        return None
    return cluster_segments(rack_idx, n, alive, r_cap)


def leadership_order(
    acc_nodes: jnp.ndarray,   # (P, RF) broker indices (complete rows)
    acc_count: jnp.ndarray,   # (P,)
    counters: jnp.ndarray,    # (N_pad, RF) Context slab
    jhash: jnp.ndarray,       # scalar: abs(java hash of topic)
    rf: int,
    chunk: int | None = None,  # partitions per scan step (static unroll)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Order each partition's replica set by leadership preference,
    reproducing ``computePreferenceLists`` (``:202-302``) exactly.

    For slot r with m = rf - r remaining candidates, the reference scans the
    candidates in rotated order (start = abs(hash) % m over the *sorted
    remaining* set) and takes the first strict minimum of counter[node][r] —
    equivalently the argmin of the key ``count * m + rotated_pos``. Counters
    persist across partitions (and topics, via Context), so partitions are
    processed with ``lax.scan``.

    Returns (ordered (P, RF), updated counters).
    """

    def order_one(counters, cand, count):
        remaining = jnp.arange(rf, dtype=jnp.int32) < count
        ordered = jnp.full((rf,), -1, dtype=jnp.int32)
        for r in range(rf):  # static unroll, rf <= batch-max RF
            # m = number of remaining candidates = count - r (the reference's
            # per-partition replicationFactor, :227-229) — computed from the
            # partition's own count so mixed-RF batches and partial rows get
            # the exact reference rotation, not the batch-max one.
            m = jnp.maximum(count - jnp.int32(r), 1)
            start = (jhash % m).astype(jnp.int32)
            # Rank of each candidate among the remaining, by broker index
            # ascending (TreeSet order, :228).
            lt = (cand[None, :] < cand[:, None]) & remaining[None, :]
            k = jnp.sum(lt, axis=1).astype(jnp.int32)
            rot = (k + start) % m
            cnt = counters[jnp.maximum(cand, 0), r]
            key = jnp.where(remaining, cnt * m + rot, BIG)
            # Partitions whose replica list is shorter than rf (defensive;
            # complete solves always have count == rf) stop early.
            valid_slot = jnp.int32(r) < count
            choice = jnp.argmin(key).astype(jnp.int32)
            chosen_node = cand[choice]
            ordered = ordered.at[r].set(jnp.where(valid_slot, chosen_node, -1))
            remaining = remaining & (jnp.arange(rf, dtype=jnp.int32) != choice)
            counters = counters.at[jnp.maximum(chosen_node, 0), r].add(
                jnp.where(valid_slot, 1, 0)
            )
        return counters, ordered

    # Chunked scan: the dependency is inherently sequential (each partition
    # reads counters the previous one wrote), but a scan step costs fixed
    # overhead, so processing CHUNK partitions per step (inner static unroll,
    # same sequential semantics) cuts step count — at 200k partitions this is
    # the difference between ~200k and ~25k device loop iterations. The
    # unroll is also compile-time weight (remote compile on the deployment
    # target), so it is overridable: callers thread a static value, and the
    # sequential semantics are chunk-invariant (pinned by tests).
    p_pad = acc_nodes.shape[0]
    default = 8 if p_pad % 8 == 0 else 1
    if chunk is None:
        chunk = default
    elif p_pad % chunk != 0:
        # An explicitly requested chunk that cannot tile P would silently
        # measure a different program than the caller asked for — say so.
        print(
            f"kafka-assigner: leader chunk {chunk} does not divide "
            f"p_pad={p_pad}; using {default}",
            file=sys.stderr,
        )
        chunk = default
    cand_chunks = acc_nodes.reshape(p_pad // chunk, chunk, rf)
    count_chunks = acc_count.reshape(p_pad // chunk, chunk)

    def per_chunk(counters, row):
        cands, counts = row  # (chunk, RF), (chunk,)
        outs = []
        for c in range(chunk):  # static unroll: sequential within the chunk
            counters, ordered = order_one(counters, cands[c], counts[c])
            outs.append(ordered)
        return counters, jnp.stack(outs)

    counters, ordered = lax.scan(per_chunk, counters, (cand_chunks, count_chunks))
    return ordered.reshape(p_pad, rf), counters


def _place_one_topic(
    current: jnp.ndarray,
    jhash: jnp.ndarray,
    p_real: jnp.ndarray,
    rack_idx: jnp.ndarray,
    alive: jnp.ndarray,  # (N_pad,) bool — scenario liveness mask
    n: int,
    rf: int,
    wave_mode: str = "auto",
    rf_actual: jnp.ndarray | None = None,  # traced per-topic RF (mixed-RF sweeps)
    r_cap: int | None = None,
    seg: Segments | None = None,  # hoisted cluster_segments (batched callers)
    width: int | None = None,  # static compat slot width (see sticky_fill)
) -> Tuple[AssignState, jnp.ndarray]:
    """One topic's *placement* (sticky fill → wave spread).

    Placement is independent of the leadership counters, so callers come in
    two shapes: the sequential scan pipeline (``_solve_one_topic``) and the
    vmapped what-if sweep (``whatif_sweep``, vmap over scenario liveness).
    Under vmap only single-leg wave modes are safe — the chained-fallback
    ``lax.cond`` lowers to ``select`` and runs every leg for every topic
    (measured 10x CPU regression in round 1) — which is why the sweep runs
    fast-only with a host rescue of stranded scenarios.

    Capacity ``ceil(P*RF/N_alive)`` (``KafkaAssignmentStrategy.java:65-71``),
    the rotation start ``abs(hash) % N_alive`` (``:188-200``) and the rotated
    node positions are all computed on device from the traced liveness mask,
    so broker-removal scenarios need no host-side re-encoding.
    """
    if rf_actual is None:
        rf_actual = jnp.int32(rf)
    n_alive = jnp.maximum(jnp.sum(alive[: max(n, 1)].astype(jnp.int32)), 1)
    cap = (p_real * rf_actual + n_alive - 1) // n_alive
    start = jhash % n_alive

    state = sticky_fill(
        current, rack_idx, rf, cap, n, p_real, alive, rf_actual, width
    )
    sticky_kept = jnp.sum(state.acc_count)
    # pos=None: the dense fallback leg derives rotated positions lazily
    # inside its wave body (start/n_alive carry the rotation), saving an
    # O(N_pad) cumsum+where per topic on the common no-dense-wave path.
    state = spread_orphans(
        state, rack_idx, None, cap, n, alive, wave_mode, r_cap,
        seg=seg, start=start, n_alive=n_alive,
    )
    return state, sticky_kept


def _order_one_topic(
    counters: jnp.ndarray,
    acc_nodes: jnp.ndarray,
    acc_count: jnp.ndarray,
    jhash: jnp.ndarray,
    rf: int,
    use_pallas: bool,
    leader_chunk: int | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if use_pallas:
        # Opt-in TPU kernel: VMEM-resident counters, no per-partition scan
        # overhead; bit-identical to leadership_order (see module docstring).
        # The flag arrives as a static jit argument from the solver (never
        # from the vmapped what-if path).
        from .pallas_leadership import leadership_order_pallas

        return leadership_order_pallas(acc_nodes, acc_count, counters, jhash, rf)
    ordered, counters = leadership_order(
        acc_nodes, acc_count, counters, jhash, rf, leader_chunk
    )
    return ordered, counters


def _solve_one_topic(
    counters: jnp.ndarray,
    current: jnp.ndarray,
    jhash: jnp.ndarray,
    p_real: jnp.ndarray,
    rack_idx: jnp.ndarray,
    alive: jnp.ndarray,
    n: int,
    rf: int,
    wave_mode: str = "auto",
    use_pallas: bool = False,
    rf_actual: jnp.ndarray | None = None,
    leader_chunk: int | None = None,
    r_cap: int | None = None,
    seg: Segments | None = None,
    width: int | None = None,  # static compat slot width (see sticky_fill)
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """One topic's full pipeline (placement + leadership), shared by the
    single-topic, batched (scan over topics), fresh-placement, and what-if
    (vmap over ``alive``) entry points so their semantics cannot drift."""
    state, sticky_kept = _place_one_topic(
        current, jhash, p_real, rack_idx, alive, n, rf, wave_mode, rf_actual,
        r_cap, seg, width,
    )
    ordered, counters = _order_one_topic(
        counters, state.acc_nodes, state.acc_count, jhash,
        rf if width is None else width, use_pallas, leader_chunk,
    )
    return counters, (ordered, state.infeasible, state.deficit, sticky_kept)


def solve_assignment(
    current: jnp.ndarray,
    rack_idx: jnp.ndarray,
    counters: jnp.ndarray,
    jhash: jnp.ndarray,
    p_real: jnp.ndarray,
    n: int,
    rf: int,
    use_pallas: bool = False,
    r_cap: int | None = None,
    width: int | None = None,  # static compat slot width (see sticky_fill)
    wave_mode: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full single-topic solve.

    Returns (ordered (P, RF) broker indices, updated counters, infeasible
    flag, deficit vector for error reporting). With ``width`` the ordered
    array and counter slab are ``width`` wide instead.
    """
    alive = default_alive(rack_idx, n)
    counters, (ordered, infeasible, deficit, _) = _solve_one_topic(
        counters, current, jhash, p_real, rack_idx, alive, n, rf,
        wave_mode=wave_mode, use_pallas=use_pallas, r_cap=r_cap, width=width,
    )
    return ordered, counters, infeasible, deficit


solve_assignment_jit = jax.jit(
    solve_assignment,
    static_argnames=("n", "rf", "use_pallas", "r_cap", "width", "wave_mode"),
    donate_argnums=(),
)


def solve_batched(
    currents: jnp.ndarray,   # (B, P_pad, L) broker index or -1
    rack_idx: jnp.ndarray,   # (N_pad,) shared across topics (one broker set per run)
    counters: jnp.ndarray,   # (N_pad, RF) cross-topic Context slab
    jhashes: jnp.ndarray,    # (B,)
    p_reals: jnp.ndarray,    # (B,)
    n: int,
    rf: int,                 # static max RF (array width)
    alive: jnp.ndarray | None = None,  # (N_pad,) scenario liveness mask
    wave_mode: str = "auto",
    use_pallas: bool = False,
    rfs: jnp.ndarray | None = None,  # (B,) per-topic RF for mixed-RF sweeps
    leader_chunk: int | None = None,  # static leadership unroll (see leadership_order)
    r_cap: int | None = None,         # static rack-id bound (ProblemEncoding.r_cap)
    width: int | None = None,         # static compat slot width (see sticky_fill)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Solve B topics in one device dispatch.

    The reference solves topics serially in CLI order because the leadership
    Context carries across topics (``KafkaAssignmentGenerator.java:166-176``,
    ``KafkaTopicAssigner.java:19-23``). We keep those exact semantics — the
    counter slab is the ``lax.scan`` carry and topics run in the given order —
    but the entire loop is one compiled program, so per-topic dispatch latency
    (the dominant cost through a TPU tunnel) is paid once per run instead of
    once per topic.

    Returns (ordered (B, P_pad, RF), counters, infeasible (B,), deficits
    (B, P_pad), sticky_kept (B,)). Inert padding topics (p_real == 0) are
    no-ops: nothing to stick, no deficit, no counter updates. ``currents``
    may arrive int16 (upload narrowing, see ``place_scan``); widened on
    device first.
    """
    currents = currents.astype(jnp.int32)
    if alive is None:
        alive = default_alive(rack_idx, n)
    if rfs is None:
        rfs = jnp.full(currents.shape[0], rf, dtype=jnp.int32)
    seg = _hoisted_segments(rack_idx, n, alive, wave_mode, r_cap)

    def per_topic(counters, inp):
        current, jhash, p_real, rf_actual = inp
        return _solve_one_topic(
            counters, current, jhash, p_real, rack_idx, alive, n, rf,
            wave_mode, use_pallas, rf_actual, leader_chunk, r_cap, seg, width,
        )

    counters, (ordered, infeasible, deficits, kept) = lax.scan(
        per_topic, counters, (currents, jhashes, p_reals, rfs)
    )
    return ordered, counters, infeasible, deficits, kept



solve_batched_jit = jax.jit(
    solve_batched,
    static_argnames=(
        "n", "rf", "wave_mode", "use_pallas", "leader_chunk", "r_cap", "width"
    ),
)


def place_scan(
    currents: jnp.ndarray,   # (B, P_pad, L)
    rack_idx: jnp.ndarray,
    jhashes: jnp.ndarray,    # (B,)
    p_reals: jnp.ndarray,    # (B,)
    n: int,
    rf: int,
    wave_mode: str = "auto",
    rfs: jnp.ndarray | None = None,
    r_cap: int | None = None,
    alive: jnp.ndarray | None = None,  # (N_pad,) scenario liveness
    width: int | None = None,          # static compat slot width (sticky_fill)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Placement-only scan over topics with the FULL fallback chain — the
    rescue path for topics the vmapped fast wave strands. Sequential (scan,
    not vmap) so the chained ``lax.cond`` legs stay real branches, but one
    compiled dispatch covers the whole rescue subset — through a tunneled
    chip that matters more than the serialization (~80-100 ms per dispatch).

    Each scan row is one topic and carries everything its placement needs
    (``current``, ``jhash``, ``p_real``, ``rf_actual``) against shared
    per-cluster operands: rows never read each other's carry (the carry is
    a dummy). That per-row independence is the batch-concat contract the
    daemon dispatcher relies on to pack DISTINCT plans whose bucketed
    shapes and statics agree into one device call along the batch axis and
    demux the outputs per job — concatenation cannot change any row's
    result, only its position.

    ``currents`` may arrive int16 (callers halve the host→device upload when
    broker indices fit — the transfer rides the chip tunnel on the
    deployment target); it is widened here, on device, before any math."""
    currents = currents.astype(jnp.int32)
    if alive is None:
        alive = default_alive(rack_idx, n)
    if rfs is None:
        rfs = jnp.full(currents.shape[0], rf, dtype=jnp.int32)
    seg = _hoisted_segments(rack_idx, n, alive, wave_mode, r_cap)

    def step(carry, inp):
        current, jhash, p_real, rf_actual = inp
        state, kept = _place_one_topic(
            current, jhash, p_real, rack_idx, alive, n, rf, wave_mode,
            rf_actual, r_cap, seg, width,
        )
        return carry, (
            state.acc_nodes, state.acc_count, state.infeasible, state.deficit,
            kept,
        )

    _, outs = lax.scan(step, 0, (currents, jhashes, p_reals, rfs))
    return outs


place_scan_jit = jax.jit(
    place_scan, static_argnames=("n", "rf", "wave_mode", "r_cap", "width")
)


def _narrow_placed(acc_nodes, acc_count, infeasible, deficit, kept):
    """Device-side downcasts at the placement→host boundary.

    The production pipeline fetches the placement arrays to the host (the
    leadership chain and the JSON decode both live there), and on the
    deployment target that fetch crosses the chip tunnel — measured round 5:
    the (B, P_pad, RF)+2×(B, P_pad) int32 pull was ~3 MB of the headline
    solve phase's wall clock. Broker indices fit int16 whenever the padded
    broker axis does (value range [-1, n_pad)); per-partition accept/deficit
    counts are bounded by the slot width (≤ the replica-list width, ~≤10 in
    any real cluster) and fit int8. Casts are trace-time no-ops when the
    bound doesn't hold, so callers never change semantics by calling this.
    """
    if acc_nodes.shape[-1] < (1 << 7):  # slot width bounds count/deficit
        acc_count = acc_count.astype(jnp.int8)
        deficit = deficit.astype(jnp.int8)
    return acc_nodes, acc_count, infeasible, deficit, kept


def _narrow_nodes(acc_nodes, n_pad: int):
    return acc_nodes.astype(jnp.int16) if n_pad < (1 << 15) else acc_nodes


def place_scan_narrow(
    currents, rack_idx, jhashes, p_reals, n, rf, wave_mode="auto",
    rfs=None, r_cap=None, alive=None, width=None,
):
    """``place_scan`` with the host-boundary downcasts fused into the same
    compiled program (the cast runs on device; the fetch moves fewer bytes).
    Output values are identical to ``place_scan``'s, only dtypes narrow."""
    outs = place_scan(
        currents, rack_idx, jhashes, p_reals, n, rf, wave_mode, rfs,
        r_cap=r_cap, alive=alive, width=width,
    )
    acc_nodes, rest = outs[0], outs[1:]
    return _narrow_placed(_narrow_nodes(acc_nodes, rack_idx.shape[0]), *rest)


place_scan_narrow_jit = jax.jit(
    place_scan_narrow, static_argnames=("n", "rf", "wave_mode", "r_cap", "width")
)


def place_chunked(
    currents: jnp.ndarray,   # (B, P_pad, L)
    rack_idx: jnp.ndarray,
    jhashes: jnp.ndarray,    # (B,)
    p_reals: jnp.ndarray,    # (B,)
    n: int,
    rf: int,
    chunk: int,              # topics per vmapped block (static)
    rfs: jnp.ndarray | None = None,
    r_cap: int | None = None,
    alive: jnp.ndarray | None = None,  # (N_pad,) scenario liveness
    width: int | None = None,          # static compat slot width (sticky_fill)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Topic-axis VMAPPED fast-leg placement, chunked, in one dispatch.

    ``place_scan`` serializes topics so the chained fallback ``lax.cond``
    legs stay real branches — the right trade on a 1-core host, where total
    work bounds wall clock. On the chip the trade inverts: the round-5
    headline trip counts (``TPU_TRIP_COUNTS_r05.json``) measured 471
    *sequential* while_loop waves across the 2048-topic scan, each wave a
    sliver of tensor work that cannot saturate anything, and the first
    on-chip run spent most of its solve phase stepping them. Placement is
    per-topic independent (the reference solves topics one at a time,
    ``KafkaAssignmentGenerator.java:173-176``; only leadership ordering
    carries cross-topic state), so this entry vmaps the single-leg fast
    wave over topics instead: the batched while_loop runs
    max-waves-per-chunk trips (3 at the headline, vs 471), each trip wide
    enough to fill the vector units. Fast-only keeps the body single-leg
    (vmap-safe, same reasoning as ``whatif_sweep``); topics the fast leg
    strands come back flagged ``infeasible`` and the caller rescues them
    through the full ``place_scan`` chain — byte-identical overall, since a
    stranded leg restarts the next from the post-sticky state anyway
    (``spread_orphans``).

    ``chunk`` bounds memory: the batch reshapes to (B/chunk, chunk, ...) and
    an outer ``lax.map`` runs the vmapped block per chunk — still ONE
    dispatch (one tunnel round-trip), with live intermediates scaled by
    ``chunk``, not B. Output contract and dtypes match
    ``place_scan_narrow``; padded rows (added when ``chunk`` ∤ B) are inert
    topics, sliced off before returning. ``currents`` may arrive int16
    (upload narrowing, see ``place_scan``); widened on device first.
    """
    currents = currents.astype(jnp.int32)
    if alive is None:
        alive = default_alive(rack_idx, n)
    if rfs is None:
        rfs = jnp.full(currents.shape[0], rf, dtype=jnp.int32)
    b = currents.shape[0]
    chunk = max(1, min(chunk, b))
    n_chunks = -(-b // chunk)
    pad = n_chunks * chunk - b
    if pad:
        currents = jnp.concatenate(
            [currents, jnp.full((pad,) + currents.shape[1:], -1, currents.dtype)]
        )
        jhashes = jnp.concatenate([jhashes, jnp.zeros(pad, jhashes.dtype)])
        p_reals = jnp.concatenate([p_reals, jnp.zeros(pad, p_reals.dtype)])
        rfs = jnp.concatenate([rfs, jnp.full(pad, rf, rfs.dtype)])
    seg = _hoisted_segments(rack_idx, n, alive, "fast", r_cap)

    def one(current, jhash, p_real, rf_actual):
        state, kept = _place_one_topic(
            current, jhash, p_real, rack_idx, alive, n, rf, "fast",
            rf_actual, r_cap, seg, width,
        )
        return state.acc_nodes, state.acc_count, state.infeasible, state.deficit, kept

    def per_chunk(blk):
        return jax.vmap(one)(*blk)

    outs = lax.map(
        per_chunk,
        (
            currents.reshape(n_chunks, chunk, *currents.shape[1:]),
            jhashes.reshape(n_chunks, chunk),
            p_reals.reshape(n_chunks, chunk),
            rfs.reshape(n_chunks, chunk),
        ),
    )
    acc_nodes, acc_count, infeasible, deficit, kept = (
        o.reshape(n_chunks * chunk, *o.shape[2:])[:b] for o in outs
    )
    return _narrow_placed(
        _narrow_nodes(acc_nodes, rack_idx.shape[0]),
        acc_count, infeasible, deficit, kept,
    )


place_chunked_jit = jax.jit(
    place_chunked, static_argnames=("n", "rf", "chunk", "r_cap", "width")
)


def order_batched(
    acc_nodes: jnp.ndarray,  # (B, P_pad, RF) placed replica sets
    acc_count: jnp.ndarray,  # (B, P_pad)
    counters: jnp.ndarray,   # (N_pad, RF) cross-topic Context slab
    jhashes: jnp.ndarray,    # (B,)
    rf: int,
    use_pallas: bool = False,
    leader_chunk: int | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stage 2: leadership ordering over already-placed topics, sequential in
    topic order (the Context counter dependency is the one true serialization
    in the whole solve, ``KafkaAssignmentStrategy.java:218-237``)."""

    def step(counters, inp):
        nodes, count, jh = inp
        ordered, counters = _order_one_topic(
            counters, nodes, count, jh, rf, use_pallas, leader_chunk
        )
        return counters, ordered

    counters, ordered = lax.scan(step, counters, (acc_nodes, acc_count, jhashes))
    return ordered, counters


order_batched_jit = jax.jit(
    order_batched, static_argnames=("rf", "use_pallas", "leader_chunk")
)


def whatif_sweep(
    currents: jnp.ndarray,   # (B, P_pad, L) the cluster's topics
    rack_idx: jnp.ndarray,   # (N_pad,)
    jhashes: jnp.ndarray,    # (B,)
    p_reals: jnp.ndarray,    # (B,)
    alive_masks: jnp.ndarray,  # (S, N_pad) one liveness mask per scenario
    n: int,
    rf: int,                   # static max RF (array width)
    wave_mode: str = "fast",
    rfs: jnp.ndarray | None = None,  # (B,) per-topic RF
    r_cap: int | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Evaluate S broker-removal scenarios over the full cluster in parallel.

    The reference answers "what if we removed these brokers" one scenario per
    process run (``--broker_hosts_to_remove``); here the scenario axis is a
    ``vmap`` over the liveness mask, embarrassingly parallel, and shards
    across a device mesh (``parallel/whatif.py``) — BASELINE config 5.

    Every metric is SET-based (replica membership, node loads), so the
    scenario body runs *placement only* — the leadership ordering merely
    permutes each partition's replica row and cannot change any output; at
    config-5 scale dropping its sequential scan from the vmapped body is a
    multi-x saving. Returns per-scenario (moved_replicas (S,),
    any_infeasible (S,), max_node_load (S,)).
    """
    if rfs is None:
        rfs = jnp.full(currents.shape[0], rf, dtype=jnp.int32)

    # wave_mode "fast" (no in-graph dense fallback): under vmap, lax.cond
    # lowers to select and both branches would execute for every scenario.
    # Stranded scenarios are re-run in dense mode by the caller.
    def one_scenario(alive):
        # Topic-axis scan (NOT vmap): under the scenario vmap a topic-vmapped
        # placement would run every wave body max-wave-count times across all
        # (scenario, topic) pairs at once — measured 1.6x slower at config-5
        # scale — while the scan keeps each topic's while_loop trip count
        # scenario-batched only, and keeps the compiled program one
        # chain-body instead of a topic-vmapped copy of every leg.
        placed, _, infeasible, _, _ = place_scan(
            currents, rack_idx, jhashes, p_reals, n, rf, wave_mode, rfs,
            r_cap=r_cap, alive=alive,
        )
        # True moved-replica metric: membership diff of the final assignment
        # vs the current matrix. (The sticky_kept proxy over-counts: an orphan
        # the wave auction happens to land on a broker from the partition's
        # old replica list is not a move.) XLA fuses the (B,P,RF,L) compare
        # into the reduction, so nothing big materializes.
        in_old = jnp.any(
            placed[:, :, :, None] == currents[:, :, None, :], axis=-1
        )
        moved = jnp.sum((placed >= 0) & ~in_old)
        # Node loads across every topic's final assignment.
        safe = jnp.where(placed >= 0, placed, rack_idx.shape[0])
        loads = jnp.zeros(rack_idx.shape[0] + 1, dtype=jnp.int32).at[safe].add(1)
        return moved, jnp.any(infeasible), jnp.max(loads[: rack_idx.shape[0]])

    return jax.vmap(one_scenario)(alive_masks)


whatif_sweep_jit = jax.jit(
    whatif_sweep, static_argnames=("n", "rf", "wave_mode", "r_cap")  # rfs traced
)


def whatif_subset_sweep(
    currents: jnp.ndarray,   # (S, T_pad, P_pad, L) per-scenario AFFECTED topics
    rack_idx: jnp.ndarray,   # (N_pad,)
    jhashes: jnp.ndarray,    # (S, T_pad)
    p_reals: jnp.ndarray,    # (S, T_pad); padded topic rows are 0 (inert)
    alive_masks: jnp.ndarray,  # (S, N_pad)
    n: int,
    rf: int,
    rfs: jnp.ndarray | None = None,  # (S, T_pad)
    r_cap: int | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The full sweep restricted to each scenario's own affected-topic
    subset — the device half of the INCREMENTAL what-if sweep
    (``parallel/whatif.py``). Identical program structure to
    ``whatif_sweep`` (per-scenario hoisted segments, topic-axis scan, waves
    batched across scenarios only), so per-topic cost matches the dense
    sweep while total work shrinks to the affected fraction.

    Returns per-scenario (moved (S,), any_infeasible (S,),
    node_load (S, n)) over the subset topics only — the caller composes
    them with the host-side baseline loads of unaffected topics.
    """
    if rfs is None:
        rfs = jnp.full(currents.shape[:2], rf, dtype=jnp.int32)

    def one_scenario(currents_s, jh_s, pr_s, rfs_s, alive):
        placed, _, infeasible, _, _ = place_scan(
            currents_s, rack_idx, jh_s, pr_s, n, rf, "fast", rfs_s,
            r_cap=r_cap, alive=alive,
        )
        in_old = jnp.any(
            placed[:, :, :, None] == currents_s[:, :, None, :], axis=-1
        )
        moved = jnp.sum((placed >= 0) & ~in_old)
        safe = jnp.where(placed >= 0, placed, rack_idx.shape[0])
        loads = jnp.zeros(rack_idx.shape[0] + 1, dtype=jnp.int32).at[safe].add(1)
        return moved, jnp.any(infeasible), loads[:n]

    return jax.vmap(one_scenario)(currents, jhashes, p_reals, rfs, alive_masks)


whatif_subset_sweep_jit = jax.jit(
    whatif_subset_sweep, static_argnames=("n", "rf", "r_cap")
)


# ---------------------------------------------------------------------------
# Consumer-group packing (ISSUE 13): the second workload family.
#
# Same problem shape as partition→broker placement — integer assignment
# under hard constraints with a movement term — but the capacity constraint
# is WEIGHTED (sum of per-partition lag/throughput weights per consumer
# <= that consumer's capacity) instead of the count capacity
# ceil(P*RF/N), and each partition takes exactly one owner (RF == 1, no
# rack axis). The objective mirrors the placement family's: a sticky
# (movement-minimizing) term — keep a partition on its current owner
# whenever the capacity gate admits it — plus the packing term (first-fit-
# decreasing onto max-headroom consumers keeps per-consumer load tight and
# flags true overflow), with the leadership analogue absent by construction
# (consumer groups have no replica ordering).
#
# Semantics are EXACTLY the host greedy packing oracle's
# (solvers/greedypack.py) — parity is pinned per assignment cell, like the
# placement family pins against solvers/greedy.py:
#
#   1. sticky admission, ascending partition row per owner: partition p
#      stays on its current owner c iff c is alive and the PREFIX weight of
#      p and all earlier rows currently on c fits cap[c] (prefix semantics,
#      not running-kept-sum: one vectorized segmented cumsum on device, one
#      identical rule on the host — deliberate, documented divergence from
#      a per-row re-check, in the solver's orphan-choice freedom);
#   2. orphan spread, first-fit-decreasing: unkept real rows in descending
#      BASE-weight order (ties: ascending row — ``proc_order``, computed
#      once on the host because positive scaling never reorders it) each
#      take the alive consumer with the most remaining headroom that fits
#      (ties: lowest consumer index); nothing fits => the row lands on the
#      max-headroom alive consumer anyway and counts as overflow (the
#      infeasibility signal — the autoscale sweep's cost curve needs the
#      overload magnitude, not a bare failure flag).
#
# All weights/capacities arrive as int32 in a caller-scaled domain
# (groups/encode.py guarantees no int32 overflow under the largest scale
# it will sweep), so device/host parity is exact integer equality.
# ---------------------------------------------------------------------------


class PackState(NamedTuple):
    """Carried orphan-scan state for the consumer-pack kernel."""

    assigned: jnp.ndarray    # (P_pad,) consumer index or -1
    load: jnp.ndarray        # (C_pad + 1,) weight per consumer (+1 scratch)
    overflowed: jnp.ndarray  # () int32: rows placed over capacity


def pack_group(
    weights: jnp.ndarray,     # (P_pad,) int32 scaled weights (0 on pad rows)
    capacities: jnp.ndarray,  # (C_pad,) int32 scaled capacities
    current: jnp.ndarray,     # (P_pad,) int32 current consumer index or -1
    proc_order: jnp.ndarray,  # (P_pad,) int32 rows by (-base weight, row)
    alive: jnp.ndarray,       # (C_pad,) bool consumer liveness
    p_real: jnp.ndarray,      # scalar int32 real partition rows
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One group's capacity-constrained partition→consumer packing.

    Returns ``(assigned (P_pad,), load (C_pad,), moved, overflowed,
    infeasible)``; see the family comment above for the exact semantics.
    """
    p_pad = weights.shape[0]
    c_pad = capacities.shape[0]
    rows_real = jnp.arange(p_pad, dtype=jnp.int32) < p_real
    cur = jnp.where(rows_real, current, -1)
    safe_cur = jnp.clip(cur, 0, c_pad - 1)
    sticky_cand = (cur >= 0) & alive[safe_cur]

    # Sticky admission via ONE segmented prefix sum: stable argsort on the
    # owner key groups each consumer's candidate rows in ascending-row
    # order; the inclusive in-segment prefix is the cumsum minus the total
    # through the previous segment.
    key = jnp.where(sticky_cand, cur, jnp.int32(c_pad))
    order = jnp.argsort(key, stable=True).astype(jnp.int32)
    kw = jnp.where(sticky_cand, weights, 0)[order]
    csum = jnp.cumsum(kw)
    sk = key[order]
    first = jnp.searchsorted(sk, sk, side="left").astype(jnp.int32)
    seg_base = jnp.where(first > 0, csum[jnp.maximum(first - 1, 0)], 0)
    prefix = csum - seg_base
    cap_of = capacities[jnp.clip(sk, 0, c_pad - 1)]
    keep_sorted = sticky_cand[order] & (prefix <= cap_of)
    kept = jnp.zeros(p_pad, dtype=bool).at[order].set(keep_sorted)

    load0 = (
        jnp.zeros(c_pad + 1, dtype=jnp.int32)
        .at[jnp.where(kept, safe_cur, c_pad)]
        .add(jnp.where(kept, weights, 0))
    )
    state = PackState(
        assigned=jnp.where(kept, cur, -1),
        load=load0,
        overflowed=jnp.int32(0),
    )

    def step(state: PackState, row: jnp.ndarray) -> Tuple[PackState, None]:
        w = weights[row]
        need = rows_real[row] & ~kept[row]
        headroom = jnp.where(
            alive, capacities - state.load[:c_pad], jnp.int32(-BIG)
        )
        fits = alive & (headroom >= w)
        any_fit = jnp.any(fits)
        # argmax returns the FIRST maximum — the lowest-index tie-break
        # the host oracle uses.
        pick_fit = jnp.argmax(jnp.where(fits, headroom, -BIG))
        pick_any = jnp.argmax(headroom)
        pick = jnp.where(any_fit, pick_fit, pick_any).astype(jnp.int32)
        assigned = state.assigned.at[row].set(
            jnp.where(need, pick, state.assigned[row])
        )
        load = state.load.at[jnp.where(need, pick, jnp.int32(c_pad))].add(
            jnp.where(need, w, 0)
        )
        overflowed = state.overflowed + jnp.where(need & ~any_fit, 1, 0)
        return PackState(assigned, load, overflowed), None

    state, _ = lax.scan(step, state, proc_order)
    moved = jnp.sum(
        rows_real & (cur >= 0) & (state.assigned != cur),
        dtype=jnp.int32,
    )
    return (
        state.assigned,
        state.load[:c_pad],
        moved,
        state.overflowed,
        state.overflowed > 0,
    )


pack_group_jit = jax.jit(pack_group)


def group_pack_sweep(
    weights: jnp.ndarray,      # (P_pad,) int32 BASE weights
    capacities: jnp.ndarray,   # (C_pad,) int32
    current: jnp.ndarray,      # (P_pad,) int32
    proc_order: jnp.ndarray,   # (P_pad,) int32 (scale-invariant, host-built)
    alive_masks: jnp.ndarray,  # (S, C_pad) one consumer-liveness row per
                               # candidate ("k consumers" = first k alive)
    scale_pcts: jnp.ndarray,   # (S,) int32 weight scale, percent (lag
                               # growth scenarios; capacities stay fixed)
    p_real: jnp.ndarray,       # scalar int32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The autoscale sweep: N candidate consumer counts × M lag scenarios
    evaluated as ONE vmapped dispatch — the "how many consumers do I need"
    cost curve in a single device round-trip, exactly the way the broker
    what-if sweep batches its liveness scenarios. Returns per-candidate
    ``(moved (S,), overflowed (S,), infeasible (S,), load (S, C_pad))``.

    Scaled weights floor at 1 on real rows (a sub-100% scale must not zero
    a partition's cost — an owned partition always occupies capacity), and
    the host-built ``proc_order`` is shared by every scenario: positive
    scaling preserves the descending-weight order even where integer
    division collapses distinct weights into ties.
    """
    p_pad = weights.shape[0]
    rows_real = jnp.arange(p_pad, dtype=jnp.int32) < p_real

    def one(alive, scale):
        w = (weights * scale) // 100
        w = jnp.maximum(w, jnp.where(rows_real, 1, 0))
        assigned, load, moved, overflowed, infeasible = pack_group(
            w, capacities, current, proc_order, alive, p_real
        )
        return moved, overflowed, infeasible, load

    return jax.vmap(one)(alive_masks, scale_pcts)


group_pack_sweep_jit = jax.jit(group_pack_sweep)
