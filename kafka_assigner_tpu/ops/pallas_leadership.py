"""Pallas TPU kernel for the leadership-ordering hot loop.

The leadership pass (``ops/assignment.py:leadership_order``) is inherently
sequential — each partition's choice reads counters the previous partition
wrote (``KafkaAssignmentStrategy.java:218-237``) — so under XLA it runs as a
``lax.scan`` whose per-step fixed overhead dominates at headline scale
(~200k partitions). This kernel removes that overhead the TPU-native way:

- the counter table (N_pad × RF int32, ≤ ~100 KB at 8k brokers) lives in
  VMEM for the whole call, updated in place via ``input_output_aliases``
  (the enclosing ``lax.scan`` over topics carries it between calls — the
  cross-topic Context semantics);
- the grid walks partition *blocks* sequentially, so only one
  (BLOCK_P, RF) tile of candidates/outputs is VMEM-resident at a time —
  arbitrarily large topics never exceed VMEM;
- within a block, a ``fori_loop`` walks partitions; the RF² candidate scan
  is fully unrolled (1, RF) row-vector math (Mosaic rejects scalar VMEM
  stores — see the kernel comment) — no per-step XLA dispatch, no buffer
  shuffling.

Semantics are bit-identical to ``leadership_order`` (differential-tested in
interpret mode). Engaged only when the solver passes ``use_pallas=True``
(TpuSolver reads ``KA_PALLAS_LEADERSHIP=1`` per call; the flag participates
in the jit cache key as a static argument). The vmapped what-if sweep never
engages it (batching aliased pallas buffers is not exercised).

Status history: compile-proven chipless in round 3 (``TPU_AOT_r03.log``
stage 6); DELETED at the end of round 5 under its pre-registered
keep-or-kill rule after 210 failed tunnel probes; RESTORED hours later when
the revived tunnel produced the measurement the rule asked for
(``PALLAS_POSTHUMOUS_r05.json`` via ``scripts/pallas_posthumous_onchip.py``):
at the giant leadership shape (P=204800, RF=3, N_pad=5120) on a real v5e the
kernel is bit-identical to the native oracle and **3.3× faster than the
equivalent XLA scan** (1464.9 ms vs 4899.2 ms median) — but 170× slower
than the host C++ pass (8.6 ms), so it stays opt-in and the host-native
pass (``native/leadership.py``) remains the production default.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

BIG = 0x3FFFFFFF
BLOCK_P = 512


def _compiler_params_cls(pltpu):
    # jax>=0.5 renamed TPUCompilerParams -> CompilerParams
    for attr in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, attr, None)
        if cls is not None:
            return cls
    raise RuntimeError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams; unsupported jax version for the Pallas "
        "leadership kernel"
    )


def _kernel(jhash_ref, cand_ref, count_ref, counters_in_ref, out_ref, counters_ref):
    # counters_in_ref and counters_ref (the output) are aliased — one VMEM
    # buffer persisting across the sequential partition-block grid; all
    # reads/writes go through the output ref.
    #
    # Mosaic constraint (found by the round-3 chipless AOT compile,
    # TPU_AOT_r03.log): scalar stores to VMEM are rejected, and scalar
    # element loads are fragile. Everything here therefore moves in (1, RF)
    # ROW vectors — dynamic-row loads/stores via pl.ds — with scalars only
    # as register values extracted by masked reductions. Interpret mode runs
    # the identical formulation.
    del counters_in_ref
    from jax.experimental import pallas as pl

    p_block, rf = cand_ref.shape
    jh = jhash_ref[0]
    iota = jnp.arange(rf, dtype=jnp.int32)  # (RF,) register vector

    def per_partition(p, _):
        count_row = count_ref[pl.ds(p, 1), :]  # (1, 1)
        count = jnp.sum(count_row.astype(jnp.int32))
        cand_row = cand_ref[pl.ds(p, 1), :][0]  # (RF,)
        alive = iota < count  # (RF,) bool
        out_vec = jnp.full((rf,), -1, jnp.int32)

        for r in range(rf):  # slot loop, static
            # per-partition m = count - r (reference semantics; see
            # ops/assignment.py order_one)
            m = jnp.maximum(count - jnp.int32(r), 1)
            start = jh % m
            # rank of cand_i among remaining candidates (ascending ids):
            # (RF, RF) broadcast compare, row-sum — all register math
            less = alive[None, :] & (cand_row[None, :] < cand_row[:, None])
            k = jnp.sum(less.astype(jnp.int32), axis=1)
            rot = (k + start) % m
            # counters[cand_i, r] for each i: RF dynamic-row loads, static
            # column r extracted by masked sum (no scalar element access)
            cnt = jnp.zeros((rf,), jnp.int32)
            col = (iota == r).astype(jnp.int32)  # (RF,) one-hot column mask
            for i in range(rf):
                ci = jnp.sum(jnp.where(iota == i, cand_row, 0))
                row = counters_ref[pl.ds(ci, 1), :][0]
                cnt = jnp.where(iota == i, jnp.sum(row * col), cnt)
            key = jnp.where(alive, cnt * m + rot, jnp.int32(BIG))
            # int argmin via min + first-matching-index (mosaic's argmin
            # lowers float-only). Keys are distinct among alive candidates
            # (ranks are a permutation and cnt*m+rot < BIG by the
            # context_to_array counter bound), so when any candidate is
            # alive the minimum is unique. When none is (padding row or
            # slot r >= count) every key is BIG and best_i lands on 0,
            # selecting cand_row[0]; that is safe NOT because of the index
            # but because every effect below is masked: the out_vec write
            # and the counter bump are both gated on valid_slot (the RMW
            # adds 0), and `alive` is already all-false.
            min_key = jnp.min(key)
            first = jnp.min(jnp.where(key == min_key, iota, jnp.int32(rf)))
            best_i = first.astype(jnp.int32)
            valid_slot = jnp.int32(r) < count
            chosen = jnp.sum(jnp.where(iota == best_i, cand_row, 0))
            out_vec = jnp.where(
                (iota == r) & valid_slot, chosen, out_vec
            )
            # counter RMW as a whole-row vector op; bump is 0 when the slot
            # is padding, so whichever row `chosen` names is left unchanged
            crow = counters_ref[pl.ds(chosen, 1), :]
            bump = (col * jnp.where(valid_slot, 1, 0))[None, :]
            counters_ref[pl.ds(chosen, 1), :] = crow + bump
            alive = alive & (iota != best_i)

        out_ref[pl.ds(p, 1), :] = out_vec[None, :]
        return 0

    lax.fori_loop(0, p_block, per_partition, 0)


def leadership_order_pallas(
    acc_nodes: jnp.ndarray,   # (P, RF) broker indices (complete rows)
    acc_count: jnp.ndarray,   # (P,)
    counters: jnp.ndarray,    # (N_pad, RF) Context slab
    jhash: jnp.ndarray,       # scalar
    rf: int,
    interpret: bool | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in replacement for ``leadership_order`` backed by the kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = should_interpret()
    p = acc_nodes.shape[0]
    block = min(BLOCK_P, p)
    # Pad the partition axis up to a block multiple (p_pad is a multiple of
    # 8, not necessarily of BLOCK_P): padded rows carry count 0, so every
    # slot is masked (out = -1, counter writes add 0) — same inertness
    # contract as the solver's own padded rows.
    p_grid = -(-p // block) * block
    # -1 padding rows index counters row 0 harmlessly (valid_slot masks the
    # write); clamp for safety.
    cand = jnp.maximum(acc_nodes, 0).astype(jnp.int32)
    count_col = acc_count.astype(jnp.int32).reshape(p, 1)
    if p_grid != p:
        cand = jnp.pad(cand, ((0, p_grid - p), (0, 0)))
        count_col = jnp.pad(count_col, ((0, p_grid - p), (0, 0)))
    jh = jnp.asarray(jhash, jnp.int32).reshape(1)

    ordered, counters_out = pl.pallas_call(
        _kernel,
        grid=(p_grid // block,),
        out_shape=(
            jax.ShapeDtypeStruct((p_grid, rf), jnp.int32),    # out
            jax.ShapeDtypeStruct(counters.shape, jnp.int32),  # counters alias
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),            # jhash scalar
            pl.BlockSpec((block, rf), lambda i: (i, 0)),      # cand tile
            pl.BlockSpec((block, 1), lambda i: (i, 0)),       # count tile
            pl.BlockSpec(counters.shape, lambda i: (0, 0)),   # counters whole
        ],
        out_specs=(
            pl.BlockSpec((block, rf), lambda i: (i, 0)),
            pl.BlockSpec(counters.shape, lambda i: (0, 0)),
        ),
        input_output_aliases={3: 1},
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("arbitrary",),  # sequential grid: counters carry
        ),
        interpret=interpret,
    )(
        jh,
        cand,
        count_col,
        counters.astype(jnp.int32),
    )
    return ordered[:p], counters_out


def pallas_leadership_enabled() -> bool:
    """Opt-in until validated on real TPU hardware (see module docstring)."""
    from ..utils.env import env_bool

    return env_bool("KA_PALLAS_LEADERSHIP")


def should_interpret() -> bool:
    """Interpret (pure-python) mode on the CPU backend only.

    Public-API check (``jax.default_backend()`` — the tunneled chip's
    experimental plugin registers as ``axon`` but the default backend
    canonicalizes to ``tpu``, verified on hardware 2026-07-31). Any other
    accelerator attempts the real Mosaic lowering and fails LOUDLY if
    unsupported — deliberately, because the silent alternative is
    interpret-mode emulation of a ~200k-step sequential loop, an
    orders-of-magnitude slowdown masquerading as the opt-in fast path."""
    return jax.default_backend() == "cpu"
