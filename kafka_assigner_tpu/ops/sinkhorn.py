"""Capacity-constrained entropic transport (Sinkhorn/Dykstra iterations).

The reference sidesteps every global-balance question with greedy first-fit —
and demonstrably dead-ends on some of them (its fresh placement of a
50-partition topic over 10 brokers/5 racks fails outright; see
``KafkaAssignmentStrategy.java:29-30`` and tests). Here the relaxed problem —
spread ``row_target`` units per partition over nodes with per-node caps,
preferring low-cost cells — is solved as an entropic transport:

    X = diag(u) · exp(-C/eps) · diag(v),  row sums == row_target,
                                          col sums <= col_cap.

Row steps scale exactly; column steps clamp multiplicatively (Dykstra-style
for the inequality marginal). Everything is elementwise over a (P, N) block
plus row/col reductions, so under ``jit`` with a partition-axis sharding the
column sums become ``psum``-style cross-shard reductions XLA inserts
automatically — the blockwise-over-the-long-axis structure that ring
attention uses for sequence length, applied to the partition axis
(SURVEY.md §5).

Uses: relaxed what-if scoring (movement lower bounds without integral
solves) and fresh-assignment seeding (``solvers/tpu.py:fresh_assignment``).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def capacity_sinkhorn(
    cost: jnp.ndarray,        # (P, N) cell costs; use BIG/inf for forbidden
    row_target: jnp.ndarray,  # (P,) units to place per partition (RF, 0 for pad)
    col_cap: jnp.ndarray,     # (N,) per-node capacity (0 for dead/padded)
    eps: float = 0.05,
    iters: int = 64,
) -> jnp.ndarray:
    """Return the transport plan X (P, N) after ``iters`` row/col sweeps."""
    logk = -cost / eps
    logk = jnp.where(jnp.isfinite(logk), logk, -jnp.inf)
    log_row_target = jnp.where(
        row_target > 0, jnp.log(jnp.maximum(row_target.astype(cost.dtype), 1e-30)), -jnp.inf
    )
    log_col_cap = jnp.where(
        col_cap > 0, jnp.log(jnp.maximum(col_cap.astype(cost.dtype), 1e-30)), -jnp.inf
    )

    def sweep(carry, _):
        log_u, log_v = carry
        # Row scaling (exact marginal): u = row_target / (K v).
        row_lse = jax.nn.logsumexp(logk + log_v[None, :], axis=1)
        log_u = log_row_target - row_lse
        log_u = jnp.where(jnp.isfinite(log_u), log_u, -jnp.inf)
        # Column clamping (inequality marginal): v *= min(1, cap / (uK)).
        col_lse = jax.nn.logsumexp(logk + log_u[:, None], axis=0)
        excess = log_col_cap - (col_lse + log_v)
        log_v = log_v + jnp.minimum(excess, 0.0)
        log_v = jnp.where(jnp.isfinite(log_v), log_v, -jnp.inf)
        return (log_u, log_v), None

    p, n = cost.shape
    init = (
        jnp.zeros(p, dtype=cost.dtype),
        jnp.zeros(n, dtype=cost.dtype),
    )
    (log_u, log_v), _ = lax.scan(sweep, init, None, length=iters)
    x = jnp.exp(log_u[:, None] + logk + log_v[None, :])
    return jnp.where(jnp.isfinite(x), x, 0.0)


def movement_estimate(
    transport: jnp.ndarray,   # (P, N) plan from capacity_sinkhorn
    sticky_mask: jnp.ndarray,  # (P, N) True where the cell is a current replica
    row_target: jnp.ndarray,
) -> jnp.ndarray:
    """Relaxed moved-replica estimate: mass NOT retained on current replicas.

    NOT a sound lower bound: the entropic regularizer bleeds ``~exp(-1/eps)``
    mass off zero-cost cells even when perfect retention is feasible, so the
    estimate sits slightly above the LP optimum at practical eps. Use it as a
    cheap *ranking* signal for wide what-if scans (relative ordering is what
    survives the entropy smoothing), then confirm the shortlist with exact
    solves.
    """
    retained = jnp.sum(jnp.where(sticky_mask, transport, 0.0))
    return jnp.sum(row_target) - retained


def relaxed_movement_sweep(
    currents: jnp.ndarray,     # (B, P_pad, L) broker index or -1, per topic
    p_reals: jnp.ndarray,      # (B,)
    alive_masks: jnp.ndarray,  # (S, N_pad) one liveness mask per scenario
    rfs: jnp.ndarray | None = None,  # (B,) per-topic RF
    n: int = 0,
    rf: int = 0,
    eps: float = 0.05,
    iters: int = 24,
) -> jnp.ndarray:
    """(S,) relaxed movement estimates for S broker-removal scenarios.

    The cheap front half of a wide what-if scan: one entropic transport per
    (scenario, topic) instead of an exact combinatorial solve — no integral
    rounding, no rack constraints, just movement-cost mass balance under node
    capacities. Rack feasibility and exact movement come from the exact sweep
    (``ops.assignment.whatif_sweep``) run on the shortlist.
    """
    p_pad = currents.shape[1]
    rows = jnp.arange(p_pad, dtype=jnp.int32)
    if rfs is None:
        rfs = jnp.full(currents.shape[0], rf, dtype=jnp.int32)

    def one_scenario(alive):
        n_alive = jnp.maximum(jnp.sum(alive[:n].astype(jnp.int32)), 1)

        def one_topic(carry, inp):
            current, p_real, rf_t = inp
            real_row = rows < p_real
            cap = (p_real * rf_t + n_alive - 1) // n_alive
            sticky = (
                jnp.zeros((p_pad, alive.shape[0] + 1), dtype=bool)
                .at[jnp.repeat(rows[:, None], current.shape[1], 1),
                    jnp.where(current >= 0, current, alive.shape[0])]
                .set(True)[:, :-1]
            )
            sticky = sticky & alive[None, :]
            allowed = real_row[:, None] & alive[None, :]
            cost = jnp.where(allowed, 1.0 - sticky.astype(jnp.float32), jnp.inf)
            row_target = jnp.where(real_row, rf_t.astype(jnp.float32), 0.0)
            col_cap = jnp.where(alive, cap.astype(jnp.float32), 0.0)
            x = capacity_sinkhorn(cost, row_target, col_cap, eps=eps, iters=iters)
            return carry + movement_estimate(x, sticky, row_target), None

        total, _ = lax.scan(
            one_topic, jnp.float32(0.0), (currents, p_reals, rfs)
        )
        return total

    return jax.vmap(one_scenario)(alive_masks)


relaxed_movement_sweep_jit = jax.jit(
    relaxed_movement_sweep, static_argnames=("n", "rf", "eps", "iters")
)


def topk_candidates(
    transport: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-partition top-k nodes by transported mass (descending) — the seed
    candidate lists fed to the exact sticky/spread kernels for rounding."""
    vals, idx = lax.top_k(transport, k)
    return idx.astype(jnp.int32), vals
