from .assignment import AssignState, leadership_order, solve_assignment

__all__ = ["AssignState", "solve_assignment", "leadership_order"]
