"""Cluster-metadata backends — the pluggable replacement for the reference's
ZooKeeper layer (L3: ``ZkUtils`` reads at ``KafkaAssignmentGenerator.java:103-129,
138-164, 189-250``, connection at ``:273-276``).

The reference hardwires one backend (live ZooKeeper via ZkClient, 10 s
timeouts) and therefore has no hermetic test path at all (SURVEY.md §4). Here
the backend is a protocol with three implementations:

- :mod:`snapshot`     — JSON cluster-snapshot file (hermetic/offline; used by
                        tests and what-if sweeps);
- :mod:`zk`           — live ZooKeeper bridge (gated on ``kazoo``);
- :mod:`kafka_admin`  — Kafka AdminClient bridge (gated on a kafka client lib).

``open_backend`` dispatches on the connect string, keeping the reference's
single ``--zk_string`` flag surface: ``file://...``/``*.json`` opens a
snapshot, anything else a live ZK quorum.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import (
    Dict, Iterator, List, Mapping, NamedTuple, Optional, Protocol,
    Sequence, Tuple,
)


@dataclass(frozen=True)
class BrokerInfo:
    """One live broker: id/host/port and optional rack, as read from broker
    metadata (``KafkaAssignmentGenerator.java:116-126``)."""

    id: int
    host: str
    port: int
    rack: Optional[str] = None


class PartitionTraffic(NamedTuple):
    """One partition's traffic/lag observation, as the cluster-health
    plane ingests it (ISSUE 11): produce/consume byte rates and the worst
    consumer-group lag. Backends without real meters serve the
    deterministic synthetic series (``obs/health.py:
    synthetic_partition_traffic``) so the scrape surface, the
    traffic-weighted objective work, and the ``/recommendations`` envelope
    have stable inputs everywhere — ``supports_traffic()`` tells consumers
    which kind they are looking at."""

    in_bytes: float   # produced bytes/s into this partition
    out_bytes: float  # consumed bytes/s out of this partition
    lag: int          # worst consumer-group lag, in messages


class GroupMember(NamedTuple):
    """One consumer-group member, as the consumer-group workload family
    ingests it (ISSUE 13): a stable member id and a consumption-capacity
    estimate in weight units/s (the same units as the lag column the
    packing solve weighs partitions by). ``capacity <= 0`` means unknown —
    the encoder substitutes the documented fair-share default
    (``groups/encode.py``)."""

    member_id: str
    capacity: float = 0.0


class ConsumerGroupState(NamedTuple):
    """One consumer group's packing problem, backend-normalized: members,
    the current partition→member ownership, and per-partition lag (the
    default weight column). ``assignment`` maps ``topic -> partition ->
    member_id`` (``None`` = currently unowned); ``lags`` maps ``topic ->
    partition -> messages``. Partitions may appear in ``lags`` without an
    owner and vice versa — the encoder reconciles both against the
    caller's partition universe."""

    group: str
    members: Tuple[GroupMember, ...]
    assignment: Dict[str, Dict[int, Optional[str]]]
    lags: Dict[str, Dict[int, int]]


class PartitionState(NamedTuple):
    """One partition's convergence-relevant state, as the execution engine
    polls it (ISSUE 7): the assigned replica list and the in-sync subset.
    Backends without ISR visibility (snapshot files, old admin clients)
    report ``isr == replicas`` — their notion of "assigned" IS "applied",
    so the weaker signal is still truthful for convergence."""

    replicas: List[int]
    isr: List[int]


class MetadataBackend(Protocol):
    """The metadata reads L4 performs, lifted verbatim from the reference's
    ZkUtils usage (``KafkaAssignmentGenerator.java:106,114,163``).

    ``rack_blind``: True when the backend structurally CANNOT report broker
    racks (as opposed to a cluster that genuinely has none configured — a
    rackless ZK cluster reports ``rack=None`` per broker and is not blind).
    Plan-producing CLI modes refuse to run on a blind backend unless
    ``--disable_rack_awareness`` makes the opt-out explicit."""

    rack_blind: bool = False

    def brokers(self) -> List[BrokerInfo]: ...

    def all_topics(self) -> List[str]: ...

    def partition_assignment(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, List[int]]]: ...

    def fetch_topics(
        self, topics: Sequence[str], missing: str = "raise"
    ) -> Iterator[Tuple[str, Dict[int, List[int]]]]:
        """Streaming variant of :meth:`partition_assignment`: yield
        ``(topic, {partition: [replica ids]})`` per input entry, in input
        order, as results become available — live backends pipeline the
        underlying reads (``KA_ZK_PIPELINE``) so callers can overlap
        downstream work (host encode) with the remaining round-trips.
        Offline backends yield from memory.

        ``missing="skip"`` is the graceful-degradation contract (ISSUE 5):
        a topic the backend cannot resolve — deleted between the topic
        listing and the metadata read — yields ``(topic, None)`` instead of
        raising, and the stream keeps flowing; callers under
        ``--failure-policy best-effort`` record and skip those entries.
        The default ``"raise"`` keeps the strict fail-fast behavior.

        The body below is a real default, not a stub: a third-party backend
        that explicitly subclasses this Protocol without overriding it
        inherits a correct (non-streaming) implementation over
        :meth:`partition_assignment`. Pure duck-typed backends without the
        method at all are handled by callers via ``getattr`` fallback
        (``generator.stream_initial_assignment``)."""
        import sys

        topics = list(topics)
        if missing == "skip":
            try:
                assignment = self.partition_assignment(topics)
            except Exception as batch_err:
                # The generic default cannot know the backend's missing-
                # topic error class, so probe per topic — but a backend
                # where NOTHING resolves is a transport outage, not a
                # cluster with every topic deleted: re-raise the original
                # error so strict AND best-effort report ingest failure
                # instead of a silent near-empty "degraded" plan.
                assignment = {}
                for t in dict.fromkeys(topics):
                    try:
                        assignment.update(self.partition_assignment([t]))
                    except Exception as per_topic_err:
                        print(
                            f"kafka-assigner: topic {t!r} unresolvable "
                            f"({type(per_topic_err).__name__}: "
                            f"{per_topic_err}); treating as vanished",
                            file=sys.stderr,
                        )
                if not assignment:
                    raise batch_err
            for t in topics:
                yield t, assignment.get(t)
            return
        assignment = self.partition_assignment(topics)
        for t in topics:
            yield t, assignment[t]

    # -- watch surface (ISSUE 8) ------------------------------------------

    def supports_watches(self) -> bool:
        """True when this backend can feed the resident daemon's
        watch-driven incremental re-encode: armed reads
        (``watch_topic_list`` / ``watch_brokers`` / ``watch_topic`` /
        ``fetch_topics(..., watch=True)``), ``poll_watch_events`` and
        ``session_generation``. Default False: a watchless backend
        (snapshots, AdminClient, kazoo) still serves the daemon — it
        degrades to interval-only full resync, identical responses, more
        metadata I/O. The live ZooKeeper backend overrides this when the
        in-tree wire client is underneath (``io/zk.py``)."""
        return False

    # -- traffic/lag surface (ISSUE 11) -----------------------------------

    def supports_traffic(self) -> bool:
        """True when this backend reports REAL per-partition traffic/lag
        observations from :meth:`fetch_partition_traffic`. Default False:
        the deterministic synthetic fallback is in use — still a valid
        scrape series (stable, skew-shaped), but a dashboard must not
        mistake it for cluster truth, so the daemon surfaces this flag in
        ``/state``."""
        return False

    def fetch_partition_traffic(
        self, partitions: Mapping[str, Sequence[int]]
    ) -> Dict[str, Dict[int, PartitionTraffic]]:
        """Per-partition traffic/lag observations for the given
        ``{topic: [partition ids]}`` map (the caller — the daemon
        supervisor — already holds the partition list in its cache, so
        this hook never re-reads metadata). Real default, not a stub: the
        deterministic synthetic series (``obs/health.py``), which any
        backend without meters inherits. Implementations with real
        sources (JMX bridges, AdminClient consumer-group offsets)
        override this AND :meth:`supports_traffic`. Partial-map contract:
        the CALLER does no synthetic fill — a topic/partition absent from
        the returned map simply gets no scrape series — so a backend that
        wants synthetic values for its unmetered partitions must merge
        them itself (``io/snapshot.py`` does exactly that)."""
        from ..obs.health import synthetic_partition_traffic

        return synthetic_partition_traffic(partitions)

    # -- consumer-group surface (ISSUE 13) ---------------------------------

    def supports_groups(self) -> bool:
        """True when this backend reports REAL consumer-group state from
        :meth:`fetch_consumer_groups`. Default False — and unlike the
        traffic hook there is NO silent synthetic fallback here: a packing
        plan against invented membership is an operator lie, so callers
        must either refuse loudly (the default contract) or take the
        deterministic synthetic family through an EXPLICIT opt-in
        (``ka-groups --synthetic`` / the ``synthetic`` request param),
        which stamps ``groups_real: false`` into every envelope."""
        return False

    def fetch_consumer_groups(
        self, groups: Optional[Sequence[str]] = None
    ) -> Dict[str, ConsumerGroupState]:
        """Consumer-group membership + current ownership + per-partition
        lag for the named groups (all groups when ``None``). The default
        is a LOUD REFUSAL, not a stub and not a synthetic stand-in: a
        backend that cannot see consumer groups must say so
        (``IngestError``) rather than let synthetic packing inputs
        masquerade as cluster truth. Implementations: the snapshot
        backend's ``groups`` section (hermetic), the AdminClient bridge
        when the client carries the whole group-offset chain (real lag,
        PR 11's ``_real_lags`` machinery)."""
        from ..errors import IngestError

        raise IngestError(
            f"{type(self).__name__} cannot read consumer groups (no group "
            "membership/offset surface on this backend); use a snapshot "
            "with a \"groups\" section, a Kafka AdminClient with consumer-"
            "group offset support, or opt into the deterministic "
            "synthetic family explicitly (--synthetic)"
        )

    # -- plan execution surface (ISSUE 7) ---------------------------------

    def supports_execution(self) -> bool:
        """True when this backend can WRITE a reassignment and report
        convergence state. Default False: a read-only backend stays safe,
        and ``ka-execute`` refuses it up front with a clear error instead
        of failing mid-plan."""
        return False

    def apply_assignment(
        self, moves: Dict[str, Dict[int, List[int]]]
    ) -> None:
        """Submit one wave of the reassignment: ``{topic: {partition:
        [target replicas]}}``. MUST be idempotent — the engine resubmits a
        wave after a crash or a dropped write, and submitting an
        already-applied target must be a no-op (set-to-same-value
        semantics). Transport failures raise ``ConnectionError``/
        ``OSError``/``ZkWireError``; the engine then reads the state back
        and decides (the write-safety rule), never blindly replays."""
        from ..errors import ExecuteError

        raise ExecuteError(
            f"{type(self).__name__} cannot execute reassignments (read-only "
            "metadata backend)"
        )

    def read_assignment_state(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, PartitionState]]:
        """Convergence-state poll: per topic, per partition, the assigned
        replicas and the in-sync subset. Topics the backend cannot resolve
        are simply absent from the result (the engine treats absence as
        not-converged / a verify mismatch, whichever phase asks).

        Real default over the streaming read surface: backends with no ISR
        visibility inherit ``isr == replicas`` (see
        :class:`PartitionState`)."""
        out: Dict[str, Dict[int, PartitionState]] = {}
        for t, parts in self.fetch_topics(
            list(dict.fromkeys(topics)), missing="skip"
        ):
            if parts is None:
                continue
            out[t] = {
                p: PartitionState(list(r), list(r))
                for p, r in parts.items()
            }
        return out

    def close(self) -> None: ...


def open_backend(connect_string: str) -> MetadataBackend:
    """Open a metadata backend from a connect string.

    ``file:///path.json`` or a path ending in ``.json`` → hermetic snapshot;
    ``kafka://host:port,...`` → Kafka AdminClient bridge; otherwise treated as
    a ZooKeeper quorum string (``host:port,...``), the reference's only mode
    (``KafkaAssignmentGenerator.java:273-276``).
    """
    if connect_string.startswith("file://"):
        from .snapshot import SnapshotBackend

        return SnapshotBackend(connect_string[len("file://"):])
    if connect_string.endswith(".json"):
        from .snapshot import SnapshotBackend

        return SnapshotBackend(connect_string)
    if connect_string.startswith("kafka://"):
        from .kafka_admin import KafkaAdminBackend

        return KafkaAdminBackend(connect_string[len("kafka://"):])
    from .zk import ZkBackend

    return ZkBackend(connect_string)
