"""Hermetic JSON cluster-snapshot backend.

The reference has no offline mode — every run needs a live ZooKeeper quorum
(``KafkaAssignmentGenerator.java:273-276``). A snapshot file captures the same
metadata so the CLI, tests, and batched what-if sweeps run without a cluster:

.. code-block:: json

    {
      "brokers": [{"id": 0, "host": "b0", "port": 9092, "rack": "r0"}, ...],
      "topics": {"events": {"0": [0, 1, 2], "1": [1, 2, 3]}}
    }

``rack`` is optional per broker, mirroring ``broker.rack().isDefined()``
(``KafkaAssignmentGenerator.java:122-124``).
"""
from __future__ import annotations

import json
from typing import Dict, Iterator, List, Sequence, Tuple

from ..obs.metrics import counter_add
from .base import BrokerInfo


class SnapshotBackend:
    def __init__(self, path: str) -> None:
        self.path = path
        with open(path, "rb") as f:
            raw = f.read()
        # zk.* is the metadata-op namespace for EVERY backend (obs/metrics
        # docstring): one counter answers "how much metadata I/O" whether
        # the run was live or hermetic.
        counter_add("zk.reads")
        counter_add("zk.bytes", len(raw))
        data = json.loads(raw)
        self._brokers = [
            BrokerInfo(
                id=int(b["id"]),
                host=str(b.get("host", f"broker-{b['id']}")),
                port=int(b.get("port", 9092)),
                rack=b.get("rack"),
            )
            for b in data.get("brokers", [])
        ]
        self._topics: Dict[str, Dict[int, List[int]]] = {
            topic: {int(p): [int(x) for x in replicas] for p, replicas in parts.items()}
            for topic, parts in data.get("topics", {}).items()
        }

    def brokers(self) -> List[BrokerInfo]:
        return list(self._brokers)

    def all_topics(self) -> List[str]:
        return list(self._topics)

    def fetch_topics(
        self, topics: Sequence[str], missing: str = "raise"
    ) -> Iterator[Tuple[str, Dict[int, List[int]]]]:
        """Streaming half of the backend surface, trivially: the snapshot is
        already in memory, so this just yields per input entry in input
        order. Missing topics raise up front, exactly like
        :meth:`partition_assignment` — or yield ``(topic, None)`` under
        ``missing="skip"`` (the best-effort degradation contract, matching
        the live backends)."""
        topics = list(topics)
        if missing != "skip":
            absent = [t for t in topics if t not in self._topics]
            if absent:
                raise KeyError(f"topics not in snapshot: {absent}")
        for t in topics:
            if t not in self._topics:
                yield t, None
                continue
            yield t, {p: list(r) for p, r in self._topics[t].items()}

    def partition_assignment(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, List[int]]]:
        missing = [t for t in topics if t not in self._topics]
        if missing:
            raise KeyError(f"topics not in snapshot: {missing}")
        return {t: {p: list(r) for p, r in self._topics[t].items()} for t in topics}

    def close(self) -> None:
        pass


def write_snapshot(
    path: str,
    brokers: Sequence[BrokerInfo],
    topics: Dict[str, Dict[int, List[int]]],
) -> None:
    """Serialize cluster metadata to a snapshot file (inverse of the loader)."""
    data = {
        "brokers": [
            {
                "id": b.id,
                "host": b.host,
                "port": b.port,
                **({"rack": b.rack} if b.rack is not None else {}),
            }
            for b in brokers
        ],
        "topics": {
            t: {str(p): list(r) for p, r in sorted(parts.items())}
            for t, parts in topics.items()
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        # kalint: disable=KA005 -- snapshot capture file, not a byte-compat plan payload
        json.dump(data, f, indent=1)
