"""Hermetic JSON cluster-snapshot backend.

The reference has no offline mode — every run needs a live ZooKeeper quorum
(``KafkaAssignmentGenerator.java:273-276``). A snapshot file captures the same
metadata so the CLI, tests, and batched what-if sweeps run without a cluster:

.. code-block:: json

    {
      "brokers": [{"id": 0, "host": "b0", "port": 9092, "rack": "r0"}, ...],
      "topics": {"events": {"0": [0, 1, 2], "1": [1, 2, 3]}}
    }

``rack`` is optional per broker, mirroring ``broker.rack().isDefined()``
(``KafkaAssignmentGenerator.java:122-124``).

Plan execution (ISSUE 7): the snapshot backend is also the hermetic test
cluster for the write path. ``apply_assignment`` records submitted moves as
*pending*; each ``read_assignment_state`` poll ticks a deterministic
convergence countdown (``KA_EXEC_SIM_POLLS`` polls per move — the stand-in
for replica catch-up time), after which the move is applied to the
in-memory assignment AND persisted back to the snapshot file (atomic
tmp+rename), so a killed-and-resumed ``ka-execute`` run observes exactly
what a real cluster would: converged waves survive the crash, in-flight
ones do not. The write-seam fault hooks (``write``/``converge`` scopes,
``faults/inject.py``) fire here like on any live backend.
"""
from __future__ import annotations

import json
from typing import Dict, Iterator, List, Sequence, Tuple

from ..faults.inject import active_injector
from ..obs.metrics import counter_add
from .base import (
    BrokerInfo,
    ConsumerGroupState,
    GroupMember,
    PartitionState,
    PartitionTraffic,
)


class SnapshotBackend:
    def __init__(self, path: str) -> None:
        self.path = path
        with open(path, "rb") as f:
            raw = f.read()
        # zk.* is the metadata-op namespace for EVERY backend (obs/metrics
        # docstring): one counter answers "how much metadata I/O" whether
        # the run was live or hermetic.
        counter_add("zk.reads")
        counter_add("zk.bytes", len(raw))
        data = json.loads(raw)
        self._brokers = [
            BrokerInfo(
                id=int(b["id"]),
                host=str(b.get("host", f"broker-{b['id']}")),
                port=int(b.get("port", 9092)),
                rack=b.get("rack"),
            )
            for b in data.get("brokers", [])
        ]
        self._topics: Dict[str, Dict[int, List[int]]] = {
            topic: {int(p): [int(x) for x in replicas] for p, replicas in parts.items()}
            for topic, parts in data.get("topics", {}).items()
        }
        # Optional per-partition traffic/lag observations (ISSUE 11):
        #   "traffic": {"events": {"0": {"in_bytes": 1e6,
        #                                "out_bytes": 2e6, "lag": 40}}}
        # Topics/partitions absent from the section fall back to the
        # deterministic synthetic series, so a partially-metered snapshot
        # still yields a complete scrape surface.
        self._traffic_raw: Dict = dict(data.get("traffic", {}) or {})
        self._traffic: Dict[str, Dict[int, PartitionTraffic]] = {
            t: {
                int(p): PartitionTraffic(
                    in_bytes=float(v.get("in_bytes", 0.0)),
                    out_bytes=float(v.get("out_bytes", 0.0)),
                    lag=int(v.get("lag", 0)),
                )
                for p, v in per.items()
            }
            for t, per in self._traffic_raw.items()
        }
        # Optional consumer-group section (ISSUE 13):
        #   "groups": {"analytics": {
        #       "members": {"c-0": 120.0, "c-1": null},
        #       "assignment": {"events": {"0": "c-0"}},
        #       "lag": {"events": {"0": 500}}}}
        # ``members`` maps member id -> capacity estimate (null/absent =
        # unknown, the encoder's fair-share default applies). Absent
        # section => supports_groups() False and the loud-refusal default
        # from io/base.py stays in force (never synthetic-as-real).
        self._groups_raw: Dict = dict(data.get("groups", {}) or {})
        self._groups: Dict[str, ConsumerGroupState] = {}
        for g, spec in self._groups_raw.items():
            members = tuple(
                GroupMember(str(m), float(c) if c is not None else 0.0)
                for m, c in sorted((spec.get("members") or {}).items())
            )
            assignment = {
                t: {int(p): (str(m) if m is not None else None)
                    for p, m in per.items()}
                for t, per in (spec.get("assignment") or {}).items()
            }
            lags = {
                t: {int(p): int(v) for p, v in per.items()}
                for t, per in (spec.get("lag") or {}).items()
            }
            self._groups[str(g)] = ConsumerGroupState(
                group=str(g), members=members,
                assignment=assignment, lags=lags,
            )
        # Simulated-convergence execution state (module docstring): pending
        # moves and their remaining poll countdowns. Resolved once per
        # backend so a run's fault schedule is coherent.
        self._pending: Dict[Tuple[str, int], List[int]] = {}
        self._pending_polls: Dict[Tuple[str, int], int] = {}
        self._faults = active_injector()

    def brokers(self) -> List[BrokerInfo]:
        return list(self._brokers)

    def all_topics(self) -> List[str]:
        # Sorted, like every other backend (zk/kafka_admin) and the daemon
        # cache: topic ORDER is part of the stdout byte contract, and a
        # file-order listing made daemon and fresh-CLI output disagree for
        # >10 numerically-named topics unless fixtures zero-padded their
        # names (the ISSUE 14 bench workaround, now dropped) — ordering is
        # canonicalized HERE, at the backend boundary, so no consumer ever
        # sees insertion order again.
        return sorted(self._topics)

    def fetch_topics(
        self, topics: Sequence[str], missing: str = "raise"
    ) -> Iterator[Tuple[str, Dict[int, List[int]]]]:
        """Streaming half of the backend surface, trivially: the snapshot is
        already in memory, so this just yields per input entry in input
        order. Missing topics raise up front, exactly like
        :meth:`partition_assignment` — or yield ``(topic, None)`` under
        ``missing="skip"`` (the best-effort degradation contract, matching
        the live backends)."""
        topics = list(topics)
        if missing != "skip":
            absent = [t for t in topics if t not in self._topics]
            if absent:
                raise KeyError(f"topics not in snapshot: {absent}")
        for t in topics:
            if t not in self._topics:
                yield t, None
                continue
            yield t, {p: list(r) for p, r in self._topics[t].items()}

    def partition_assignment(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, List[int]]]:
        missing = [t for t in topics if t not in self._topics]
        if missing:
            raise KeyError(f"topics not in snapshot: {missing}")
        return {t: {p: list(r) for p, r in self._topics[t].items()} for t in topics}

    # -- traffic/lag surface (ISSUE 11) ------------------------------------

    def supports_traffic(self) -> bool:
        """True only when the snapshot file carried a ``traffic`` section
        — a bare metadata snapshot serves the synthetic series and says
        so."""
        return bool(self._traffic)

    def fetch_partition_traffic(self, partitions):
        """Snapshot-recorded observations where present, synthetic
        fallback per absent topic/partition (the backend-hook contract,
        ``io/base.py``)."""
        from ..obs.health import synthetic_partition_traffic

        synth = synthetic_partition_traffic(partitions)
        out = {}
        for topic, parts in partitions.items():
            recorded = self._traffic.get(topic, {})
            out[topic] = {
                int(p): recorded.get(int(p), synth[topic][int(p)])
                for p in parts
            }
        return out

    # -- consumer-group surface (ISSUE 13) ---------------------------------

    def supports_groups(self) -> bool:
        """True only when the snapshot file carried a ``groups`` section —
        a bare metadata snapshot keeps the loud-refusal default (the
        synthetic family is an explicit caller opt-in, never a silent
        fallback)."""
        return bool(self._groups)

    def fetch_consumer_groups(self, groups=None):
        counter_add("zk.reads")
        if not self._groups:
            from ..errors import IngestError

            # Same loud-refusal contract as the io/base.py default: a
            # snapshot with no groups section has nothing real to serve.
            raise IngestError(
                f"snapshot {self.path!r} carries no \"groups\" section; "
                "record one, or opt into the deterministic synthetic "
                "family explicitly (--synthetic)"
            )
        if groups is None:
            return {
                g: st for g, st in sorted(self._groups.items())
            }
        missing = [g for g in groups if g not in self._groups]
        if missing:
            raise KeyError(f"groups not in snapshot: {missing}")
        return {g: self._groups[g] for g in dict.fromkeys(groups)}

    # -- plan execution surface (simulated convergence; module docstring) --

    def supports_execution(self) -> bool:
        return True

    def apply_assignment(
        self, moves: Dict[str, Dict[int, List[int]]]
    ) -> None:
        from ..utils.env import env_int

        # The write seam: `write:i=drop` raises before anything applies;
        # `write:i=lost` acks the call but records nothing (the quorum
        # member died after the ack) — the convergence poll must time out.
        lost = False
        if self._faults is not None:
            lost = self._faults.write_attempt() == "lost"
        counter_add("zk.writes")
        unknown = [t for t in moves if t not in self._topics]
        if unknown:
            raise KeyError(f"topics not in snapshot: {unknown}")
        if lost:
            return
        sim_polls = env_int("KA_EXEC_SIM_POLLS")
        for t, parts in moves.items():
            for p, reps in parts.items():
                key = (t, int(p))
                self._pending[key] = [int(r) for r in reps]
                self._pending_polls[key] = sim_polls
        # Idempotent by construction: resubmitting a move just restarts its
        # countdown; a move already applied re-applies to the same value.

    def read_assignment_state(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, PartitionState]]:
        # `converge:i=stall` freezes ONE poll: countdowns do not tick and
        # already-due moves stay invisible — exactly a busy controller.
        stalled = self._faults is not None and self._faults.converge_poll()
        if not stalled:
            applied = False
            for key in sorted(self._pending_polls):
                if self._pending_polls[key] > 0:
                    self._pending_polls[key] -= 1
                    continue
                t, p = key
                self._topics[t][p] = self._pending.pop(key)
                del self._pending_polls[key]
                applied = True
            if applied:
                self._persist()
        return {
            t: {
                p: PartitionState(list(r), list(r))
                for p, r in self._topics[t].items()
            }
            for t in dict.fromkeys(topics)
            if t in self._topics
        }

    def _persist(self) -> None:
        """Write the applied assignment back to the snapshot file
        (``write_snapshot`` is atomic + fsync'd): a converged wave must
        survive a crash exactly like a real cluster's state does.
        Unwritable snapshots (read-only fixture paths) degrade loudly —
        the in-memory state is still correct for this process."""
        import sys

        try:
            write_snapshot(self.path, self._brokers, self._topics,
                           traffic=self._traffic_raw,
                           groups=self._groups_raw)
        except OSError as e:
            print(
                f"kafka-assigner: snapshot persist failed for "
                f"{self.path!r} ({e}); converged state is in-memory only",
                file=sys.stderr,
            )

    def close(self) -> None:
        pass


def write_snapshot(
    path: str,
    brokers: Sequence[BrokerInfo],
    topics: Dict[str, Dict[int, List[int]]],
    traffic: Dict | None = None,
    groups: Dict | None = None,
) -> None:
    """Serialize cluster metadata to a snapshot file (inverse of the
    loader). Atomic + fsync'd (``utils/atomicwrite.py``): the execution
    engine persists converged waves through this, and a torn or
    un-synced snapshot would be a corrupted "cluster" after a crash."""
    from ..utils.atomicwrite import atomic_write_text

    data = {
        "brokers": [
            {
                "id": b.id,
                "host": b.host,
                "port": b.port,
                **({"rack": b.rack} if b.rack is not None else {}),
            }
            for b in brokers
        ],
        "topics": {
            t: {str(p): list(r) for p, r in sorted(parts.items())}
            for t, parts in topics.items()
        },
    }
    if traffic:
        # Round-trip the optional traffic section (ISSUE 11): a converged
        # wave's persist must not silently strip the cluster's meters.
        data["traffic"] = traffic
    if groups:
        # Same round-trip contract for the consumer-group section
        # (ISSUE 13): execution persists must not strip the groups.
        data["groups"] = groups
    # kalint: disable=KA005 -- snapshot capture file, not a byte-compat plan payload
    atomic_write_text(path, json.dumps(data, indent=1),
                      prefix=".ka_snapshot_")
