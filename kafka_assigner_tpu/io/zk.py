"""Live ZooKeeper bridge — the tpu-framework equivalent of the reference's
``ZkClient``/``ZkUtils`` layer (``KafkaAssignmentGenerator.java:273-276``,
``pom.xml:50-58``).

Reads the same znodes Kafka's ZkUtils reads:
  - ``/brokers/ids/<id>``      → ``{"host":..., "port":..., "rack":...}``
  - ``/brokers/topics``        → topic list
  - ``/brokers/topics/<name>`` → ``{"partitions": {"0": [ids...]}}``

Client selection: ``kazoo`` when installed (battle-tested session handling),
else the in-tree minimal wire client (``io/zkwire.py`` — the read-only jute
subset this tool needs), so live-ZK runs need no third-party dependency at
all. ``KA_ZK_CLIENT={auto,kazoo,wire}`` overrides. The snapshot backend
covers every offline use.
"""
from __future__ import annotations

import json
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..faults.inject import active_injector
from ..obs.metrics import counter_add, gauge_set
from ..obs.trace import span
from .base import BrokerInfo, PartitionState

# Session/connect timeouts follow the reference: new ZkClient(zk, 10000, 10000)
# (KafkaAssignmentGenerator.java:273-274).
ZK_TIMEOUT_S = 10.0

#: Kafka's classic reassignment protocol znode: the controller watches it,
#: executes the replica moves it describes, and deletes it when every
#: partition has caught up — one reassignment in flight at a time.
ADMIN_REASSIGN_PATH = "/admin/reassign_partitions"


def _resolve_endpoint(meta: dict, broker_id: str) -> tuple:
    """Extract (host, port) from a broker znode.

    Kafka ≥0.9 brokers with non-PLAINTEXT or multiple listeners register
    ``host: null`` plus an ``endpoints`` list (``"SSL://host:9093"``); the
    reference resolves via ``broker.getBrokerEndPoint(SecurityProtocol.
    PLAINTEXT)`` and fails loudly when absent
    (``KafkaAssignmentGenerator.java:117,194``). We prefer the top-level
    host, fall back to the first parseable endpoint, and raise rather than
    silently returning an unmatchable empty hostname.
    """
    host = meta.get("host")
    if host:
        return host, int(meta.get("port") or 9092)
    for ep in meta.get("endpoints", []):
        rest = ep.split("://", 1)[-1]
        if ":" in rest:
            h, _, p = rest.rpartition(":")
            if h:
                return h, int(p)
    raise ValueError(
        f"broker {broker_id} has no resolvable host (host=null and no "
        f"parseable endpoints in {meta.get('endpoints')!r})"
    )


class ZkBackend:
    def __init__(self, connect_string: str) -> None:
        from ..utils.env import env_choice

        choice = env_choice("KA_ZK_CLIENT")
        client_cls = None
        if choice in ("auto", "kazoo"):
            try:
                from kazoo.client import KazooClient as client_cls
            except ImportError:
                if choice == "kazoo":
                    raise RuntimeError(
                        "KA_ZK_CLIENT=kazoo but the 'kazoo' package is not "
                        "installed"
                    ) from None
        if client_cls is None:
            from .zkwire import MiniZkClient as client_cls
        # Fault-injection wiring (ISSUE 7 satellite): the in-tree wire
        # client hooks the injector at its own socket seams; any OTHER
        # client (kazoo) gets the backend-level twin hooks here, so the
        # same KA_FAULTS_SPEC schedule fires regardless of client. The
        # write/converge seams are backend-level for every client.
        self._wire = client_cls.__module__.endswith("zkwire")
        self._faults = active_injector()
        self._binj = None if self._wire else self._faults
        if self._binj is not None:
            self._binj.connect_attempt()  # kazoo's connect seam
        self._zk = client_cls(hosts=connect_string, timeout=ZK_TIMEOUT_S)
        self._zk.start(timeout=ZK_TIMEOUT_S)

    @staticmethod
    def _is_nonode(e: Exception) -> bool:
        """True for any client's missing-znode error — the wire client's
        ``NoNodeError`` or kazoo's (matched by name: kazoo may be absent)."""
        return type(e).__name__ == "NoNodeError"

    def _fault_reply(self) -> None:
        """Backend-level ``reply``-scope hook for clients that never expose
        raw frames (kazoo): no-op for the wire client, which injects at the
        socket layer itself (no double-firing). ``getattr``: duck-typed
        harnesses build this backend without ``__init__`` (``__new__`` plus
        a fake client), and they get the plain no-op."""
        binj = getattr(self, "_binj", None)
        if binj is not None:
            binj.backend_reply()

    def _iter_gets(
        self, paths: Sequence[str], missing_ok: bool = False,
        watch: bool = False,
    ) -> Iterator[Tuple[bytes, object]]:
        """``(data, stat)`` per path, in path order — pipelined where the
        client allows it. Wire client: the xid-matched ``iter_get`` window.
        Kazoo: a sliding window of async handles (kazoo pipelines on its own
        connection thread; the window bounds outstanding memory). Anything
        else: serial gets. Under ``missing_ok`` a missing znode yields
        ``None`` at its position instead of raising (graceful degradation,
        ISSUE 5).

        Runs on whatever thread is consuming the iterator (the streaming
        ingest's producer thread) — metrics only, no tracing spans (the span
        stack belongs to the orchestration thread).
        """
        if not paths:
            return
        iter_get = getattr(self._zk, "iter_get", None)
        if iter_get is not None:
            if watch:  # wire client only (supports_watches gates callers)
                yield from iter_get(paths, missing_ok=missing_ok,
                                    watch=True)
            else:
                yield from iter_get(paths, missing_ok=missing_ok)
            return
        get_async = getattr(self._zk, "get_async", None)
        if get_async is not None:
            from ..utils.env import env_int

            window = env_int("KA_ZK_PIPELINE")
            counter_add("zk.pipeline.batches")
            gauge_set("zk.pipeline.in_flight", min(window, len(paths)))
            counter_add(
                "zk.pipeline.rtts_saved",
                len(paths) - -(-len(paths) // window),
            )

            def _resolve(handle):
                try:
                    self._fault_reply()
                    return handle.get(timeout=ZK_TIMEOUT_S)
                except Exception as e:
                    if missing_ok and self._is_nonode(e):
                        return None
                    raise

            handles: deque = deque()
            for path in paths:
                handles.append(get_async(path))
                if len(handles) >= window:
                    yield _resolve(handles.popleft())
            while handles:
                yield _resolve(handles.popleft())
            return
        for path in paths:
            try:
                self._fault_reply()
                yield self._zk.get(path)
            except Exception as e:
                if missing_ok and self._is_nonode(e):
                    yield None
                else:
                    raise

    def _iter_children(
        self, paths: Sequence[str], missing_ok: bool = False
    ) -> Iterator[Optional[List[str]]]:
        """Child listings per path, in path order — the ``getChildren``
        fan-out pipelined through the wire client's xid-matched window
        (``iter_children``; same replay contract as ``iter_get``). Kazoo
        and other duck-typed clients degrade to serial calls (kazoo
        pipelines internally on its connection thread). Under
        ``missing_ok`` a missing znode yields ``None`` at its position."""
        if not paths:
            return
        iter_children = getattr(self._zk, "iter_children", None)
        if iter_children is not None:
            yield from iter_children(paths, missing_ok=missing_ok)
            return
        for path in paths:
            try:
                self._fault_reply()
                yield self._zk.get_children(path)
            except Exception as e:
                if missing_ok and self._is_nonode(e):
                    yield None
                else:
                    raise

    def brokers(self) -> List[BrokerInfo]:
        out = []
        with span("zk/brokers"):
            self._fault_reply()
            children = sorted(self._zk.get_children("/brokers/ids"), key=int)
            counter_add("zk.reads")
            paths = [f"/brokers/ids/{bid}" for bid in children]
            for bid, (raw, _) in zip(children, self._iter_gets(paths)):
                counter_add("zk.reads")
                counter_add("zk.bytes", len(raw))
                meta = json.loads(raw)
                host, port = _resolve_endpoint(meta, bid)
                out.append(
                    BrokerInfo(
                        id=int(bid), host=host, port=port,
                        rack=meta.get("rack"),
                    )
                )
        return out

    def all_topics(self) -> List[str]:
        counter_add("zk.reads")
        self._fault_reply()
        return sorted(self._zk.get_children("/brokers/topics"))

    def fetch_topics(
        self, topics: Sequence[str], missing: str = "raise",
        watch: bool = False,
    ) -> Iterator[Tuple[str, Dict[int, List[int]]]]:
        """Batched topic-metadata fetch: yields ``(topic, {partition:
        [replica ids]})`` per input entry, in input order, as pipelined
        responses arrive — the streaming half of the ``MetadataBackend``
        surface (``io/base.py``). Duplicates are fetched per occurrence,
        like the serial loop. A missing topic — the delete-during-scan race
        — raises the wire client's ``NoNodeError`` (kazoo: its own
        ``NoNodeError``) at that topic's position, or under
        ``missing="skip"`` yields ``(topic, None)`` and keeps streaming
        (the ``--failure-policy best-effort`` degradation path).
        ``watch=True`` (wire client only; the daemon's pipelined resync)
        arms a one-shot data watch per topic read."""
        topics = list(topics)
        paths = [f"/brokers/topics/{topic}" for topic in topics]
        stream = self._iter_gets(paths, missing_ok=(missing == "skip"),
                                 watch=watch)
        for topic, res in zip(topics, stream):
            if res is None:
                counter_add("zk.topics_missing")
                yield topic, None
                continue
            raw, _ = res
            counter_add("zk.reads")
            counter_add("zk.bytes", len(raw))
            meta = json.loads(raw)
            yield topic, {
                int(p): [int(x) for x in replicas]
                for p, replicas in meta.get("partitions", {}).items()
            }

    def partition_assignment(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, List[int]]]:
        out: Dict[str, Dict[int, List[int]]] = {}
        with span("zk/partition_assignment"):
            for topic, parts in self.fetch_topics(topics):
                out[topic] = parts
        return out

    # -- traffic/lag surface (ISSUE 11) ------------------------------------

    def supports_traffic(self) -> bool:
        """ZooKeeper stores topology, not meters: byte rates live in the
        brokers' JMX surface and lag in the consumer coordinators, neither
        reachable over a quorum connection. Always False — the health
        plane serves the deterministic synthetic series for ZK-backed
        clusters and says so, rather than inventing a half-real source."""
        return False

    def fetch_partition_traffic(self, partitions):
        """The synthetic fallback, explicitly: the contract lives on every
        backend even where the real source is structurally absent (module
        rationale in :meth:`supports_traffic`)."""
        from ..obs.health import synthetic_partition_traffic

        return synthetic_partition_traffic(partitions)

    # -- watch surface (ISSUE 8: the daemon's churn feed) ------------------

    TOPICS_PATH = "/brokers/topics"
    BROKERS_PATH = "/brokers/ids"

    def supports_watches(self) -> bool:
        """True when the underlying client speaks the wire watch surface
        (the in-tree MiniZkClient). Kazoo has its own watch machinery, but
        the daemon's poll-driven loop is built on the wire client's explicit
        ``poll_watches``; other clients degrade to interval resync."""
        return all(
            hasattr(self._zk, m)
            for m in ("poll_watches", "session_generation", "ping")
        )

    def session_generation(self) -> int:
        return getattr(self._zk, "session_generation", 0)

    def watch_topic_list(self) -> List[str]:
        """The sorted topic list, arming a one-shot CHILD watch on the
        topics znode (topic created/deleted → NodeChildrenChanged)."""
        counter_add("zk.reads")
        return sorted(self._zk.get_children(self.TOPICS_PATH, watch=True))

    def watch_brokers(self) -> List[str]:
        """The broker-id children, arming a CHILD watch on ``/brokers/ids``
        (broker joined/left → the daemon must fully resync: the cluster
        encoding itself changes)."""
        counter_add("zk.reads")
        return sorted(
            self._zk.get_children(self.BROKERS_PATH, watch=True), key=int
        )

    def watch_topic(self, topic: str) -> Optional[Dict[int, List[int]]]:
        """One topic's partition assignment, arming a one-shot DATA watch
        on its znode (partition reassigned/added → NodeDataChanged). A
        topic deleted between listing and read returns None — the caller
        drops it from the cache, exactly like the best-effort scan."""
        try:
            raw, _ = self._zk.get(
                f"{self.TOPICS_PATH}/{topic}", watch=True
            )
        except Exception as e:
            if self._is_nonode(e):
                return None
            raise
        counter_add("zk.reads")
        counter_add("zk.bytes", len(raw))
        meta = json.loads(raw)
        return {
            int(p): [int(x) for x in replicas]
            for p, replicas in meta.get("partitions", {}).items()
        }

    #: Idle keepalive cadence for the watch-poll loop: a third of the
    #: session timeout, like real ZK clients. Pinging EVERY poll would make
    #: each blocking read return on its own ping reply (~RTT) instead of
    #: pacing at the poll timeout — a busy loop against the quorum.
    PING_INTERVAL_S = ZK_TIMEOUT_S / 3.0

    def poll_watch_events(self, timeout: float = 0.25) -> List[tuple]:
        """Drain watch notifications into normalized daemon events:
        ``("topics", None)`` — the topic set changed (re-list + diff);
        ``("topic", name)`` — one topic's data changed or it was deleted
        (re-read-with-watch tells which); ``("brokers", None)`` — the
        broker set changed (full resync). Unknown paths are ignored."""
        now = time.monotonic()
        if now - getattr(self, "_last_ping", 0.0) >= self.PING_INTERVAL_S:
            self._zk.ping()
            self._last_ping = now
        out: List[tuple] = []
        for ev in self._zk.poll_watches(timeout):
            if ev.path == self.TOPICS_PATH:
                out.append(("topics", None))
            elif ev.path == self.BROKERS_PATH \
                    or ev.path.startswith(self.BROKERS_PATH + "/"):
                out.append(("brokers", None))
            elif ev.path.startswith(self.TOPICS_PATH + "/"):
                rest = ev.path[len(self.TOPICS_PATH) + 1:]
                if "/" not in rest:  # the topic znode itself
                    out.append(("topic", rest))
        return out

    # -- plan execution surface (ISSUE 7) ---------------------------------

    def supports_execution(self) -> bool:
        return True

    def apply_assignment(
        self, moves: Dict[str, Dict[int, List[int]]]
    ) -> None:
        """Submit one wave through Kafka's classic reassignment protocol:
        create ``/admin/reassign_partitions`` carrying the wave's target in
        Kafka's own reassignment JSON; the controller moves the replicas
        and deletes the znode when every partition caught up. One request
        may be in flight at a time, so an existing znode (the previous
        wave's tail, another operator) is WAITED out within the poll
        budget, then ours is created. Idempotent: re-creating the same
        target after a crash re-describes moves the controller has already
        applied (set-to-same-value no-ops)."""
        from ..errors import ExecuteError
        from ..utils.env import env_float
        from .json_io import format_reassignment_json

        payload = format_reassignment_json(
            moves, topic_order=list(moves)
        ).encode("utf-8")
        counter_add("zk.writes")
        # The write seam (faults/inject.py): `drop` raises before anything
        # reaches the quorum; `lost` acks without applying.
        if self._faults is not None \
                and self._faults.write_attempt() == "lost":
            return
        deadline = time.monotonic() + env_float("KA_EXEC_POLL_TIMEOUT")
        interval = env_float("KA_EXEC_POLL_INTERVAL")
        while True:
            if self._zk.exists(ADMIN_REASSIGN_PATH) is None:
                try:
                    self._zk.create(
                        ADMIN_REASSIGN_PATH, payload, makepath=True
                    )
                    return
                except Exception as e:
                    # Lost the create race (another writer, or the
                    # controller re-created state): wait and retry. Any
                    # other error propagates.
                    if type(e).__name__ != "NodeExistsError":
                        raise
            if time.monotonic() >= deadline:
                raise ExecuteError(
                    "a partition reassignment is already in flight "
                    f"({ADMIN_REASSIGN_PATH} never cleared within the poll "
                    "budget); re-run with --resume once it completes"
                )
            time.sleep(
                min(interval, max(0.0, deadline - time.monotonic()))
            )

    def read_assignment_state(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, PartitionState]]:
        """Convergence poll: assigned replicas from the topic znodes plus
        the in-sync subset from the per-partition ``state`` znodes — the
        children fan-out and the state reads both pipelined through the
        xid-matched window. Clusters (or fixtures) without the
        ``partitions/<p>/state`` layout degrade to ``isr == replicas``
        (``missing_ok`` yields ``None`` per absent znode, never an
        error)."""
        unique = list(dict.fromkeys(topics))
        replicas: Dict[str, Dict[int, List[int]]] = {}
        for t, parts in self.fetch_topics(unique, missing="skip"):
            if parts is not None:
                replicas[t] = parts
        present = [t for t in unique if t in replicas]
        # getChildren fan-out, one pipelined window across all topics.
        kid_paths = [f"/brokers/topics/{t}/partitions" for t in present]
        isr: Dict[Tuple[str, int], List[int]] = {}
        keys: List[Tuple[str, int]] = []
        state_paths: List[str] = []
        for t, kids in zip(
            present, self._iter_children(kid_paths, missing_ok=True)
        ):
            for kid in kids or ():
                if not kid.lstrip("-").isdigit():
                    continue
                p = int(kid)
                if p in replicas[t]:
                    keys.append((t, p))
                    state_paths.append(
                        f"/brokers/topics/{t}/partitions/{kid}/state"
                    )
        for (t, p), res in zip(
            keys, self._iter_gets(state_paths, missing_ok=True)
        ):
            if res is None:
                continue
            raw, _ = res
            counter_add("zk.reads")
            counter_add("zk.bytes", len(raw))
            try:
                got = json.loads(raw).get("isr")
            except ValueError:  # kalint: disable=KA008 -- unparsable state znode: the replicas-as-isr fallback below IS the handling
                continue
            if isinstance(got, list):
                isr[(t, p)] = [int(x) for x in got]
        return {
            t: {
                p: PartitionState(
                    list(reps), isr.get((t, p), list(reps))
                )
                for p, reps in parts.items()
            }
            for t, parts in replicas.items()
        }

    def close(self) -> None:
        self._zk.stop()
        self._zk.close()
