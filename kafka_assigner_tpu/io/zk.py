"""Live ZooKeeper bridge — the tpu-framework equivalent of the reference's
``ZkClient``/``ZkUtils`` layer (``KafkaAssignmentGenerator.java:273-276``,
``pom.xml:50-58``).

Reads the same znodes Kafka's ZkUtils reads:
  - ``/brokers/ids/<id>``      → ``{"host":..., "port":..., "rack":...}``
  - ``/brokers/topics``        → topic list
  - ``/brokers/topics/<name>`` → ``{"partitions": {"0": [ids...]}}``

Client selection: ``kazoo`` when installed (battle-tested session handling),
else the in-tree minimal wire client (``io/zkwire.py`` — the read-only jute
subset this tool needs), so live-ZK runs need no third-party dependency at
all. ``KA_ZK_CLIENT={auto,kazoo,wire}`` overrides. The snapshot backend
covers every offline use.
"""
from __future__ import annotations

import json
from typing import Dict, List, Sequence

from ..obs.metrics import counter_add
from ..obs.trace import span
from .base import BrokerInfo

# Session/connect timeouts follow the reference: new ZkClient(zk, 10000, 10000)
# (KafkaAssignmentGenerator.java:273-274).
ZK_TIMEOUT_S = 10.0


def _resolve_endpoint(meta: dict, broker_id: str) -> tuple:
    """Extract (host, port) from a broker znode.

    Kafka ≥0.9 brokers with non-PLAINTEXT or multiple listeners register
    ``host: null`` plus an ``endpoints`` list (``"SSL://host:9093"``); the
    reference resolves via ``broker.getBrokerEndPoint(SecurityProtocol.
    PLAINTEXT)`` and fails loudly when absent
    (``KafkaAssignmentGenerator.java:117,194``). We prefer the top-level
    host, fall back to the first parseable endpoint, and raise rather than
    silently returning an unmatchable empty hostname.
    """
    host = meta.get("host")
    if host:
        return host, int(meta.get("port") or 9092)
    for ep in meta.get("endpoints", []):
        rest = ep.split("://", 1)[-1]
        if ":" in rest:
            h, _, p = rest.rpartition(":")
            if h:
                return h, int(p)
    raise ValueError(
        f"broker {broker_id} has no resolvable host (host=null and no "
        f"parseable endpoints in {meta.get('endpoints')!r})"
    )


class ZkBackend:
    def __init__(self, connect_string: str) -> None:
        from ..utils.env import env_choice

        choice = env_choice("KA_ZK_CLIENT")
        client_cls = None
        if choice in ("auto", "kazoo"):
            try:
                from kazoo.client import KazooClient as client_cls
            except ImportError:
                if choice == "kazoo":
                    raise RuntimeError(
                        "KA_ZK_CLIENT=kazoo but the 'kazoo' package is not "
                        "installed"
                    ) from None
        if client_cls is None:
            from .zkwire import MiniZkClient as client_cls
        self._zk = client_cls(hosts=connect_string, timeout=ZK_TIMEOUT_S)
        self._zk.start(timeout=ZK_TIMEOUT_S)

    def brokers(self) -> List[BrokerInfo]:
        out = []
        with span("zk/brokers"):
            children = sorted(self._zk.get_children("/brokers/ids"), key=int)
            counter_add("zk.reads")
            for bid in children:
                raw, _ = self._zk.get(f"/brokers/ids/{bid}")
                counter_add("zk.reads")
                counter_add("zk.bytes", len(raw))
                meta = json.loads(raw)
                host, port = _resolve_endpoint(meta, bid)
                out.append(
                    BrokerInfo(
                        id=int(bid), host=host, port=port,
                        rack=meta.get("rack"),
                    )
                )
        return out

    def all_topics(self) -> List[str]:
        counter_add("zk.reads")
        return sorted(self._zk.get_children("/brokers/topics"))

    def partition_assignment(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, List[int]]]:
        out: Dict[str, Dict[int, List[int]]] = {}
        with span("zk/partition_assignment"):
            for topic in topics:
                raw, _ = self._zk.get(f"/brokers/topics/{topic}")
                counter_add("zk.reads")
                counter_add("zk.bytes", len(raw))
                meta = json.loads(raw)
                out[topic] = {
                    int(p): [int(x) for x in replicas]
                    for p, replicas in meta.get("partitions", {}).items()
                }
        return out

    def close(self) -> None:
        self._zk.stop()
        self._zk.close()
