"""Kafka AdminClient bridge — modern replacement for ZooKeeper metadata reads
(Kafka ≥ 2.x clusters increasingly deny direct ZK access; the reference
predates this and only speaks ZK, ``pom.xml:50-58``).

Gated on ``confluent_kafka`` or ``kafka-python``; raises a clear error when
neither is installed. Offline runs should use the snapshot backend.

Caveat: confluent-kafka's AdminClient metadata does not expose broker racks,
so that path is **rack-blind** — every broker degenerates to its own rack
(the reference's missing-rack fallback, ``KafkaAssignmentStrategy.java:84-87``)
and rack diversity is no longer guaranteed. The backend advertises this via
``rack_blind=True``: plan-producing CLI modes REFUSE to run on it unless
``--disable_rack_awareness`` makes the opt-out explicit (VERDICT r3 item 7 —
a warning alone let an operator ship a rack-unsafe plan from a tool whose
headline feature is rack awareness). ``brokers()`` still emits the stderr
warning for inspection-only modes; use the zk:// or file:// backends (or
kafka-python, whose ``describe_cluster`` carries racks) when racks matter.
"""
from __future__ import annotations

import sys

from typing import Dict, Iterator, List, Sequence, Tuple

from ..faults.inject import active_injector
from ..obs.metrics import counter_add, hist_ms
from .base import BrokerInfo, PartitionState


class KafkaAdminBackend:
    rack_blind = False  # flipped below when the confluent impl is chosen

    def __init__(self, bootstrap_servers: str) -> None:
        self._impl = None
        self._warned_rack_blind = False
        # Fault-injection hooks (ISSUE 7 satellite): the AdminClient never
        # exposes wire frames, so the backend-level twin hooks fire the
        # same KA_FAULTS_SPEC schedule here — connect at construction,
        # reply per metadata RPC (nonode maps to KeyError, the missing-
        # topic class `_is_unknown_topic` recognizes), write/converge at
        # the execution seams.
        self._faults = active_injector()
        if self._faults is not None:
            self._faults.connect_attempt()
        try:
            from confluent_kafka.admin import AdminClient  # type: ignore

            self._impl = "confluent"
            self.rack_blind = True
            self._admin = AdminClient({"bootstrap.servers": bootstrap_servers})
        except ImportError:
            try:
                from kafka import KafkaAdminClient  # type: ignore

                self._impl = "kafka-python"
                self._admin = KafkaAdminClient(bootstrap_servers=bootstrap_servers)
            except ImportError as e:
                raise RuntimeError(
                    "Kafka AdminClient access requires 'confluent-kafka' or "
                    "'kafka-python'; use a file://cluster.json snapshot for "
                    "offline runs"
                ) from e

    def _fault_reply(self) -> None:
        """Per-RPC ``reply``-scope hook: ``nonode`` becomes ``KeyError``
        (the unknown-topic class), ``drop``/``trunc`` a connection loss."""
        if self._faults is not None:
            self._faults.backend_reply(missing_exc=KeyError)

    def brokers(self) -> List[BrokerInfo]:
        counter_add("zk.reads")  # metadata-op namespace, any backend
        self._fault_reply()
        if self._impl == "confluent":
            with hist_ms("zk.op_ms"):
                md = self._admin.list_topics(timeout=10)
            if not self._warned_rack_blind:
                self._warned_rack_blind = True
                print(
                    "WARNING: confluent-kafka's AdminClient metadata carries "
                    "no broker rack info; every broker is treated as its own "
                    "rack and rack-aware assignment CANNOT guarantee rack "
                    "diversity. Use the zk:// or file:// backend (or install "
                    "kafka-python) when racks matter.",
                    file=sys.stderr,
                )
            return [
                BrokerInfo(id=b.id, host=b.host, port=b.port, rack=None)
                for b in sorted(md.brokers.values(), key=lambda b: b.id)
            ]
        with hist_ms("zk.op_ms"):
            cluster = self._admin.describe_cluster()
        return [
            BrokerInfo(
                id=int(b["node_id"]), host=b["host"], port=int(b["port"]),
                rack=b.get("rack"),
            )
            for b in sorted(cluster["brokers"], key=lambda b: int(b["node_id"]))
        ]

    def all_topics(self) -> List[str]:
        counter_add("zk.reads")
        self._fault_reply()
        if self._impl == "confluent":
            with hist_ms("zk.op_ms"):
                md = self._admin.list_topics(timeout=10)
            return sorted(md.topics)
        with hist_ms("zk.op_ms"):
            names = self._admin.list_topics()
        return sorted(names)

    def partition_assignment(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, List[int]]]:
        counter_add("zk.reads")
        self._fault_reply()
        out: Dict[str, Dict[int, List[int]]] = {}
        if self._impl == "confluent":
            with hist_ms("zk.op_ms"):
                md = self._admin.list_topics(timeout=10)
            for topic in topics:
                tmeta = md.topics[topic]
                out[topic] = {
                    int(p): list(pm.replicas) for p, pm in tmeta.partitions.items()
                }
            return out
        with hist_ms("zk.op_ms"):
            described = self._admin.describe_topics(topics)
        for t in described:
            out[t["topic"]] = {
                int(p["partition"]): [int(r) for r in p["replicas"]]
                for p in t["partitions"]
            }
        return out

    def fetch_topics(
        self, topics: Sequence[str], missing: str = "raise"
    ) -> Iterator[Tuple[str, Dict[int, List[int]]]]:
        """Streaming half of the backend surface. The AdminClient metadata
        call is already a single batched RPC (nothing to pipeline), so this
        fetches once and yields per input entry in input order. Under
        ``missing="skip"`` a topic absent from the batched metadata yields
        ``(topic, None)`` instead of a KeyError (the best-effort
        degradation contract, io/base.py)."""
        topics = list(topics)
        if missing == "skip":
            for t, parts in zip(topics, self._fetch_skip_missing(topics)):
                yield t, parts
            return
        assignment = self.partition_assignment(topics)
        for t in topics:
            yield t, assignment[t]

    @staticmethod
    def _is_unknown_topic(e: Exception) -> bool:
        """Missing-topic errors only (KeyError from the confluent metadata
        map, kafka-python's UnknownTopicOrPartitionError by name) — a
        TRANSPORT failure must re-raise as an ingest failure, never be
        laundered into 'every topic vanished' degraded success."""
        return isinstance(e, KeyError) or "UnknownTopic" in type(e).__name__

    def _fetch_skip_missing(self, topics):
        """The ``missing="skip"`` lane: ONE batched RPC first (strict-cost),
        falling back to per-topic probes only when the batch fails on a
        missing topic. Returns per-input-entry assignments (None = vanished).
        """
        unique = list(dict.fromkeys(topics))
        try:
            assignment = self.partition_assignment(unique)
        except Exception as e:
            if not self._is_unknown_topic(e):
                raise
            assignment = {}
            for t in unique:
                try:
                    assignment.update(self.partition_assignment([t]))
                except Exception as per_topic_err:
                    if not self._is_unknown_topic(per_topic_err):
                        raise
                    print(
                        f"kafka-assigner: topic {t!r} unknown to the "
                        "AdminClient; treating as vanished",
                        file=sys.stderr,
                    )
        return [assignment.get(t) for t in topics]

    # -- traffic/lag surface (ISSUE 11) ------------------------------------

    def supports_traffic(self) -> bool:
        """Real consumer-group LAG only when the WHOLE chain is present:
        group listing, per-group committed offsets, AND an end-offset
        source (``end_offsets`` duck-typed on the admin object or an
        attached consumer — a bare ``KafkaAdminClient`` has none, and a
        True here with no end offsets would report ``traffic_real`` for
        fully synthetic lag, exactly the operator lie this flag exists to
        prevent). Byte rates need JMX, which no AdminClient exposes —
        those stay synthetic either way."""
        return (
            self._impl == "kafka-python"
            and hasattr(self._admin, "list_consumer_groups")
            and hasattr(self._admin, "list_consumer_group_offsets")
            and self._end_offsets_fn() is not None
        )

    def _end_offsets_fn(self):
        """The batched log-end-offset callable (``end_offsets(list[TP])
        -> {TP: offset}``), duck-typed off the admin object itself or an
        attached consumer-style ``_client``; None when neither carries
        one (the common bare-AdminClient case — lag stays synthetic and
        :meth:`supports_traffic` says so)."""
        for holder in (self._admin, getattr(self._admin, "_client", None)):
            fn = getattr(holder, "end_offsets", None)
            if callable(fn):
                return fn
        return None

    def fetch_partition_traffic(self, partitions):
        """Synthetic byte rates always (no JMX over an admin connection);
        the lag column upgraded to real worst-group lag when the client
        carries the consumer-group offset surface. Any failure in the
        duck-typed lag sweep degrades LOUDLY to the synthetic column —
        the health plane must keep scraping through a flaky coordinator."""
        import sys

        from ..obs.health import synthetic_partition_traffic

        out = synthetic_partition_traffic(partitions)
        if not self.supports_traffic():
            return out
        try:
            lags = self._real_lags(partitions)
        except Exception as e:
            print(
                f"kafka-assigner: consumer-group lag sweep failed "
                f"({type(e).__name__}: {e}); serving synthetic lag",
                file=sys.stderr,
            )
            return out
        for topic, per in out.items():
            for p, tr in per.items():
                if (topic, p) in lags:
                    per[p] = tr._replace(lag=lags[(topic, p)])
        return out

    def _real_lags(self, partitions):
        """Worst lag per (topic, partition) over every consumer group the
        AdminClient reports. End offsets are group-independent, so they
        are fetched ONCE as a single batched call over the wanted set —
        per-(group, partition) round trips would make the lag sweep the
        dominant resync cost on exactly the busy clusters it exists
        for."""
        from kafka import TopicPartition  # type: ignore

        wanted = {
            (t, int(p)) for t, parts in partitions.items() for p in parts
        }
        ends_raw = self._end_offsets_fn()(
            [TopicPartition(t, p) for t, p in sorted(wanted)]
        )
        ends = {
            (tp.topic, int(tp.partition)): off
            for tp, off in ends_raw.items() if off is not None
        }
        lags = {}
        groups = [
            g[0] if isinstance(g, tuple) else g
            for g in self._admin.list_consumer_groups()
        ]
        for group in groups:
            offsets = self._admin.list_consumer_group_offsets(group)
            for tp, meta in offsets.items():
                key = (tp.topic, int(tp.partition))
                if key not in wanted or key not in ends:
                    continue
                committed = getattr(meta, "offset", None)
                if committed is None or committed < 0:
                    continue
                lag = max(0, int(ends[key]) - int(committed))
                lags[key] = max(lags.get(key, 0), lag)
        return lags

    # -- consumer-group surface (ISSUE 13) ---------------------------------

    def supports_groups(self) -> bool:
        """Real consumer-group packing inputs need the WHOLE chain the lag
        column needs (:meth:`supports_traffic` — group listing, committed
        offsets, an end-offset source) PLUS group description for
        membership. Anything less keeps the io/base.py loud-refusal
        default: a packing plan over invented members would be
        synthetic-as-real, the exact lie this flag exists to prevent."""
        return self.supports_traffic() and hasattr(
            self._admin, "describe_consumer_groups"
        )

    def fetch_consumer_groups(self, groups=None):
        """Membership from ``describe_consumer_groups`` (duck-typed across
        kafka-python versions: member assignments accepted as parsed
        ``(topic, partitions)`` pairs or skipped when only opaque bytes are
        exposed — an unowned partition is a valid packing input), current
        ownership from those assignments, lag per partition from the same
        batched end-offset sweep PR 11's traffic hook uses. Capacity
        estimates are not observable over an admin connection (they need
        per-member metering); members report ``capacity=0`` (unknown) and
        the encoder's documented fair-share default applies."""
        from ..errors import IngestError
        from .base import ConsumerGroupState, GroupMember

        if not self.supports_groups():
            raise IngestError(
                "this Kafka AdminClient cannot read consumer groups (needs "
                "kafka-python with list/describe_consumer_groups, "
                "list_consumer_group_offsets and an end_offsets source); "
                "use a snapshot with a \"groups\" section or --synthetic"
            )
        self._fault_reply()
        counter_add("zk.reads")
        if groups is None:
            groups = [
                g[0] if isinstance(g, tuple) else g
                for g in self._admin.list_consumer_groups()
            ]
        wanted_groups = list(dict.fromkeys(groups))
        # ONE batched describe for the whole set — the API takes a list,
        # and a per-group RPC would make membership the dominant request
        # cost on group-heavy clusters (same batching rule as the
        # end-offset sweep in _real_lags).
        with hist_ms("zk.op_ms"):
            all_described = self._admin.describe_consumer_groups(
                wanted_groups
            )
        described_of: Dict[str, list] = {g: [] for g in wanted_groups}
        unattributed = False
        for desc in all_described:
            gid = str(getattr(desc, "group", getattr(desc, "group_id", "")))
            if gid:
                described_of.setdefault(gid, []).append(desc)
            else:
                unattributed = True
        if unattributed:
            # A client whose description objects carry no group id:
            # results come back in request order — map positionally.
            described_of = {
                g: [d] for g, d in zip(wanted_groups, all_described)
            }
        out = {}
        for group in wanted_groups:
            members = []
            assignment: Dict[str, Dict[int, str]] = {}
            for desc in described_of.get(group, []):
                for m in getattr(desc, "members", []) or []:
                    member_id = str(getattr(m, "member_id", m))
                    members.append(GroupMember(member_id, 0.0))
                    massign = getattr(m, "member_assignment", None)
                    pairs = getattr(massign, "assignment", None)
                    if not pairs:
                        continue  # opaque/undecoded bytes: ownership unknown
                    for topic, parts in pairs:
                        per = assignment.setdefault(str(topic), {})
                        for p in parts:
                            per[int(p)] = member_id
            # THIS group's lag (not the cross-group worst the traffic hook
            # publishes): committed offsets per partition vs ONE batched
            # end-offset read — the PR 11 lag chain, group-scoped.
            offsets = self._admin.list_consumer_group_offsets(group)
            lags: Dict[str, Dict[int, int]] = {}
            if offsets:
                ends_raw = self._end_offsets_fn()(sorted(
                    offsets, key=lambda tp: (tp.topic, int(tp.partition))
                ))
                ends = {
                    (tp.topic, int(tp.partition)): off
                    for tp, off in ends_raw.items() if off is not None
                }
                for tp, meta in offsets.items():
                    key = (tp.topic, int(tp.partition))
                    committed = getattr(meta, "offset", None)
                    if key not in ends or committed is None \
                            or committed < 0:
                        continue
                    lags.setdefault(key[0], {})[key[1]] = max(
                        0, int(ends[key]) - int(committed)
                    )
            out[group] = ConsumerGroupState(
                group=group,
                members=tuple(sorted(members)),
                assignment=assignment,
                lags=lags,
            )
        return out

    # -- plan execution surface (ISSUE 7) ---------------------------------

    def supports_execution(self) -> bool:
        """KIP-455 ``alter_partition_reassignments`` when the client carries
        it (kafka-python duck-typed; confluent-kafka's librdkafka has no
        reassignment API at all). A backend that cannot write says so up
        front — ``ka-execute`` refuses before touching the journal."""
        return self._impl == "kafka-python" and hasattr(
            self._admin, "alter_partition_reassignments"
        )

    def apply_assignment(
        self, moves: Dict[str, Dict[int, List[int]]]
    ) -> None:
        from ..errors import ExecuteError

        if not self.supports_execution():
            raise ExecuteError(
                "this Kafka AdminClient cannot execute reassignments "
                "(no KIP-455 alter_partition_reassignments support); "
                "execute against the zk:// backend instead"
            )
        counter_add("zk.writes")
        if self._faults is not None \
                and self._faults.write_attempt() == "lost":
            return
        # Duck-typed KIP-455 call: {(topic, partition): [target replicas]}.
        with hist_ms("zk.op_ms"):
            self._admin.alter_partition_reassignments({
                (t, int(p)): [int(r) for r in reps]
                for t, parts in moves.items()
                for p, reps in parts.items()
            })

    def read_assignment_state(
        self, topics: Sequence[str]
    ) -> Dict[str, Dict[int, PartitionState]]:
        """Convergence poll over the AdminClient metadata: both client
        impls DO expose per-partition ISR (confluent ``isrs``, kafka-python
        describe ``isr``), so the engine gets the real in-sync signal even
        where racks are invisible. The ``converge`` stall scope lives on
        the snapshot backend only — it freezes PENDING state, and this
        backend holds none; blanking the result here would misfire the
        engine's plan/verify reads as fatal failures instead of a retried
        poll. The ``reply`` scope still covers this RPC's failure modes."""
        self._fault_reply()
        unique = list(dict.fromkeys(topics))
        out: Dict[str, Dict[int, PartitionState]] = {}
        if self._impl == "confluent":
            with hist_ms("zk.op_ms"):
                md = self._admin.list_topics(timeout=10)
            for t in unique:
                tmeta = md.topics.get(t)
                if tmeta is None:
                    continue
                out[t] = {
                    int(p): PartitionState(
                        [int(r) for r in pm.replicas],
                        [int(r) for r in getattr(
                            pm, "isrs", pm.replicas
                        )],
                    )
                    for p, pm in tmeta.partitions.items()
                }
            return out
        try:
            with hist_ms("zk.op_ms"):
                described = self._admin.describe_topics(unique)
        except Exception as e:
            if not self._is_unknown_topic(e):
                raise
            # One vanished topic must not blank the whole poll (the engine
            # would read that as EVERY wave partition unconverged / every
            # verify entry mismatched): probe per topic, like the
            # skip-missing ingest lane, and omit only the vanished ones.
            described = []
            for t in unique:
                try:
                    described.extend(self._admin.describe_topics([t]))
                except Exception as per_topic_err:
                    if not self._is_unknown_topic(per_topic_err):
                        raise
        for t in described:
            out[t["topic"]] = {
                int(p["partition"]): PartitionState(
                    [int(r) for r in p["replicas"]],
                    [int(r) for r in p.get("isr", p["replicas"])],
                )
                for p in t["partitions"]
            }
        return out

    def close(self) -> None:
        if self._impl == "kafka-python":
            self._admin.close()
