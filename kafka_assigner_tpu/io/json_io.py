"""Kafka reassignment-JSON formatting, byte-compatible with the reference.

Two producers exist in the reference and both must round-trip through Kafka's
``kafka-reassign-partitions`` tool (``README.md:52``):

- PRINT_CURRENT_ASSIGNMENT delegates to Kafka's own
  ``zkUtils.formatAsReassignmentJson`` (``KafkaAssignmentGenerator.java:108-110``);
- PRINT_REASSIGNMENT hand-builds ``{"version":1,"partitions":[{topic,partition,
  replicas}...]}`` with org.json (``KafkaAssignmentGenerator.java:169-186``).

We emit one canonical compact form for both: key order ``version, partitions``
and ``topic, partition, replicas``, no whitespace — the shape Kafka's parser
accepts and the reference's org.json ``toString()`` emits.
"""
from __future__ import annotations

import json
from typing import Dict, List, Mapping, Sequence

from .base import BrokerInfo

KAFKA_FORMAT_VERSION = 1  # KafkaAssignmentGenerator.java:49


def format_reassignment_json(
    assignments: Mapping[str, Mapping[int, Sequence[int]]],
    topic_order: Sequence[str] | None = None,
) -> str:
    """Serialize ``{topic: {partition: [replicas]}}`` as Kafka reassignment
    JSON. Topics follow ``topic_order`` (the CLI's topic iteration order,
    ``KafkaAssignmentGenerator.java:173``); partitions ascend within a topic
    (TreeMap semantics, ``KafkaAssignmentStrategy.java:205,221``)."""
    topics = list(topic_order) if topic_order is not None else sorted(assignments)
    partitions = [
        {"topic": t, "partition": p, "replicas": list(assignments[t][p])}
        for t in topics
        for p in sorted(assignments[t])
    ]
    return json.dumps(
        {"version": KAFKA_FORMAT_VERSION, "partitions": partitions},
        separators=(",", ":"),
        ensure_ascii=False,  # org.json writes non-ASCII raw
    )


def format_reassignment_pairs(
    pairs: Sequence,  # [(topic, {partition: [replicas]}), ...], duplicates allowed
) -> str:
    """Like :func:`format_reassignment_json` but over an ordered list of
    (topic, assignment) pairs — the shape the reassignment driver produces,
    where a topic listed twice on the CLI is solved and emitted twice
    (reference topic loop, ``KafkaAssignmentGenerator.java:173-183``)."""
    partitions = [
        {"topic": t, "partition": p, "replicas": list(assignment[p])}
        for t, assignment in pairs
        for p in sorted(assignment)
    ]
    return json.dumps(
        {"version": KAFKA_FORMAT_VERSION, "partitions": partitions},
        separators=(",", ":"),
        ensure_ascii=False,  # org.json writes non-ASCII raw
    )


def parse_reassignment_json(payload: str) -> Dict[str, Dict[int, List[int]]]:
    """Inverse of :func:`format_reassignment_json` (accepts any Kafka-parseable
    reassignment JSON, whatever the key order/whitespace)."""
    data = json.loads(payload)
    version = data.get("version")
    if version != KAFKA_FORMAT_VERSION:
        raise ValueError(f"unsupported reassignment JSON version: {version!r}")
    out: Dict[str, Dict[int, List[int]]] = {}
    for entry in data.get("partitions", []):
        out.setdefault(entry["topic"], {})[int(entry["partition"])] = [
            int(r) for r in entry["replicas"]
        ]
    return out


def format_brokers_json(brokers: Sequence[BrokerInfo]) -> str:
    """PRINT_CURRENT_BROKERS payload: JSON array of ``{id, host, port, rack?}``
    per live broker, rack omitted when undefined
    (``KafkaAssignmentGenerator.java:113-129``)."""
    entries = []
    for b in brokers:
        entry = {"id": b.id, "host": b.host, "port": b.port}
        if b.rack is not None:
            entry["rack"] = b.rack
        entries.append(entry)
    return json.dumps(entries, separators=(",", ":"), ensure_ascii=False)
