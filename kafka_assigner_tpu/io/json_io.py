"""Kafka reassignment-JSON formatting, byte-compatible with the reference.

Two distinct serializers exist in the reference, and their bytes differ:

- PRINT_CURRENT_ASSIGNMENT (and the rollback section of mode 3) delegates to
  Kafka's own ``zkUtils.formatAsReassignmentJson``
  (``KafkaAssignmentGenerator.java:108-110``). Kafka 0.10's
  ``kafka.utils.Json.encode`` walks small Scala immutable Maps in insertion
  order, so the bytes are ``{"version":1,"partitions":[{"topic":…,
  "partition":…,"replicas":[…]},…]}`` — *insertion* key order, compact, raw
  strings. :func:`format_reassignment_json` reproduces that.
- PRINT_REASSIGNMENT's "NEW ASSIGNMENT" and PRINT_CURRENT_BROKERS hand-build
  JSON with org.json 20131018 (``KafkaAssignmentGenerator.java:113-129,
  169-186``), whose ``JSONObject`` stores keys in a ``java.util.HashMap`` —
  ``toString()`` therefore walks **HashMap bucket order**, not insertion
  order. For a default-capacity-16 JDK8 HashMap (bucket =
  ``(h ^ h>>>16) & 15`` over ``String.hashCode``, see ``utils/javahash.py``):

  ============================  =================================
  inserted                      org.json/JDK8 emission order
  ============================  =================================
  version, partitions           ``partitions, version``
  topic, partition, replicas    ``partition, replicas, topic``
  id, host, port[, rack]        ``[rack, ]port, host, id``
  ============================  =================================

  :func:`format_reassignment_pairs` and :func:`format_brokers_json` reproduce
  those bytes (``tests/test_golden_output.py`` pins them). JDK7's HashMap
  spreads hashes differently, so the reference's own bytes vary by JVM; we
  pin the JDK8 order, the standard runtime of the Kafka-0.10 era.

Every form round-trips through Kafka's ``kafka-reassign-partitions`` parser
(``README.md:52``), which accepts any key order.
"""
from __future__ import annotations

import json
from typing import Dict, List, Mapping, Sequence

from .base import BrokerInfo

KAFKA_FORMAT_VERSION = 1  # KafkaAssignmentGenerator.java:49


def format_reassignment_json(
    assignments: Mapping[str, Mapping[int, Sequence[int]]],
    topic_order: Sequence[str] | None = None,
) -> str:
    """Serialize ``{topic: {partition: [replicas]}}`` as Kafka reassignment
    JSON. Topics follow ``topic_order`` (the CLI's topic iteration order,
    ``KafkaAssignmentGenerator.java:173``); partitions ascend within a topic
    (TreeMap semantics, ``KafkaAssignmentStrategy.java:205,221``)."""
    topics = list(topic_order) if topic_order is not None else sorted(assignments)
    partitions = [
        {"topic": t, "partition": p, "replicas": list(assignments[t][p])}
        for t in topics
        for p in sorted(assignments[t])
    ]
    return json.dumps(
        {"version": KAFKA_FORMAT_VERSION, "partitions": partitions},
        separators=(",", ":"),
        ensure_ascii=False,  # org.json writes non-ASCII raw
    )


def format_reassignment_pairs(
    pairs: Sequence,  # [(topic, {partition: [replicas]}), ...], duplicates allowed
) -> str:
    """The "NEW ASSIGNMENT" payload over an ordered list of (topic,
    assignment) pairs — the shape the reassignment driver produces, where a
    topic listed twice on the CLI is solved and emitted twice (reference
    topic loop, ``KafkaAssignmentGenerator.java:173-183``).

    Byte-matches org.json's ``toString()`` on JDK8 (see module docstring):
    array order is insertion order (topics in CLI order, partitions ascending
    — TreeMap semantics), object key order is HashMap bucket order."""
    partitions = [
        {"partition": p, "replicas": list(assignment[p]), "topic": t}
        for t, assignment in pairs
        for p in sorted(assignment)
    ]
    return json.dumps(
        {"partitions": partitions, "version": KAFKA_FORMAT_VERSION},
        separators=(",", ":"),
        ensure_ascii=False,  # org.json writes non-ASCII raw
    )


def parse_reassignment_json(payload: str) -> Dict[str, Dict[int, List[int]]]:
    """Inverse of :func:`format_reassignment_json` (accepts any Kafka-parseable
    reassignment JSON, whatever the key order/whitespace)."""
    data = json.loads(payload)
    version = data.get("version")
    if version != KAFKA_FORMAT_VERSION:
        raise ValueError(f"unsupported reassignment JSON version: {version!r}")
    out: Dict[str, Dict[int, List[int]]] = {}
    for entry in data.get("partitions", []):
        out.setdefault(entry["topic"], {})[int(entry["partition"])] = [
            int(r) for r in entry["replicas"]
        ]
    return out


def format_brokers_json(brokers: Sequence[BrokerInfo]) -> str:
    """PRINT_CURRENT_BROKERS payload: JSON array, one object per live broker,
    rack omitted when undefined (``KafkaAssignmentGenerator.java:113-129``).

    Key order is org.json-on-JDK8 bucket order (module docstring):
    ``rack`` (when defined), ``port``, ``host``, ``id``."""
    entries = []
    for b in brokers:
        entry = {} if b.rack is None else {"rack": b.rack}
        entry.update({"port": b.port, "host": b.host, "id": b.id})
        entries.append(entry)
    return json.dumps(entries, separators=(",", ":"), ensure_ascii=False)
