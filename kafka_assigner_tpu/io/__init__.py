from .base import BrokerInfo, MetadataBackend, open_backend

__all__ = ["BrokerInfo", "MetadataBackend", "open_backend"]
