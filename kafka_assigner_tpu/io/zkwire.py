"""Minimal pure-python ZooKeeper wire client — the read-only jute subset the
metadata layer needs (``get_children`` + ``get``), used by ``io/zk.py`` as a
dependency-free fallback when ``kazoo`` is not installed.

The reference tool cannot run at all without a live ZK quorum AND the full
ZkClient stack on the classpath (``KafkaAssignmentGenerator.java:273-276``,
``pom.xml:50-58``). Here the preferred client is still kazoo (battle-tested
reconnects/SASL/watches), but the assignment generator only ever performs
three read RPCs over an open session, which is a small, stable corner of the
protocol (ZooKeeper's jute serialization, unchanged since 3.0):

- frames: 4-byte big-endian length prefix;
- session handshake: ``ConnectRequest``/``ConnectResponse``;
- ``getChildren`` (type 8) and ``getData`` (type 4) with
  ``ReplyHeader{xid, zxid, err}`` responses;
- ``closeSession`` (type -11).

No watches, no ephemerals, no reconnect-transparent writes: the CLI opens a
session, reads the broker/topic znodes, and closes. The reference's 10 s
timeout bounds each connect attempt and each in-session read; session
ESTABLISHMENT may retry up to ``KA_ZK_CONNECT_RETRIES`` loudly-warned
passes over the endpoint list with backoff, so the worst-case connect
envelope is ``passes x endpoints x timeout`` against a SYN-blackholing
quorum — set the knob to 1 to restore a single-pass bound.
``tests/test_zk_socket.py`` runs this client against an in-process jute
server over a real TCP socket (and runs kazoo against the same server when
it is installed).

Reads pipeline (ISSUE 4): ``get_many``/``iter_get`` keep up to
``KA_ZK_PIPELINE`` requests in flight on the session socket with
out-of-order-safe xid matching, so N znode reads cost ~``ceil(N/window)``
round-trips instead of N; a window of one degrades to the exact serial
frame sequence (``tests/test_zk_golden_frames.py`` pins both byte-for-byte
against spec-derived frames). Session connects retry across the shuffled
endpoint list with jittered backoff (``KA_ZK_CONNECT_RETRIES``).

Self-healing reads (ISSUE 5): a session that dies MID-read — socket drop,
truncated/desynced frame, per-reply timeout — no longer kills the run.
Transport-level failures raise :class:`ZkConnectionError` (a loud subclass
of :class:`ZkWireError`), and both the serial ops and the pipelined
``iter_get`` window catch it, re-establish the session (up to
``KA_ZK_SESSION_RETRIES`` times, jittered backoff, every attempt warned on
stderr + counted as ``zk.session.reestablished``) and re-issue ONLY the
unanswered reads. Reads are idempotent, so the replay is byte-identical to
an uninterrupted run (the golden-frame pins hold with the window replayed
at any cut point). Server-REPORTED errors (NoNode, auth) are never
retried — a missing znode on a healthy session is an answer, not a fault.
The fault-injection harness (``faults/inject.py``, ``KA_FAULTS_SPEC``)
hooks this client at the connect/handshake/reply seams to drive exactly
these paths deterministically.

Writes (ISSUE 7, the plan execution engine): the client now speaks the
four mutation opcodes the reassignment write path needs — ``create``
(type 1), ``delete`` (type 2), ``exists`` (type 3, a read) and ``setData``
(type 5) — under a STRICTER safety rule than the reads, because a write is
not idempotent-by-observation: after a transport failure the socket state
is unknown and the request may or may not have been applied. Writes are
therefore (a) NEVER pipelined — each goes through the serial
:meth:`MiniZkClient._write_call` path, one request/one reply (kalint rule
KA010 machine-checks that the write opcodes never reach the windowed
helpers) — and (b) NEVER blindly replayed after session re-establishment:
on a transport error the client reconnects, READS the server state back
(a caller-supplied ``landed`` probe: does the node exist / carry the
written bytes?), and re-issues only when the write provably did not land.
Server-reported errors (``NodeExistsError``, ``NoNodeError``, bad version)
propagate untouched — they are answers, not faults.
"""
from __future__ import annotations

import random
import socket
import struct
import sys
import time
from typing import Iterator, List, NamedTuple, Optional, Sequence, Tuple

from ..faults.inject import active_injector
from ..obs.metrics import counter_add, gauge_set, hist_observe, hist_ms

#: ZooKeeper opcodes (zookeeper.ZooDefs.OpCode). The three WRITE opcodes
#: (create/delete/setData) are restricted to the serial write path — see the
#: module docstring's write-safety rule and kalint rule KA010.
OP_CREATE = 1
OP_DELETE = 2
OP_EXISTS = 3
OP_GET_DATA = 4
OP_SET_DATA = 5
OP_GET_CHILDREN = 8
OP_PING = 11
OP_CLOSE = -11

#: KeeperException codes.
ERR_NONODE = -101
ERR_NODEEXISTS = -110
ERR_BADVERSION = -103

PING_XID = -2
#: The server-initiated notification "xid" (ClientCnxn.NOTIFICATION_XID):
#: a WatcherEvent frame, not a reply to any request.
NOTIFICATION_XID = -1

#: WatcherEvent types (org.apache.zookeeper.Watcher.Event.EventType).
EVENT_CREATED = 1
EVENT_DELETED = 2
EVENT_DATA_CHANGED = 3
EVENT_CHILDREN_CHANGED = 4

#: world:anyone open ACL (ZooDefs.Ids.OPEN_ACL_UNSAFE) — the only ACL the
#: reassignment admin znode needs; vector of one ACL{perms=ALL(31),
#: Id{scheme="world", id="anyone"}}.
_OPEN_ACL = (
    struct.pack(">i", 1)
    + struct.pack(">i", 31)
    + struct.pack(">i", 5) + b"world"
    + struct.pack(">i", 6) + b"anyone"
)


class ZkWireError(RuntimeError):
    """Connection-level or server-reported failure of the wire client."""


class ZkConnectionError(ZkWireError):
    """Transport-level failure of an open session (socket drop, truncated or
    desynced frame, reply timeout): the socket's state is unknown but no
    read was half-applied, so the unanswered requests may be safely
    re-issued on a fresh session (reads are idempotent). The resilience
    layer retries exactly this class — never server-reported errors."""


class NoNodeError(ZkWireError):
    """The requested znode does not exist (KeeperException.NoNode)."""


class NodeExistsError(ZkWireError):
    """The znode a ``create`` targeted already exists
    (KeeperException.NodeExists) — for the reassignment admin znode this
    means another reassignment is still in flight."""


class BadVersionError(ZkWireError):
    """A versioned write lost its compare-and-set race
    (KeeperException.BadVersion) — somebody else mutated the znode."""


class WatchEvent(NamedTuple):
    """One server-pushed WatcherEvent (type, keeper state, chroot-stripped
    path). ZooKeeper watches are one-shot: after an event the caller must
    re-read WITH a fresh watch flag to stay subscribed — which conveniently
    is also the re-read the daemon's delta re-encode needs (ISSUE 8)."""

    type: int
    state: int
    path: str


class ZnodeStat(NamedTuple):
    czxid: int
    mzxid: int
    ctime: int
    mtime: int
    version: int
    cversion: int
    aversion: int
    ephemeralOwner: int
    dataLength: int
    numChildren: int
    pzxid: int


def _pack_buffer(data: Optional[bytes]) -> bytes:
    if data is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(data)) + data


def _pack_str(s: str) -> bytes:
    return _pack_buffer(s.encode("utf-8"))


class _Reader:
    """Sequential jute decoder over one reply frame."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise ZkConnectionError("truncated ZooKeeper reply frame")
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def read_int(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def read_long(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def read_buffer(self) -> Optional[bytes]:
        n = self.read_int()
        if n < 0:
            return None
        return self._take(n)

    def read_str(self) -> str:
        buf = self.read_buffer()
        return "" if buf is None else buf.decode("utf-8")

    def read_stat(self) -> ZnodeStat:
        return ZnodeStat(*struct.unpack(">qqqqiiiqiiq", self._take(68)))


def parse_hosts(connect_string: str) -> Tuple[List[Tuple[str, int]], str]:
    """``host:port,host:port[/chroot]`` → (endpoints, chroot). Kafka connect
    strings routinely carry a chroot suffix (``zk1:2181,zk2:2181/kafka``)."""
    hosts_part, slash, chroot = connect_string.partition("/")
    chroot = (slash + chroot).rstrip("/") if slash else ""
    endpoints = []
    for tok in hosts_part.split(","):
        tok = tok.strip()
        if not tok:
            continue
        host, _, port = tok.rpartition(":")
        if not host:
            host, port = tok, "2181"
        endpoints.append((host, int(port)))
    if not endpoints:
        raise ZkWireError(f"no ZooKeeper endpoints in {connect_string!r}")
    return endpoints, chroot


def _decode_get(r: _Reader) -> Tuple[bytes, ZnodeStat]:
    """getData reply body: data buffer + stat."""
    data = r.read_buffer() or b""
    return data, r.read_stat()


def _decode_children(r: _Reader) -> List[str]:
    """getChildren reply body: vector of child names."""
    count = r.read_int()
    if count < 0:
        return []
    return [r.read_str() for _ in range(count)]


class MiniZkClient:
    """Duck-type of the ``kazoo.client.KazooClient`` surface ``ZkBackend``
    uses: ``start`` / ``get_children`` / ``get`` / ``stop`` / ``close`` —
    plus the write subset the plan execution engine needs (``create`` /
    ``set`` / ``delete`` / ``exists``, kazoo-compatible signatures)."""

    def __init__(self, hosts: str, timeout: float = 10.0) -> None:
        self._endpoints, self._chroot = parse_hosts(hosts)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._xid = 0
        self._max_in_flight = 0  # high-water mark across this session
        #: Pending server-pushed WatcherEvents (drained by poll_watches).
        self._watch_events: List[WatchEvent] = []
        #: Bumped on every successful (re-)establishment: watches do NOT
        #: survive a session, so a caller that armed watches compares this
        #: against the value it saw at arm time to detect that a transparent
        #: in-client reconnect invalidated them (the daemon's resync
        #: trigger, ISSUE 8).
        self.session_generation = 0
        # Fault-injection harness hook (None in production: one attribute
        # read per frame). Resolved once per client so a run's schedule is
        # coherent across reconnects.
        self._faults = active_injector()

    # -- session ----------------------------------------------------------

    def start(self, timeout: Optional[float] = None) -> None:
        """Establish a session: up to ``KA_ZK_CONNECT_RETRIES`` passes over
        the endpoint list (shuffled once, like production ZK clients, so a
        fleet of callers does not pile onto the first quorum member), with
        exponential backoff between passes. Every failed pass is warned on
        stderr — a silent half-minute of retries looks exactly like a hang."""
        from ..utils.backoff import JitteredBackoff
        from ..utils.env import env_int

        deadline_t = timeout if timeout is not None else self._timeout
        retries = env_int("KA_ZK_CONNECT_RETRIES")
        endpoints = list(self._endpoints)
        random.shuffle(endpoints)
        last_err: Optional[Exception] = None
        # Jittered backoff (0.5x-1.5x the nominal step): a fleet of
        # parallel what-if workers retrying a flapped quorum member must
        # not re-arrive in lockstep (thundering herd).
        pass_backoff = JitteredBackoff(0.1, cap=2.0)
        for attempt in range(1, retries + 1):
            for host, port in endpoints:
                try:
                    if self._faults is not None:
                        self._faults.connect_attempt()
                    sock = socket.create_connection((host, port), deadline_t)
                    sock.settimeout(deadline_t)
                    # Pipelining sends many small frames back-to-back; with
                    # Nagle on, each write after the first stalls on the
                    # peer's delayed ACK (~40 ms on many stacks) — the exact
                    # latency this client exists to remove.
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    self._sock = sock
                    self._handshake(int(deadline_t * 1000))
                    self.session_generation += 1
                    return
                except (OSError, ZkWireError) as e:
                    last_err = e
                    if self._sock is not None:
                        self._sock.close()
                        self._sock = None
            if attempt < retries:
                backoff = pass_backoff.next_delay()
                print(
                    f"kafka-assigner: ZooKeeper connect pass {attempt}/"
                    f"{retries} failed over {len(endpoints)} endpoint(s) "
                    f"({last_err}); retrying in {backoff:.1f}s",
                    file=sys.stderr,
                )
                time.sleep(backoff)
        raise ZkWireError(
            f"could not establish a ZooKeeper session with any of "
            f"{endpoints} after {retries} pass(es): {last_err}"
        )

    def _handshake(self, timeout_ms: int) -> None:
        # ConnectRequest: protocolVersion, lastZxidSeen, timeOut, sessionId,
        # passwd, readOnly (3.4+; servers without it ignore the extra byte).
        req = (
            struct.pack(">iqiq", 0, 0, timeout_ms, 0)
            + _pack_buffer(b"\x00" * 16)
            + b"\x00"
        )
        self._send_frame(req)
        raw = self._recv_frame()
        if self._faults is not None:
            raw = self._faults.filter_handshake(raw)
        r = _Reader(raw)
        r.read_int()            # protocolVersion
        negotiated = r.read_int()  # timeOut
        r.read_long()           # sessionId (0 on expiry, unused otherwise)
        if negotiated <= 0:
            # The expired-session ConnectResponse: negotiated timeout 0
            # (sessionId is also 0, but the timeout alone is decisive).
            raise ZkWireError("ZooKeeper session expired during handshake")

    # -- rpc --------------------------------------------------------------

    def _send_frame(self, payload: bytes) -> None:
        assert self._sock is not None
        counter_add("zk.wire_frames_out")
        counter_add("zk.wire_bytes_out", 4 + len(payload))
        self._sock.sendall(struct.pack(">i", len(payload)) + payload)

    def _recv_frame(self) -> bytes:
        assert self._sock is not None
        header = self._recv_exact(4)
        (n,) = struct.unpack(">i", header)
        if n < 0 or n > (64 << 20):
            raise ZkConnectionError(f"invalid ZooKeeper frame length {n}")
        counter_add("zk.wire_frames_in")
        counter_add("zk.wire_bytes_in", 4 + n)
        return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        assert self._sock is not None
        chunks = []
        while n:
            chunk = self._sock.recv(n)
            if not chunk:
                raise ZkConnectionError("ZooKeeper connection closed mid-reply")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _reconnect(self, attempt: int, retries: int, err: Exception) -> None:
        """Tear down the dead socket and establish a fresh session (which
        itself retries over the endpoint list): the in-session half of the
        resilience layer. Jittered backoff, loud stderr, counted."""
        from ..utils.backoff import JitteredBackoff

        counter_add("zk.session.reestablished")
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # kalint: disable=KA008 -- socket already dead; the reconnect below is the recovery
                pass
            self._sock = None
        backoff = JitteredBackoff(0.05, cap=1.0).delay_for(attempt)
        print(
            f"kafka-assigner: ZooKeeper session lost mid-read "
            f"({type(err).__name__}: {err}); re-establishing and replaying "
            f"unanswered reads (attempt {attempt}/{retries}, "
            f"backoff {backoff:.2f}s)",
            file=sys.stderr,
        )
        time.sleep(backoff)
        self.start()

    def _call(self, op: int, payload: bytes) -> _Reader:
        if self._sock is None:
            raise ZkWireError("ZooKeeper session is not started")
        from ..utils.env import env_int

        retries = env_int("KA_ZK_SESSION_RETRIES")
        attempt = 0
        while True:
            self._xid += 1
            xid = self._xid
            try:
                # Metrics-only timing (hist_ms): one RPC per znode is too
                # hot for the span log, but the latency distribution is
                # exactly what a fleet-scale run needs to see.
                with hist_ms("zk.op_ms"):
                    return self._call_inner(op, xid, payload)
            except (OSError, ZkConnectionError) as e:
                # Transport death only: a serial read is unanswered by
                # definition, so re-issuing it on a fresh session is safe.
                # NoNode/server errors propagate untouched above.
                attempt += 1
                if attempt > retries:
                    raise
                self._reconnect(attempt, retries, e)

    def _call_inner(self, op: int, xid: int, payload: bytes) -> _Reader:
        self._send_frame(struct.pack(">ii", xid, op) + payload)
        rxid, err, r = self._recv_reply()
        if rxid != xid:
            raise ZkConnectionError(
                f"ZooKeeper reply xid {rxid} does not match request {xid}"
            )
        if err == ERR_NONODE:
            raise NoNodeError(f"znode does not exist (err {err})")
        if err == ERR_NODEEXISTS:
            raise NodeExistsError(f"znode already exists (err {err})")
        if err == ERR_BADVERSION:
            raise BadVersionError(f"znode version mismatch (err {err})")
        if err != 0:
            raise ZkWireError(f"ZooKeeper error {err}")
        return r

    def _recv_reply(self) -> Tuple[int, int, _Reader]:
        """One reply frame's ``ReplyHeader`` (xid, err) plus its body reader,
        skipping stray ping replies (the session-keepalive xid) and queueing
        watch notifications (xid -1) for ``poll_watches``."""
        # kalint: disable=KA011 -- bounded by the session socket timeout set at connect (settimeout in start)
        while True:
            raw = self._recv_frame()
            if self._faults is not None:
                raw = self._faults.filter_reply(raw, self._sock)
            r = _Reader(raw)
            rxid = r.read_int()
            r.read_long()  # zxid
            err = r.read_int()
            if rxid == PING_XID:  # stray ping reply; not ours
                continue
            if rxid == NOTIFICATION_XID:  # server-pushed WatcherEvent
                self._watch_events.append(self._decode_watch_event(r))
                continue
            return rxid, err, r

    def _decode_watch_event(self, r: _Reader) -> WatchEvent:
        ev_type = r.read_int()
        state = r.read_int()
        path = r.read_str()
        if self._chroot and path.startswith(self._chroot):
            path = path[len(self._chroot):] or "/"
        counter_add("zk.watch_events")
        return WatchEvent(ev_type, state, path)

    def _path(self, path: str) -> str:
        return (self._chroot + path) if self._chroot else path

    # -- reads ------------------------------------------------------------

    def get_children(self, path: str, watch: bool = False) -> List[str]:
        """Child listing; ``watch=True`` additionally arms a one-shot CHILD
        watch on the znode (NodeChildrenChanged / NodeDeleted events arrive
        via :meth:`poll_watches`)."""
        r = self._call(
            OP_GET_CHILDREN,
            _pack_str(self._path(path)) + (b"\x01" if watch else b"\x00"),
        )
        return _decode_children(r)

    def exists(self, path: str) -> Optional[ZnodeStat]:
        """``exists`` (type 3): the znode's stat, or ``None`` when absent —
        a READ (NoNode is the answer, not an error), and the write path's
        read-back probe."""
        try:
            r = self._call(OP_EXISTS, _pack_str(self._path(path)) + b"\x00")
        except NoNodeError:
            return None
        return r.read_stat()

    def get(self, path: str, watch: bool = False) -> Tuple[bytes, ZnodeStat]:
        """``getData``; ``watch=True`` additionally arms a one-shot DATA
        watch (NodeDataChanged / NodeDeleted events via
        :meth:`poll_watches`)."""
        r = self._call(
            OP_GET_DATA,
            _pack_str(self._path(path)) + (b"\x01" if watch else b"\x00"),
        )
        data = r.read_buffer() or b""
        return data, r.read_stat()

    # -- watches (ISSUE 8: the daemon's churn feed) ------------------------

    def ping(self) -> None:
        """Session keepalive (opcode 11, xid -2): the daemon's idle watch
        loop sends one per poll so a quiet session never expires server-side.
        The reply is consumed (and skipped) by whichever read runs next —
        ``_recv_reply`` and ``poll_watches`` both ignore ping replies."""
        if self._sock is None:
            raise ZkWireError("ZooKeeper session is not started")
        self._send_frame(struct.pack(">ii", PING_XID, OP_PING))

    def poll_watches(self, timeout: float = 0.25) -> List[WatchEvent]:
        """Drain pending watch notifications, blocking up to ``timeout``
        seconds for the first event. Returns the (possibly empty) event
        list; transport death raises :class:`ZkConnectionError` — watches do
        not survive the session, so the caller must re-establish, RE-ARM and
        resync (``session_generation`` tells it when a transparent reconnect
        did this underneath).

        Only server-initiated frames are legal here (no request is in
        flight): WatcherEvents are collected, ping replies are dropped
        WITHOUT ending the wait (an idle keepalive must not turn the poll
        into a busy loop), anything else is a desynced session. Readability
        is tested with ``select`` before any byte is consumed, so a quiet
        window can never abandon a half-read frame — once a frame's header
        is on the wire, the body read runs under the ordinary session
        socket timeout."""
        import select

        events, self._watch_events = self._watch_events, []
        if events or self._sock is None:
            return events
        deadline = time.monotonic() + max(timeout, 0.0)
        # kalint: disable=KA011 -- bounded by the caller-passed timeout: every select waits at most the remaining deadline and an empty poll returns
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return events
            try:
                ready, _, _ = select.select([self._sock], [], [], remaining)
            except OSError as e:
                raise ZkConnectionError(
                    f"ZooKeeper session died while polling watches: {e}"
                ) from e
            if not ready:
                return events
            raw = self._recv_frame()
            r = _Reader(raw)
            rxid = r.read_int()
            r.read_long()  # zxid
            r.read_int()   # err
            if rxid == NOTIFICATION_XID:
                events.append(self._decode_watch_event(r))
                events.extend(self._drain_ready_watches())
                return events
            if rxid != PING_XID:
                raise ZkConnectionError(
                    f"unexpected reply xid {rxid} on an idle session while "
                    "polling watches (desynced)"
                )

    def _drain_ready_watches(self) -> List[WatchEvent]:
        """Collect frames already queued behind a just-read notification:
        zero-timeout readability probes, so nothing blocks and no frame is
        ever half-read."""
        import select

        out: List[WatchEvent] = []
        assert self._sock is not None
        # kalint: disable=KA011 -- select() with zero timeout bounds every iteration; the loop exits on the first empty probe
        while True:
            ready, _, _ = select.select([self._sock], [], [], 0)
            if not ready:
                return out
            raw = self._recv_frame()
            r = _Reader(raw)
            rxid = r.read_int()
            r.read_long()
            r.read_int()
            if rxid == NOTIFICATION_XID:
                out.append(self._decode_watch_event(r))
            elif rxid != PING_XID:
                raise ZkConnectionError(
                    f"unexpected reply xid {rxid} while draining "
                    "watch notifications"
                )

    # -- pipelined reads --------------------------------------------------

    def iter_get(
        self, paths: Sequence[str], missing_ok: bool = False,
        watch: bool = False,
    ) -> Iterator[Optional[Tuple[bytes, ZnodeStat]]]:
        """Pipelined ``getData`` over the session socket: up to
        ``KA_ZK_PIPELINE`` requests in flight at once, responses matched by
        xid (ZooKeeper answers a session's requests in order, but the
        matching is out-of-order-safe by construction — a reordering proxy
        or a future multi-op cannot silently mis-pair results). Yields
        ``(data, stat)`` in request order as responses arrive, so callers
        can overlap downstream work with the remaining round-trips.

        Failure contract: a per-response timeout raises loudly, naming the
        outstanding window; a server-reported error (``NoNodeError`` for a
        missing znode) stops new sends, drains the already-sent window —
        keeping the session usable, exactly like a failed serial ``get`` —
        and is raised at the failing path's position in request order, after
        every earlier result has been yielded. Under ``missing_ok=True`` a
        missing znode instead yields ``None`` at its position and the
        pipeline keeps flowing — the graceful-degradation hook for topics
        deleted between ``getChildren`` and ``getData`` (ISSUE 5). With a
        window of one the frame sequence on the wire is byte-identical to
        serial ``get`` calls.

        Self-healing (ISSUE 5): a transport-level death mid-window
        (:class:`ZkConnectionError`, ``OSError``) re-establishes the session
        and re-issues only the not-yet-yielded reads, up to
        ``KA_ZK_SESSION_RETRIES`` times — results already handed to the
        caller are never re-fetched, so the output stream is byte-identical
        to an uninterrupted run.

        Abandoning the iterator early (``break``, GeneratorExit) drains the
        in-flight window on close, so the session stays usable for
        subsequent calls. Latency accounting note: pipelined reads report
        ``zk.pipeline.batch_ms`` only — a reply's arrival time inside a
        window is not a per-op latency, so they deliberately do NOT feed the
        serial ``zk.op_ms`` histogram (which therefore covers serial ops
        only).

        ``watch=True`` arms a one-shot DATA watch per read (the daemon's
        pipelined resync re-arm, ISSUE 8) — notifications arrive via
        :meth:`poll_watches`.

        Not thread-safe: one pipelined batch (or serial call) at a time per
        client — the streaming ingest hands the whole client to its producer
        thread for the duration of the batch.
        """
        yield from self._iter_pipelined(paths, missing_ok, OP_GET_DATA,
                                        _decode_get, watch)

    def iter_children(
        self, paths: Sequence[str], missing_ok: bool = False
    ) -> Iterator[Optional[List[str]]]:
        """Pipelined ``getChildren`` over the session socket — the same
        xid-matched window, replay and failure contract as :meth:`iter_get`
        (ISSUE 7 satellite: the per-topic ``partitions`` children fan-out of
        the convergence poll was the last serial read loop). Yields the
        child-name list per path in request order; under ``missing_ok`` a
        missing znode yields ``None`` at its position."""
        yield from self._iter_pipelined(paths, missing_ok, OP_GET_CHILDREN,
                                        _decode_children)

    def _iter_pipelined(self, paths, missing_ok, op, decode, watch=False):
        """The shared pipelined-read driver behind :meth:`iter_get` and
        :meth:`iter_children`: the window/replay loop, parameterized only by
        READ opcode + body decoder (+ the read watch flag). Write opcodes
        must never reach this path (the module write-safety rule; kalint
        KA010)."""
        if self._sock is None:
            raise ZkWireError("ZooKeeper session is not started")
        from ..utils.env import env_int

        window = env_int("KA_ZK_PIPELINE")
        retries = env_int("KA_ZK_SESSION_RETRIES")
        n = len(paths)
        if n == 0:
            return
        t0 = time.perf_counter()
        counter_add("zk.pipeline.batches")
        yielded = 0
        attempt = 0
        while yielded < n:
            inner = self._iter_window(paths, yielded, window, missing_ok,
                                      op, decode, watch)
            try:
                try:
                    for res in inner:
                        yielded += 1
                        if yielded == n:
                            # Account BEFORE the final yield: consumers like
                            # zip() abandon the generator at its last item,
                            # so code after the loop would never run.
                            counter_add(
                                "zk.pipeline.rtts_saved", n - -(-n // window)
                            )
                            hist_observe(
                                "zk.pipeline.batch_ms",
                                (time.perf_counter() - t0) * 1e3,
                            )
                        yield res
                finally:
                    # Prompt close on any exit (incl. the caller abandoning
                    # THIS generator): the window helper's own finally then
                    # drains its in-flight replies.
                    inner.close()
            except (OSError, ZkConnectionError) as e:
                attempt += 1
                if attempt > retries:
                    raise
                self._reconnect(attempt, retries, e)

    def _iter_window(
        self,
        paths: Sequence[str],
        start: int,
        window: int,
        missing_ok: bool,
        op: int,
        decode,
        watch: bool = False,
    ) -> Iterator[object]:
        """One session's attempt at positions ``start..n-1`` of a pipelined
        batch (the replay loop in :meth:`_iter_pipelined` re-enters here
        after a reconnect). Yields results in position order; transport
        failures raise :class:`ZkConnectionError`/``OSError`` to the replay
        loop."""
        n = len(paths)
        pending: dict = {}   # xid -> request position
        ready: dict = {}     # position -> decoded result | None | ZkWireError
        sent = start
        yielded = start
        failed = False       # stop filling the window once an error lands
        desynced = False     # socket state unknown: draining cannot help
        try:
            while yielded < n:
                while sent < n and len(pending) < window and not failed:
                    self._xid += 1
                    self._send_frame(
                        struct.pack(">ii", self._xid, op)
                        + _pack_str(self._path(paths[sent]))
                        + (b"\x01" if watch else b"\x00")
                    )
                    pending[self._xid] = sent
                    sent += 1
                    if len(pending) > self._max_in_flight:
                        self._max_in_flight = len(pending)
                        gauge_set(
                            "zk.pipeline.in_flight", self._max_in_flight
                        )
                if pending:
                    try:
                        rxid, err, r = self._recv_reply()
                    except socket.timeout:
                        desynced = True
                        raise ZkConnectionError(
                            f"timed out waiting for {len(pending)} pipelined "
                            f"ZooKeeper replies (window {window}, first "
                            f"outstanding path "
                            f"{paths[min(pending.values())]!r})"
                        ) from None
                    pos = pending.pop(rxid, None)
                    if pos is None:
                        desynced = True
                        raise ZkConnectionError(
                            f"ZooKeeper reply xid {rxid} matches no "
                            f"in-flight pipelined request "
                            f"(window {sorted(pending)})"
                        )
                    if err == ERR_NONODE and missing_ok:
                        ready[pos] = None  # degraded: caller skips this path
                    elif err == ERR_NONODE:
                        ready[pos] = NoNodeError(
                            f"znode does not exist: {paths[pos]!r} "
                            f"(err {err})"
                        )
                        failed = True
                    elif err != 0:
                        ready[pos] = ZkWireError(
                            f"ZooKeeper error {err} for {paths[pos]!r}"
                        )
                        failed = True
                    else:
                        ready[pos] = decode(r)
                while yielded in ready:
                    res = ready[yielded]
                    if isinstance(res, ZkWireError):
                        if pending:  # drain the in-flight window first so
                            break    # the session stays usable after raise
                        raise res
                    del ready[yielded]
                    yielded += 1
                    yield res
        finally:
            # Early abandonment (break/GeneratorExit) leaves replies for the
            # in-flight window unread on the socket; the next serial call
            # would mis-pair them as stale xids. Drain them here — unless the
            # socket is already desynced/broken, where reading again can only
            # block or re-fail (swallowed: the original error wins).
            if pending and not desynced:
                try:
                    while pending:
                        rxid, _, _ = self._recv_reply()
                        pending.pop(rxid, None)
                except (OSError, ZkWireError):  # kalint: disable=KA008 -- best-effort drain; the original error wins
                    pass

    def get_many(
        self, paths: Sequence[str], missing_ok: bool = False
    ) -> List[Optional[Tuple[bytes, ZnodeStat]]]:
        """Batch primitive over :meth:`iter_get`: all results at once, in
        request order (``None`` per missing path under ``missing_ok``)."""
        return list(self.iter_get(paths, missing_ok=missing_ok))

    # -- writes (serial only; never pipelined, never blindly replayed) -----

    def _write_call(self, op: int, payload: bytes, landed):
        """The serial write RPC under the module write-safety rule. One
        request, one reply, never inside a pipelined window (kalint KA010).

        On a TRANSPORT failure the socket state is unknown — the request may
        or may not have been applied server-side — so unlike the read path
        this never blindly re-issues: it re-establishes the session, calls
        the ``landed`` probe (a read against the fresh session: does the
        server already show this write's effect?), and only re-sends when
        the write provably did not land. Returns the reply reader, or
        ``None`` when the landed-probe confirmed the effect (the reply bytes
        were lost with the old socket). Server-reported errors (NodeExists,
        NoNode, BadVersion) propagate untouched — they are answers."""
        if self._sock is None:
            raise ZkWireError("ZooKeeper session is not started")
        from ..utils.env import env_int

        retries = env_int("KA_ZK_SESSION_RETRIES")
        attempt = 0
        while True:
            self._xid += 1
            xid = self._xid
            try:
                # zk.writes is owned by the BACKEND layer (one count per
                # wave submission on every backend, comparable across
                # them); this layer's frame counters already account the
                # wire traffic.
                with hist_ms("zk.op_ms"):
                    return self._call_inner(op, xid, payload)
            except (OSError, ZkConnectionError) as e:
                attempt += 1
                if attempt > retries:
                    raise
                self._reconnect(attempt, retries, e)
                # Read-back, then decide (NEVER replay blind): the probe
                # runs on the fresh session through the ordinary retrying
                # read path.
                if landed():
                    counter_add("zk.write_readback_confirmed")
                    print(
                        "kafka-assigner: write reply lost with the session "
                        "but the read-back shows it landed; not re-issuing",
                        file=sys.stderr,
                    )
                    return None

    def create(self, path: str, value: bytes = b"", makepath: bool = False,
               **_kazoo_compat) -> str:
        """Create a plain persistent znode (kazoo-compatible surface,
        including ``makepath``; the world:anyone ACL the admin znodes use).
        The landed probe treats "exists with exactly the written bytes" as
        success — an existing node with OTHER bytes re-raises the server's
        NodeExists on the re-issue, exactly like an uninterrupted race
        would."""
        full = self._path(path)

        def _landed() -> bool:
            try:
                data, _ = self.get(path)
            except NoNodeError:
                return False
            return data == value

        if makepath:
            # kazoo semantics: materialize missing parents first (empty
            # persistent znodes; a parent created by somebody else in the
            # meantime is fine). Serial creates, shallowest first. Parents
            # are probed/created on the already-chrooted full path, so the
            # raw exists opcode is used instead of the chroot-prefixing
            # public surface.
            segs = full.strip("/").split("/")[:-1]
            parent = ""
            for seg in segs:
                parent = f"{parent}/{seg}"

                def _parent_landed(p: str = parent) -> bool:
                    try:
                        r = self._call(OP_EXISTS, _pack_str(p) + b"\x00")
                    except NoNodeError:
                        return False
                    r.read_stat()
                    return True

                try:
                    if not _parent_landed():
                        self._write_call(
                            OP_CREATE,
                            _pack_str(parent) + _pack_buffer(b"")
                            + _OPEN_ACL + struct.pack(">i", 0),
                            _parent_landed,
                        )
                except NodeExistsError:  # kalint: disable=KA008 -- lost a benign parent-create race; the parent exists, which is the goal
                    pass
        payload = (
            _pack_str(full) + _pack_buffer(value) + _OPEN_ACL
            + struct.pack(">i", 0)  # flags: persistent, non-sequential
        )
        r = self._write_call(OP_CREATE, payload, _landed)
        return r.read_str() if r is not None else full

    def set_data(self, path: str, value: bytes,
                 version: int = -1) -> Optional[ZnodeStat]:
        """``setData`` with kazoo's ``set`` semantics (version -1 = any).
        Landed probe: the znode now carries exactly the written bytes."""

        def _landed() -> bool:
            try:
                data, _ = self.get(path)
            except NoNodeError:
                return False
            return data == value

        payload = (
            _pack_str(self._path(path)) + _pack_buffer(value)
            + struct.pack(">i", version)
        )
        r = self._write_call(OP_SET_DATA, payload, _landed)
        return r.read_stat() if r is not None else None

    #: kazoo duck-type alias (``KazooClient.set``).
    set = set_data

    def delete(self, path: str, version: int = -1,
               **_kazoo_compat) -> None:
        """Delete a znode. Landed probe: the znode is gone."""

        def _landed() -> bool:
            return self.exists(path) is None

        payload = _pack_str(self._path(path)) + struct.pack(">i", version)
        self._write_call(OP_DELETE, payload, _landed)

    # -- teardown ---------------------------------------------------------

    def stop(self) -> None:
        if self._sock is None:
            return
        try:
            self._xid += 1
            self._send_frame(struct.pack(">ii", self._xid, OP_CLOSE))
            # best effort: read the close ack so the server sees a clean end
            self._sock.settimeout(1.0)
            try:
                self._recv_frame()
            except (OSError, ZkWireError):  # kalint: disable=KA008 -- best-effort close ack; the session is ending either way
                pass
        except OSError:  # kalint: disable=KA008 -- close of an already-dead socket; nothing left to report to
            pass

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
