"""Project-native static analysis.

- :mod:`.kalint` — the AST linter enforcing the knob-registry and
  jit-boundary house rules (rules KA001-KA005; ``python -m
  kafka_assigner_tpu.analysis.kalint``).
- :mod:`.knobdoc` — generates the README "Tuning knobs" table from the
  declarative registry in ``utils/env.py`` (``--check`` catches docs drift).

No eager re-exports: both submodules double as ``python -m`` entry points,
and importing them here would shadow that (runpy's double-import warning).
"""
