"""Project-native static analysis.

- :mod:`.kalint` — the interprocedural static analyzer (rules
  KA000-KA017): per-module AST checks plus a project-wide resolution
  layer (import graph, symbol tables, call graph) feeding a taint engine
  (traced set across module boundaries, solve-lock-held set) and a
  content-hash analysis cache. ``python -m
  kafka_assigner_tpu.analysis.kalint`` (``--explain KA0NN`` for call
  chains, ``--format json`` for CI).
- :mod:`.knobdoc` — generates the README "Tuning knobs" table from the
  declarative registry in ``utils/env.py`` (``--check`` catches docs
  drift).
- :mod:`.ruledoc` — generates the README kalint rule table from the
  ``RULE_DOCS`` catalog (``--check`` catches rule-doc drift the same
  way).

No eager re-exports: the submodules double as ``python -m`` entry points,
and importing them here would shadow that (runpy's double-import warning).
"""
