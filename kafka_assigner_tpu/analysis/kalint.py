"""``kalint`` — the project-native AST linter.

The system's value proposition is byte-compatibility with the reference
assigner under a large surface of tuning knobs; the two correctness risks
that grow with the codebase are silent config drift (a knob read raw,
bypassing the loud-ignore house rule in ``utils/env.py``) and host-sync
leaking into jitted solver paths. ``kalint`` machine-checks both:

====== =====================================================================
rule   meaning
====== =====================================================================
KA000  meta: unparsable file, or a suppression comment without a reason
KA001  raw ``os.environ``/``os.getenv`` access to a ``KA_*`` knob outside
       the registry module (``utils/env.py``) — use the typed accessors
KA002  host-sync / nondeterminism call (``jax.device_get``, ``.item()``,
       ``np.asarray``, ``time.*`` clocks, ``random.*``) inside kernel
       modules (``ops/``) or inside any function traced by ``jax.jit``
KA003  a ``KA_*`` string literal that does not resolve to a registered
       knob (catches typos at lint time instead of silently-unset knobs)
KA004  a registered knob missing from the README knob table (docs drift;
       the table is generated — ``python -m ...analysis.knobdoc --write``)
KA005  plan/golden JSON emission (``json.dumps``/``json.dump``) outside
       ``io/json_io.py``'s byte-compat helpers
KA006  a ``jnp.`` / ``jax.numpy`` call at module import time (module scope,
       class bodies, decorators, default arguments) — imports must stay
       cheap and backend-agnostic; build arrays lazily inside functions
KA007  a jit-traced function closes over a mutable module-level global
       (reads a module-scope list/dict/set binding, or rebinds any global
       via ``global``) — trace-time capture freezes the value at first
       compile, so later mutations are silently ignored by every cached
       executable; pass the value as an argument or bind it immutably
KA008  an ``except`` clause that swallows its exception silently (a body
       that is nothing but ``pass`` or a bare ``continue``) — a robustness
       layer lives or dies on failures staying visible: log it, count it,
       re-raise it, or suppress with a written reason
KA009  a jitted ``ops/`` entry point (a ``*_jit`` name from
       ``ops.assignment``) dispatched outside a registered bucket-boundary
       module — every array crossing into ``ops/`` must be padded to a
       registered bucket size (``models/problem.py``: partition/node axes
       multiples of 8, batch axis powers of two), and only the boundary
       modules build their arrays through that encode layer (the program
       store contract-checks their shapes at runtime,
       ``utils/programstore.py:BucketContract``). An ad-hoc dispatch site
       would silently explode the per-signature compile/program caches
KA010  a ZooKeeper WRITE opcode (``OP_CREATE``/``OP_SET_DATA``/
       ``OP_DELETE``) referenced outside the wire client's serial write
       methods (``io/zkwire.py``: ``create``/``set_data``/``delete``) —
       the write-safety rule (ISSUE 7): writes are never pipelined through
       the xid window and never blindly replayed after session
       re-establishment, so no other code may build a write frame
KA011  a ``while True`` loop containing a blocking socket/poll call
       (``recv*``, ``accept``, ``poll``, ``select``, ``sleep``) whose
       enclosing function consults NO deadline: neither a registered
       ``KA_*`` knob whose name carries TIMEOUT/INTERVAL/RETRIES/DEADLINE
       nor a ``.settimeout(...)`` call — a resident daemon must not be
       able to regress into an unbounded wait (ISSUE 8); loops genuinely
       bounded elsewhere carry a reasoned suppression naming the bound
KA012  daemon request-handling code (any module under ``daemon/`` except
       ``supervisor.py``/``state.py``) reading a ``.backend`` or ``.state``
       attribute — reaching into a supervisor's session or cache from the
       routing/service layer is CROSS-BULKHEAD access (ISSUE 9): one
       cluster's failure domain must stay behind its owning
       ``ClusterSupervisor``'s methods, or a handler can trivially couple
       two clusters' fates (the exact coupling the bulkheads exist to
       forbid)
KA013  a metric/span name literal passed to the obs write API
       (``counter_add``/``gauge_set``/``hist_observe``/``hist_ms``/
       ``span``/``record_span``, plus the supervisor's ``_count``/
       ``_metric`` wrappers and ``span``'s ``hist=`` keyword) that is not
       declared in the name registry (``obs/names.py``) — a typo'd metric
       name vanishes SILENTLY today (the registry creates entries on
       first write, dashboards query the name that never arrives), so
       names are declared once and machine-checked like knobs (KA003's
       twin for the telemetry namespace); dynamic names (f-strings,
       ``_metric(...)`` results) are the registered composition points
       and pass through
KA014  a metric registered in ``obs/names.py:METRIC_NAMES`` that neither
       carries a recognized unit suffix on its last dotted segment
       (``_ms``/``_bytes``/``_frac``/``_total``/``_seconds``, or the bare
       token as the whole segment, e.g. ``zk.bytes``) nor sits in the
       declared ``UNITLESS_METRICS`` allowlist — a dashboard reading
       ``foo.latency`` cannot know ms from seconds, so every name states
       its unit in the name or is consciously declared unitless; stale
       allowlist entries (names no longer registered) and entries that
       ALSO carry a unit suffix are findings too (the allowlist must stay
       an exact complement, not a dumping ground)
====== =====================================================================

Suppression: put ``# kalint: disable=KA002 -- <reason>`` on the offending
line or on its own line directly above. The reason is mandatory — a
reasonless suppression is itself a finding (KA000) and does not suppress.

Run ``python -m kafka_assigner_tpu.analysis.kalint`` (no args: lint the whole
package plus the README check; exit non-zero on findings), or pass explicit
file paths. ``scripts/lint.sh`` wires this into the tier-1 gate.
"""
from __future__ import annotations

import argparse
import ast
import io
import re
import sys
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Sequence, Set

RULES = {
    "KA000": "meta finding (syntax error / reasonless suppression)",
    "KA001": "raw os.environ access to a KA_* knob outside the registry",
    "KA002": "host-sync or nondeterminism call in traced kernel code",
    "KA003": "KA_* string literal does not resolve to a registered knob",
    "KA004": "registered knob missing from the README knob table",
    "KA005": "plan JSON emission outside io/json_io.py",
    "KA006": "jnp./jax.numpy call at module import time",
    "KA007": "jit-traced function closes over a mutable module-level global",
    "KA008": "except clause swallows the exception silently (pass/continue)",
    "KA009": "ops/ jit entry dispatched outside a bucket-boundary module",
    "KA010": "ZooKeeper write opcode outside the serial write path",
    "KA011": "unbounded blocking recv/poll loop (no deadline knob consulted)",
    "KA012": "cross-bulkhead access: daemon handler reaches into a "
             "supervisor's backend/cache",
    "KA013": "metric/span name literal not declared in the obs name "
             "registry (obs/names.py)",
    "KA014": "registered metric carries no unit suffix and is not in the "
             "unitless allowlist (obs/names.py)",
}

#: Modules whose ENTIRE body is treated as traced kernel code (KA002): these
#: compile under jit wholesale, and even their module-level helpers feed
#: trace-time constants, so host clocks/randomness have no business anywhere
#: in them.
KERNEL_MODULES = frozenset({"ops/assignment.py", "ops/pallas_leadership.py"})
#: The one module allowed to touch os.environ for KA_* knobs (KA001).
REGISTRY_MODULE = "utils/env.py"
#: The one module allowed to emit plan JSON (KA005).
JSON_BOUNDARY_MODULE = "io/json_io.py"
#: Modules allowed to dispatch the jitted ops/ entry points (KA009): each
#: builds its arrays through models/problem.py's bucketing layer and its
#: dispatches are shape-contract-checked at runtime by the program store
#: (utils/programstore.py:BucketContract).
BUCKET_BOUNDARY_MODULES = frozenset({
    "solvers/tpu.py", "solvers/warmup.py", "parallel/whatif.py",
})
#: The wire-client module and the only functions in it allowed to reference
#: the ZooKeeper WRITE opcodes (KA010): the serial, read-back-then-decide
#: write methods. The pipelined window helpers and every other module must
#: never see a write opcode.
WIRE_MODULE = "io/zkwire.py"
WRITE_OPCODES = frozenset({"OP_CREATE", "OP_SET_DATA", "OP_DELETE"})
SERIAL_WRITE_FUNCS = frozenset({"create", "set_data", "delete"})
#: KA012: the daemon package's bulkhead boundary. ``supervisor.py`` OWNS a
#: cluster's backend/cache; ``state.py`` IS the cache. Everything else
#: under ``daemon/`` (the routing/service layer, future middleware) must go
#: through supervisor methods — a ``.backend``/``.state`` attribute read
#: there is cross-bulkhead access.
DAEMON_PKG_PREFIX = "daemon/"
DAEMON_BULKHEAD_MODULES = frozenset({
    "daemon/supervisor.py", "daemon/state.py",
})
BULKHEAD_ATTRS = frozenset({"backend", "state"})

_KNOB_RE = re.compile(r"KA_[A-Z][A-Z0-9_]*")
_SUPPRESS_RE = re.compile(
    r"#\s*kalint:\s*disable=([A-Z0-9, ]+?)\s*(?:--\s*(\S.*))?$"
)
_TIME_CALLS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns", "sleep",
})
_NUMPY_ALIASES = frozenset({"np", "numpy", "onp"})


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _is_name(node: ast.AST, name: str) -> bool:
    return isinstance(node, ast.Name) and node.id == name


def _const_str(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _knob_literal(node: ast.AST):
    v = _const_str(node)
    return v if v is not None and _KNOB_RE.fullmatch(v) else None


def _suppressions(src: str, path: str):
    """Per-line ``# kalint: disable=...`` map. A suppression covers its own
    line and the line below (so it can sit above a long statement). A
    suppression without a reason is a KA000 finding and suppresses nothing
    (the reason IS the audit trail).

    Only real COMMENT tokens count — suppression syntax quoted inside a
    string literal or docstring (e.g. this module's own docs) is neither a
    suppression nor a finding."""
    table: dict = {}
    metas: List[Finding] = []
    try:
        comments = [
            t for t in tokenize.generate_tokens(io.StringIO(src).readline)
            if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        comments = []  # unparsable source is KA000 via ast.parse already
    for tok in comments:
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            metas.append(Finding(
                "KA000", path, lineno, tok.start[1] + m.start() + 1,
                "suppression requires a reason: "
                "'# kalint: disable=KAnnn -- <why>'",
            ))
            continue
        table.setdefault(lineno, set()).update(rules)
        table.setdefault(lineno + 1, set()).update(rules)
    return table, metas


# --- KA002 machinery --------------------------------------------------------

def _banned_call(node: ast.Call):
    """Message when ``node`` is one of the banned host-sync/nondeterminism
    calls, else None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "device_get" and _is_name(f.value, "jax"):
        return "jax.device_get(...) host sync"
    if f.attr == "item" and not node.args and not node.keywords:
        return ".item() host sync"
    if f.attr == "asarray" and isinstance(f.value, ast.Name) \
            and f.value.id in _NUMPY_ALIASES:
        return f"{f.value.id}.asarray(...) host materialization"
    if _is_name(f.value, "time") and f.attr in _TIME_CALLS:
        return f"time.{f.attr}() wall clock / host nondeterminism"
    if _is_name(f.value, "random"):
        return f"random.{f.attr}() nondeterminism"
    if (
        isinstance(f.value, ast.Attribute)
        and f.value.attr == "random"
        and isinstance(f.value.value, ast.Name)
        and f.value.value.id in _NUMPY_ALIASES
    ):
        return f"{f.value.value.id}.random.{f.attr}() nondeterminism"
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` or a bare ``jit`` name (``from jax import jit``)."""
    return _is_name(node, "jit") or (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and _is_name(node.value, "jax")
    )


def _jit_roots(tree: ast.AST) -> Set[str]:
    """Function names handed to ``jax.jit`` in this module — as call
    arguments (``f_jit = jax.jit(f, ...)``) or decorators (``@jax.jit``,
    ``@jax.jit(...)``, ``@partial(jax.jit, ...)``)."""
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                roots.add(node.args[0].id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    roots.add(node.name)
                elif isinstance(dec, ast.Call):
                    if _is_jit_expr(dec.func):
                        roots.add(node.name)
                    elif (
                        (_is_name(dec.func, "partial")
                         or (isinstance(dec.func, ast.Attribute)
                             and dec.func.attr == "partial"))
                        and dec.args and _is_jit_expr(dec.args[0])
                    ):
                        roots.add(node.name)
    return roots


def _traced_functions(tree: ast.AST):
    """Transitive closure of jit roots over same-module calls-by-name:
    the statically knowable approximation of 'code that runs under
    trace'. Cross-module callees are covered by KERNEL_MODULES."""
    funcs = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    traced = {name for name in _jit_roots(tree) if name in funcs}
    frontier = list(traced)
    while frontier:
        fn = funcs[frontier.pop()]
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                if callee in funcs and callee not in traced:
                    traced.add(callee)
                    frontier.append(callee)
    return [funcs[name] for name in sorted(traced)]


# --- rule passes ------------------------------------------------------------

def _os_bindings(tree: ast.AST):
    """Names the module binds to the ``os`` module, ``os.environ``, and
    ``os.getenv`` — ``import os as o`` / ``from os import environ as env`` /
    ``from os import getenv`` all count, so the import form cannot be used
    to slip a raw knob read past KA001."""
    os_mods = {"os"}
    environs: Set[str] = set()
    getenvs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "os":
                    os_mods.add(alias.asname or "os")
        elif isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "environ":
                    environs.add(bound)
                elif alias.name == "getenv":
                    getenvs.add(bound)
    return os_mods, environs, getenvs


def _check_ka001(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath == REGISTRY_MODULE:
        return []
    os_mods, environs, getenvs = _os_bindings(tree)

    def is_environ(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id in environs:
            return True
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id in os_mods
        )

    def is_getenv(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id in getenvs:
            return True
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "getenv"
            and isinstance(node.value, ast.Name)
            and node.value.id in os_mods
        )

    out: List[Finding] = []

    def hit(node, key):
        out.append(Finding(
            "KA001", path, node.lineno, node.col_offset + 1,
            f"raw os.environ access to {key!r}; use the typed accessors in "
            "utils/env.py (env_int/env_float/env_bool/env_choice/env_str)",
        ))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "pop", "setdefault")
                and is_environ(f.value)
                and node.args
            ):
                key = _knob_literal(node.args[0])
                if key:
                    hit(node, key)
            elif is_getenv(f) and node.args:
                key = _knob_literal(node.args[0])
                if key:
                    hit(node, key)
        elif isinstance(node, ast.Subscript) and is_environ(node.value):
            key = _knob_literal(node.slice)
            if key:
                hit(node, key)
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1:
            if (
                isinstance(node.ops[0], (ast.In, ast.NotIn))
                and is_environ(node.comparators[0])
            ):
                key = _knob_literal(node.left)
                if key:
                    hit(node, key)
    return out


def _check_ka002(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath in KERNEL_MODULES:
        scopes: Iterable[ast.AST] = [tree]
        where = "kernel module"
    else:
        scopes = _traced_functions(tree)
        where = "jit-traced function"
    out: List[Finding] = []
    seen: Set[int] = set()
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and id(node) not in seen:
                seen.add(id(node))
                msg = _banned_call(node)
                if msg:
                    out.append(Finding(
                        "KA002", path, node.lineno, node.col_offset + 1,
                        f"{msg} in {where} (host work must stay outside the "
                        "traced solve)",
                    ))
    return out


def _check_ka003(tree: ast.AST, knobs: Set[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        v = _knob_literal(node)
        if v is not None and v not in knobs:
            out.append(Finding(
                "KA003", path, node.lineno, node.col_offset + 1,
                f"{v!r} is not a registered knob (typo? declare it in "
                "utils/env.py)",
            ))
    return out


def _check_ka005(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath == JSON_BOUNDARY_MODULE:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("dumps", "dump")
            and _is_name(node.func.value, "json")
        ):
            out.append(Finding(
                "KA005", path, node.lineno, node.col_offset + 1,
                f"json.{node.func.attr}(...) outside io/json_io.py; plan "
                "payloads must go through the byte-compat helpers (suppress "
                "with a reason for non-plan payloads)",
            ))
    return out


def _jnp_module_aliases(tree: ast.AST) -> Set[str]:
    """Names this module binds to ``jax.numpy``: ``import jax.numpy as X``
    and ``from jax import numpy as X``. The conventional ``jnp`` is always
    included — most modules import it lazily inside functions, and a stray
    module-level ``jnp.zeros(...)`` pasted above such an import is exactly
    the bug class KA006 exists for (NameError today, silent backend init
    after the next refactor)."""
    aliases = {"jnp"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.numpy" and alias.asname:
                    aliases.add(alias.asname)
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "numpy":
                    aliases.add(alias.asname or "numpy")
    return aliases


def _deferred_nodes(tree: ast.AST) -> Set[int]:
    """ids of AST nodes that do NOT execute at import time: function and
    lambda bodies. Decorators, default arguments, and class bodies all run
    at import and are deliberately left in."""
    deferred: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    deferred.add(id(sub))
        elif isinstance(node, ast.Lambda):
            for sub in ast.walk(node.body):
                deferred.add(id(sub))
    return deferred


def _check_ka006(tree: ast.AST, path: str) -> List[Finding]:
    aliases = _jnp_module_aliases(tree)
    deferred = _deferred_nodes(tree)
    out: List[Finding] = []
    for node in ast.walk(tree):
        if id(node) in deferred or not isinstance(node, ast.Call):
            continue
        f = node.func
        parts: List[str] = []
        while isinstance(f, ast.Attribute):
            parts.append(f.attr)
            f = f.value
        if not isinstance(f, ast.Name) or not parts:
            continue
        root = f.id
        # `jnp.zeros(...)` (any registered alias) or the spelled-out
        # `jax.numpy.zeros(...)` chain; `jax.jit(...)` etc. stay legal.
        if root in aliases or (root == "jax" and parts[-1] == "numpy"):
            dotted = ".".join([root] + list(reversed(parts)))
            out.append(Finding(
                "KA006", path, node.lineno, node.col_offset + 1,
                f"{dotted}(...) at module import time (imports must stay "
                "cheap and backend-agnostic; build arrays lazily inside "
                "functions)",
            ))
    return out


#: Constructors whose module-scope result is a mutable container (KA007).
_MUTABLE_CTORS = frozenset({
    "list", "dict", "set", "bytearray", "defaultdict", "deque", "Counter",
    "OrderedDict",
})


def _module_mutable_globals(tree: ast.AST) -> Set[str]:
    """Names bound at module scope to obviously-mutable containers: literal
    list/dict/set displays, comprehensions, or calls to the stdlib mutable
    constructors. Module-scope statements only (incl. inside module-level
    ``if``/``try`` blocks) — function and class bodies bind elsewhere."""

    def value_is_mutable(node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None
            )
            return name in _MUTABLE_CTORS
        return False

    out: Set[str] = set()

    def scan(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and value_is_mutable(stmt.value):
                for target in stmt.targets:
                    for n in ast.walk(target):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and value_is_mutable(stmt.value) \
                    and isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
            # recurse into compound module-scope statements
            for attr in ("body", "orelse", "finalbody"):
                scan(getattr(stmt, attr, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                scan(handler.body)

    scan(tree.body)  # type: ignore[attr-defined]
    return out


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names the function binds locally (parameters, assignments, loop and
    with targets, comprehension targets, inner defs): a Load of such a name
    is not a global read. Over-approximates (any binding anywhere in the
    function shadows for the whole check) — that only suppresses findings,
    never fabricates them."""
    bound: Set[str] = set()
    args = fn.args
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        bound.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            if node is not fn:
                bound.add(node.name)
        elif isinstance(node, ast.alias):
            bound.add(node.asname or node.name.split(".")[0])
    return bound


def _check_ka007(tree: ast.AST, path: str) -> List[Finding]:
    mutable = _module_mutable_globals(tree)
    out: List[Finding] = []
    for fn in _traced_functions(tree):
        globals_declared: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
                out.append(Finding(
                    "KA007", path, node.lineno, node.col_offset + 1,
                    f"jit-traced function {fn.name!r} rebinds module "
                    f"global(s) {', '.join(node.names)} via 'global' (the "
                    "rebinding runs at trace time only; cached executables "
                    "never see it — return the value instead)",
                ))
        if not mutable:
            continue
        local = _local_bindings(fn) - globals_declared
        seen_names: Set[str] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in mutable
                and node.id not in local
                and node.id not in seen_names  # one finding per name per fn
            ):
                seen_names.add(node.id)
                out.append(Finding(
                    "KA007", path, node.lineno, node.col_offset + 1,
                    f"jit-traced function {fn.name!r} closes over mutable "
                    f"module global {node.id!r} (its value is frozen into "
                    "the compiled executable at trace time; later mutations "
                    "are silently ignored — pass it as an argument or bind "
                    "it immutably, e.g. tuple/frozenset/MappingProxyType)",
                ))
    return out


def _ops_jit_bindings(tree: ast.AST):
    """Names this module binds to ``ops.assignment`` ``*_jit`` entry points
    (``from ..ops.assignment import solve_batched_jit [as x]``) and names
    bound to the ``ops.assignment`` module itself (``from ..ops import
    assignment [as x]``, ``import ...ops.assignment as x``) — both forms can
    dispatch a kernel program."""
    entries: Set[str] = set()
    modules: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.endswith("ops.assignment"):
                for alias in node.names:
                    if alias.name.endswith("_jit"):
                        entries.add(alias.asname or alias.name)
            elif node.module.endswith("ops") or node.module == "ops":
                for alias in node.names:
                    if alias.name == "assignment":
                        modules.add(alias.asname or "assignment")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("ops.assignment") and alias.asname:
                    modules.add(alias.asname)
    return entries, modules


def _check_ka009(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath in BUCKET_BOUNDARY_MODULES or relpath in KERNEL_MODULES:
        return []
    entries, modules = _ops_jit_bindings(tree)
    if not entries and not modules:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        target = None
        if isinstance(f, ast.Name) and f.id in entries:
            target = f.id
        elif (
            isinstance(f, ast.Attribute)
            and f.attr.endswith("_jit")
            and isinstance(f.value, ast.Name)
            and f.value.id in modules
        ):
            target = f.attr
        if target:
            out.append(Finding(
                "KA009", path, node.lineno, node.col_offset + 1,
                f"ops kernel entry {target}(...) dispatched outside a "
                "bucket-boundary module (arrays crossing into ops/ must be "
                "padded to registered bucket sizes — models/problem.py "
                "_pad8/batch_bucket — and dispatched from "
                f"{sorted(BUCKET_BOUNDARY_MODULES)}, whose shapes the "
                "program store contract-checks at runtime)",
            ))
    return out


def _check_ka010(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    """A WRITE opcode reference (``OP_CREATE``/``OP_SET_DATA``/
    ``OP_DELETE``, as a bare name or an attribute like
    ``zkwire.OP_CREATE``) is legal only inside the wire client's serial
    write methods. The module-level constant DEFINITIONS (Store context)
    are exempt; every Load anywhere else — including zkwire's own pipelined
    helpers — is a finding."""
    out: List[Finding] = []

    def visit(node: ast.AST, func: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            child_func = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_func = child.name
            name = None
            if isinstance(child, ast.Name) \
                    and isinstance(child.ctx, ast.Load) \
                    and child.id in WRITE_OPCODES:
                name = child.id
            elif isinstance(child, ast.Attribute) \
                    and child.attr in WRITE_OPCODES:
                name = child.attr
            if name is not None and not (
                relpath == WIRE_MODULE and child_func in SERIAL_WRITE_FUNCS
            ):
                out.append(Finding(
                    "KA010", path, child.lineno, child.col_offset + 1,
                    f"ZooKeeper write opcode {name} referenced outside the "
                    "serial write path (io/zkwire.py "
                    f"{sorted(SERIAL_WRITE_FUNCS)}): writes are never "
                    "pipelined and never blindly replayed — route mutations "
                    "through the wire client's write methods",
                ))
            visit(child, child_func)

    visit(tree, None)
    return out


#: Call names that block on external progress (KA011): any ``recv*``
#: variant plus the accept/poll/select family and bare sleeps. Deliberately
#: name-based — the rule is a tripwire for new unbounded wait loops, not a
#: full escape analysis.
_BLOCKING_NAMES = frozenset({"accept", "poll", "select", "sleep"})
#: Substrings of knob names that count as a deadline consult (KA011).
_DEADLINE_TOKENS = ("TIMEOUT", "INTERVAL", "RETRIES", "DEADLINE")


def _is_blocking_call(node: ast.Call) -> bool:
    f = node.func
    name = None
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name is None:
        return False
    return "recv" in name or name in _BLOCKING_NAMES


def _scope_consults_deadline(scope: ast.AST) -> bool:
    """True when ``scope`` (function or module) reads a deadline-shaped
    registered knob (a ``KA_*`` literal carrying TIMEOUT/INTERVAL/RETRIES/
    DEADLINE) or sets a socket timeout — the evidence KA011 accepts that a
    blocking loop is bounded."""
    for node in ast.walk(scope):
        v = _knob_literal(node)
        if v is not None and any(tok in v for tok in _DEADLINE_TOKENS):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "settimeout"
        ):
            return True
    return False


def _check_ka011(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    consult_cache: dict = {}

    def consults(scope: ast.AST) -> bool:
        key = id(scope)
        if key not in consult_cache:
            consult_cache[key] = _scope_consults_deadline(scope)
        return consult_cache[key]

    def visit(node: ast.AST, scope: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child
            if (
                isinstance(child, ast.While)
                and isinstance(child.test, ast.Constant)
                and child.test.value in (True, 1)
                and any(
                    isinstance(n, ast.Call) and _is_blocking_call(n)
                    for n in ast.walk(child)
                )
                and not consults(child_scope)
            ):
                out.append(Finding(
                    "KA011", path, child.lineno, child.col_offset + 1,
                    "blocking recv/poll loop with no deadline: the "
                    "enclosing function consults no registered KA_* "
                    "timeout/interval/retries knob and sets no socket "
                    "timeout — bound the wait, or suppress with a reason "
                    "naming where the bound lives",
                ))
            visit(child, child_scope)

    visit(tree, tree)
    return out


def _check_ka012(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    """Daemon modules outside the bulkhead boundary must not read a
    ``.backend`` or ``.state`` attribute: the supervisor's session and
    cache are its failure domain, and the service/routing layer touching
    them directly couples clusters the bulkheads exist to isolate. Store
    contexts (assignments) are not reads and stay legal; genuinely-needed
    exceptions carry a reasoned suppression."""
    if not relpath.startswith(DAEMON_PKG_PREFIX) \
            or relpath in DAEMON_BULKHEAD_MODULES:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in BULKHEAD_ATTRS
        ):
            out.append(Finding(
                "KA012", path, node.lineno, node.col_offset + 1,
                f".{node.attr} read outside the bulkhead boundary "
                "(cross-bulkhead access): a supervisor's session/cache "
                "belongs to daemon/supervisor.py — route through the "
                "owning ClusterSupervisor's methods (handle, lifecycle, "
                "state_view, healthz_view, counters, ...)",
            ))
    return out


#: The obs write API whose literal first argument is a METRIC name (KA013).
METRIC_NAME_CALLS = frozenset({
    "counter_add", "gauge_set", "hist_observe", "hist_ms", "counter_value",
})
#: Calls whose literal first argument is a SPAN name.
SPAN_NAME_CALLS = frozenset({"span", "record_span"})
#: The daemon supervisor's name-composing wrappers: their literal first
#: argument may be either namespace (``_count`` feeds counters, ``_metric``
#: labels both metric and span names with ``@cluster``).
EITHER_NAME_CALLS = frozenset({"_count", "_metric"})


def _call_terminal_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _check_ka013(
    tree: ast.AST, path: str, metric_names, span_names
) -> List[Finding]:
    """Literal metric/span names must resolve against the declared registry
    (``obs/names.py``) — the KA003 posture for the telemetry namespace.
    Dynamic first arguments (f-strings, variables, ``self._metric(...)``)
    are skipped: they compose REGISTERED bases with runtime labels."""
    every = metric_names | span_names
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _call_terminal_name(node)
        if fname is None:
            continue
        table = table_desc = None
        if fname in METRIC_NAME_CALLS:
            table, table_desc = metric_names, "METRIC_NAMES"
        elif fname in SPAN_NAME_CALLS:
            table, table_desc = span_names, "SPAN_NAMES"
        elif fname in EITHER_NAME_CALLS:
            table, table_desc = every, "METRIC_NAMES/SPAN_NAMES"
        if table is not None:
            # The name may arrive positionally OR as name=... — both are
            # the same write; a keyword spelling must not bypass the rule.
            name_node = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None,
            )
            lit = _const_str(name_node) if name_node is not None else None
            if lit is not None and lit not in table:
                out.append(Finding(
                    "KA013", path, node.lineno, node.col_offset + 1,
                    f"{fname}({lit!r}) uses an undeclared name: a typo'd "
                    "metric/span vanishes silently — declare it in "
                    f"obs/names.py ({table_desc}) or fix the spelling",
                ))
        if fname in SPAN_NAME_CALLS:
            for kw in node.keywords:
                if kw.arg == "hist":
                    lit = _const_str(kw.value)
                    if lit is not None and lit not in metric_names:
                        out.append(Finding(
                            "KA013", path, kw.value.lineno,
                            kw.value.col_offset + 1,
                            f"span(hist={lit!r}) uses an undeclared "
                            "histogram name — declare it in obs/names.py "
                            "(METRIC_NAMES) or fix the spelling",
                        ))
    return out


def _check_ka008(tree: ast.AST, path: str) -> List[Finding]:
    """An ``except`` body that is exactly one ``pass`` or one bare
    ``continue`` handles nothing and records nothing — the exception
    vanishes. Any other body (a log call, a metric bump, a re-raise, even an
    assignment) is taken as deliberate handling; truly-intentional swallows
    carry a reasoned suppression, which IS the audit trail."""
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        body = node.body
        if len(body) == 1 and isinstance(body[0], (ast.Pass, ast.Continue)):
            what = "pass" if isinstance(body[0], ast.Pass) else "continue"
            out.append(Finding(
                "KA008", path, body[0].lineno, body[0].col_offset + 1,
                f"except clause swallows the exception silently (bare "
                f"{what}): log it, count it, re-raise, or suppress with a "
                "reason",
            ))
    return out


#: Unit tokens KA014 recognizes on a metric name's LAST dotted segment —
#: either the whole segment (``zk.bytes``) or a ``_token`` suffix
#: (``exec.wave_ms``). ``_total`` is listed for completeness although the
#: Prometheus renderer also appends it to counters mechanically.
METRIC_UNIT_TOKENS = ("ms", "bytes", "frac", "total", "seconds")


def _has_unit_suffix(name: str) -> bool:
    seg = name.rsplit(".", 1)[-1]
    return seg in METRIC_UNIT_TOKENS or any(
        seg.endswith("_" + tok) for tok in METRIC_UNIT_TOKENS
    )


def check_metric_units(
    metric_names=None, unitless=None,
    path: str = "kafka_assigner_tpu/obs/names.py",
) -> List[Finding]:
    """KA014: every registered metric either states its unit in its name or
    is consciously declared unitless (``obs/names.py:UNITLESS_METRICS``) —
    so a dashboard never guesses whether ``foo.latency`` is ms or seconds.
    Registry-level (one pass per lint run), not per-module: the names ARE
    the data, there is no AST to walk."""
    if metric_names is None or unitless is None:
        from ..obs.names import METRIC_NAMES, UNITLESS_METRICS

        if metric_names is None:
            metric_names = METRIC_NAMES
        if unitless is None:
            unitless = UNITLESS_METRICS
    out: List[Finding] = []
    for name in sorted(metric_names):
        if _has_unit_suffix(name):
            if name in unitless:
                out.append(Finding(
                    "KA014", path, 1, 1,
                    f"metric {name!r} carries a unit suffix AND sits in "
                    "UNITLESS_METRICS — pick one (the allowlist is for "
                    "names with genuinely no unit)",
                ))
            continue
        if name not in unitless:
            out.append(Finding(
                "KA014", path, 1, 1,
                f"metric {name!r} carries no unit suffix "
                f"({'/'.join('_' + t for t in METRIC_UNIT_TOKENS)} on its "
                "last segment) and is not declared in UNITLESS_METRICS — "
                "dashboards must never guess units: rename it or declare "
                "it unitless",
            ))
    for name in sorted(unitless):
        if name not in metric_names:
            out.append(Finding(
                "KA014", path, 1, 1,
                f"UNITLESS_METRICS entry {name!r} is not a registered "
                "metric (stale allowlist entry — remove it)",
            ))
    return out


def check_readme(readme_text: str, knobs=None, path: str = "README.md"):
    """KA004: every registered knob must appear in the README (the generated
    knob table keeps this true; drift means the table is stale)."""
    if knobs is None:
        from ..utils.env import KNOBS

        knobs = KNOBS
    names = knobs if not hasattr(knobs, "keys") else list(knobs)
    out: List[Finding] = []
    for name in names:
        # whole-name match: KA_FOO must not be satisfied by KA_FOO_BAR
        pat = r"(?<![A-Z0-9_])" + re.escape(name) + r"(?![A-Z0-9_])"
        if not re.search(pat, readme_text):
            out.append(Finding(
                "KA004", path, 1, 1,
                f"registered knob {name} is missing from the README knob "
                "table (regenerate: python -m "
                "kafka_assigner_tpu.analysis.knobdoc --write)",
            ))
    return out


# --- drivers ----------------------------------------------------------------

def lint_source(
    src: str,
    relpath: str,
    *,
    knobs: Set[str] | None = None,
    metric_names: Set[str] | None = None,
    span_names: Set[str] | None = None,
    path: str | None = None,
) -> List[Finding]:
    """Lint one module. ``relpath`` is the package-relative posix path (it
    selects the module class: registry / kernel / json boundary); ``path`` is
    the display path for findings (defaults to ``relpath``)."""
    path = path or relpath
    if knobs is None:
        from ..utils.env import KNOBS

        knobs = set(KNOBS)
    if metric_names is None or span_names is None:
        from ..obs.names import METRIC_NAMES, SPAN_NAMES

        if metric_names is None:
            metric_names = METRIC_NAMES
        if span_names is None:
            span_names = SPAN_NAMES
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(
            "KA000", path, e.lineno or 1, (e.offset or 0) + 1,
            f"syntax error: {e.msg}",
        )]
    suppress, findings = _suppressions(src, path)
    findings = list(findings)
    raw = (
        _check_ka001(tree, relpath, path)
        + _check_ka002(tree, relpath, path)
        + _check_ka003(tree, set(knobs), path)
        + _check_ka005(tree, relpath, path)
        + _check_ka006(tree, path)
        + _check_ka007(tree, path)
        + _check_ka008(tree, path)
        + _check_ka009(tree, relpath, path)
        + _check_ka010(tree, relpath, path)
        + _check_ka011(tree, path)
        + _check_ka012(tree, relpath, path)
        + _check_ka013(tree, path, set(metric_names), set(span_names))
    )
    for f in raw:
        if f.rule in suppress.get(f.line, ()):  # reasoned suppression
            continue
        findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def lint_package(root: Path | None = None) -> List[Finding]:
    """Lint every module of the installed package tree plus the README knob
    check; the empty list is the green state ``scripts/lint.sh`` gates on."""
    pkg = Path(root) if root else Path(__file__).resolve().parent.parent
    repo = pkg.parent
    findings: List[Finding] = []
    for p in sorted(pkg.rglob("*.py")):
        rel = p.relative_to(pkg).as_posix()
        try:
            display = p.relative_to(repo).as_posix()
        except ValueError:
            display = str(p)
        findings.extend(
            lint_source(p.read_text(encoding="utf-8"), rel, path=display)
        )
    readme = repo / "README.md"
    if readme.is_file():
        findings.extend(check_readme(readme.read_text(encoding="utf-8")))
    findings.extend(check_metric_units())
    return findings


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kalint", description="project-native static analysis "
        "(knob registry + jit-boundary house rules)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files to lint (default: the whole package + "
                             "README knob check)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}  {desc}")
        return 0
    if args.paths:
        pkg = Path(__file__).resolve().parent.parent
        findings: List[Finding] = []
        for raw in args.paths:
            p = Path(raw).resolve()
            try:
                rel = p.relative_to(pkg).as_posix()
            except ValueError:
                rel = p.name
            findings.extend(
                lint_source(p.read_text(encoding="utf-8"), rel, path=raw)
            )
    else:
        findings = lint_package()
    for f in findings:
        print(f)
    n = len(findings)
    print(
        f"kalint: {n} finding(s)" if n else "kalint: clean",
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
