"""Determinism taint layer: statically prove the byte-identity invariant.

The system's north-star contract (PAPER.md §7, PARITY.md) is that every
output surface — plan JSON, daemon envelopes, journals, flight dumps,
snapshots, Prometheus exposition — is byte-identical across runs,
processes, and coalescing regimes. Historically the repo only *repaired*
ordering bugs after they surfaced (the snapshot topic-order fix, the
journal/flight ordering pins); this layer finds them before they ship,
by source→sink taint over the ISSUE 12 interprocedural call graph.

**Sources** (nondeterminism origins):

- set iteration / set-typed comprehensions / a set materialized through
  ``list()``/``tuple()`` — order is ``PYTHONHASHSEED``-dependent (KA024);
- ``concurrent.futures.as_completed`` / queue-drain order — completion
  order is scheduling-dependent (KA024);
- ``os.listdir``/``os.scandir``/``glob.*``/``Path.iterdir`` — the OS
  returns directory entries in arbitrary order (KA026);
- wall-clock / ``random.*`` / ``uuid`` / ``id()`` / ``hash()`` value
  reads (KA025). Monotonic clocks (``time.monotonic``/``perf_counter``)
  are exempt by construction: they price deadlines and spans, never
  produce an absolute timestamp that could land in an envelope;
- a thread-racy collection (written from another PR 16 thread entry)
  iterated — or its ``dict`` views drained — mid-mutation (KA027).

**Sanitizers**: ``sorted(...)`` (directly, or consuming a comprehension
over the source), ``.sort()`` on the materialized sequence, canonical-
order helpers (a callee whose name contains ``canonical`` or ``sorted``),
and the order-insensitive consumers (``len``/``min``/``max``/``sum``/
``any``/``all``/membership tests/set algebra), which never observe order
at all. Sanitizing is PER EXPRESSION: a ``sorted()`` on the wrong axis
discharges nothing, and ``random.shuffle`` re-taints a sequence that was
already sorted. KA027 is the exception — ``sorted()`` does not discharge
it (iterating a collection another thread mutates can raise or tear
regardless of later ordering); only a snapshot taken under a lock the
writers hold does.

**Sinks** (byte-pinned surfaces): ``json.dumps``/``json.dump`` call
sites anywhere in the package (plan emission, envelope builders,
journal/flight/ledger/snapshot persistence), the declared in-project
byte surfaces that do not literally call ``json.dumps`` (Prometheus
exposition rendering in ``obs/promtext.py``), and ``print``/``sys.stdout``
writes in package modules (the CLI byte contract; ``scripts/`` harness
progress logging is exempt — smoke-script stdout is operator narration,
not a pinned surface, and their byte assertions compare *daemon* output).

A function is **sink-reaching** when a sink is reachable from it over
the call graph; source findings fire only inside sink-reaching functions
and carry the function→…→sink chain for ``--explain`` and SARIF
``codeFlows``. Everything here under-approximates like the resolver
itself: an unresolvable call contributes no reach, an expression the
local classifier cannot type is silent — CLEAN means "no *demonstrable*
order leak", the same posture as every other kalint layer.

Timestamps are legal in envelopes at DECLARED field names only:
:data:`TS_FIELD_ALLOWLIST` / :data:`TS_FIELD_TOKENS` (``ts``,
``request_id``, ``*_uptime_*`` …) — a wall-clock read stamped into one
of those fields is the contract working, not a finding.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding
from .resolve import FunctionInfo, Project

# -- sink taxonomy ------------------------------------------------------------

#: ``json.<name>`` serialization calls that pin bytes at the call site.
JSON_SINK_NAMES = frozenset({"dumps", "dump"})

#: Module aliases a ``<mod>.dumps(...)`` sink call may be qualified with.
JSON_MODULE_NAMES = frozenset({"json", "_json"})

#: Declared in-project byte surfaces that do not literally call
#: ``json.dumps``: (relpath, function name) -> surface description.
DECLARED_SINK_FUNCS: Dict[Tuple[str, str], str] = {
    ("obs/promtext.py", "render"): "Prometheus exposition rendering",
}

#: Module prefix whose stdout is harness narration, not a pinned surface.
SCRIPTS_PREFIX = "scripts/"

# -- source taxonomy ----------------------------------------------------------

#: Filesystem-enumeration calls (KA026): ``<os>.name(...)`` attribute or
#: bare-name forms. ``Path`` methods are matched by attribute name alone —
#: there is exactly one thing ``.iterdir()``/``.rglob()`` can mean.
FS_ENUM_OS_NAMES = frozenset({"listdir", "scandir"})
FS_ENUM_GLOB_NAMES = frozenset({"glob", "iglob"})
FS_ENUM_PATH_METHODS = frozenset({"iterdir", "rglob"})

#: ``random.<name>`` module-level value sources (KA025). A seeded
#: ``random.Random(seed)`` instance is deterministic by construction, so
#: only calls qualified with the MODULE name count.
RANDOM_VALUE_NAMES = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "sample", "getrandbits", "gauss",
})

#: Wall-clock attribute calls (KA025): ``time.<name>`` / ``datetime.<name>``.
#: ``monotonic``/``perf_counter`` are deliberately absent (module docstring).
WALL_CLOCK_NAMES = frozenset({"time", "time_ns", "now", "utcnow", "today"})
WALL_CLOCK_MODULES = frozenset({"time", "datetime", "date"})

#: ``uuid.<name>`` identity sources (KA025).
UUID_VALUE_NAMES = frozenset({"uuid1", "uuid4", "getnode"})

#: Builtin identity sources (KA025): ``id(x)`` is an address, ``hash(x)``
#: is ``PYTHONHASHSEED``-keyed for strs/bytes.
BUILTIN_VALUE_NAMES = frozenset({"id", "hash"})

#: Envelope field names where a wall-clock/identity value is DECLARED
#: legal (exact match), plus substring tokens for derived names
#: (``process_uptime_seconds``, ``started_ts``, ``retry_in_s`` …).
TS_FIELD_ALLOWLIST = frozenset({"t", "rid", "seq", "now"})
TS_FIELD_TOKENS = (
    "ts", "time", "timestamp", "uptime", "elapsed", "duration",
    "started", "finished", "deadline", "request_id", "seed",
)

#: Order-insensitive consumers: these never observe iteration order.
ORDER_INSENSITIVE_CALLS = frozenset({
    "len", "min", "max", "sum", "any", "all", "bool", "set", "frozenset",
    "sorted",
})

#: Consumers that preserve (and therefore expose) the arbitrary order.
MATERIALIZING_CALLS = frozenset({"list", "tuple", "iter", "reversed",
                                 "enumerate", "map", "filter", "join"})

#: Source-kind labels for messages.
_KIND_DESC = {
    "set": "set iteration order (PYTHONHASHSEED-dependent)",
    "queue": "completion/drain order (scheduling-dependent)",
    "fs": "filesystem enumeration order (OS-dependent)",
    "shuffled": "re-shuffled sequence order",
}
_KIND_RULE = {"set": "KA024", "queue": "KA024", "shuffled": "KA024",
              "fs": "KA026"}


# -- sink reachability --------------------------------------------------------

@dataclass
class SinkReach:
    """Backward reachability to the nearest byte-pinned sink. ``towards``
    maps each member to ``(next hop key or None, call-site line in the
    member)``; ``sink_of`` maps each member to ``(sink funckey, sink
    description)`` — the seed's own sink call for seeds."""
    towards: Dict[str, Tuple[Optional[str], int]]
    sink_of: Dict[str, Tuple[str, str]]

    def __contains__(self, key: str) -> bool:
        return key in self.towards

    def chain(self, key: str) -> Tuple[str, ...]:
        """``key@line`` hops from ``key`` to the sink function, each line
        being the call site that leads one hop closer to the sink (the
        seed's line is its sink call)."""
        hops: List[str] = []
        cur: Optional[str] = key
        seen: Set[str] = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            nxt, line = self.towards.get(cur, (None, 0))
            hops.append(f"{cur}@{line}")
            cur = nxt
        return tuple(hops)

    def describe(self, key: str) -> str:
        sink_key, desc = self.sink_of.get(key, (key, "serialization sink"))
        return f"{desc} at {sink_key}"


def _dotted_head(node: ast.AST) -> Optional[str]:
    """The qualifying name of ``<name>.attr`` (one level), else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return node.value.id
    return None


def _sink_call_desc(node: ast.Call, relpath: str) -> Optional[str]:
    """Description when ``node`` pins bytes at the call site, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in JSON_SINK_NAMES \
            and _dotted_head(f) in JSON_MODULE_NAMES:
        return f"json.{f.attr} serialization"
    if isinstance(f, ast.Name) and f.id in JSON_SINK_NAMES:
        return f"{f.id}(...) serialization"
    if relpath.startswith(SCRIPTS_PREFIX):
        return None  # harness narration is not a pinned surface
    if isinstance(f, ast.Name) and f.id == "print":
        for kw in node.keywords:
            if kw.arg == "file":
                # print(..., file=sys.stderr) is diagnostics, not bytes
                head = _dotted_head(kw.value)
                attr = getattr(kw.value, "attr", None)
                if head == "sys" and attr != "stdout":
                    return None
        return "stdout emission (print)"
    if isinstance(f, ast.Attribute) and f.attr == "write":
        recv = f.value
        if isinstance(recv, ast.Attribute) and recv.attr == "stdout" \
                and _dotted_head(recv) == "sys":
            return "stdout emission (sys.stdout.write)"
    return None


def sink_reach(project: Project) -> SinkReach:
    """Every function from which a byte-pinned sink is reachable, with a
    next-hop pointer toward the nearest sink (BFS over the reversed call
    graph — "nearest" keeps ``--explain`` chains short and concrete)."""
    cached = getattr(project, "_determinism_reach", None)
    if cached is not None:
        return cached
    towards: Dict[str, Tuple[Optional[str], int]] = {}
    sink_of: Dict[str, Tuple[str, str]] = {}
    frontier: List[str] = []
    for key, fn in sorted(project.functions.items()):
        desc: Optional[str] = None
        line = fn.node.lineno
        declared = DECLARED_SINK_FUNCS.get((fn.relpath, fn.name))
        if declared is not None:
            desc = declared
        else:
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    d = _sink_call_desc(node, fn.relpath)
                    if d is not None:
                        desc, line = d, node.lineno
                        break
        if desc is not None:
            towards[key] = (None, line)
            sink_of[key] = (key, desc)
            frontier.append(key)
    reverse: Dict[str, List[Tuple[str, int]]] = {}
    for caller, callees in project.call_graph.items():
        for callee, line in callees.items():
            reverse.setdefault(callee, []).append((caller, line))
    i = 0
    while i < len(frontier):
        cur = frontier[i]
        i += 1
        for caller, line in sorted(reverse.get(cur, ())):
            if caller in towards:
                continue
            towards[caller] = (cur, line)
            sink_of[caller] = sink_of[cur]
            frontier.append(caller)
    # Phase 2, the callee direction: a helper whose RESULT a member
    # consumes (the call is not a discarded Expr statement) hands its
    # return value to code that serializes — the PR 15/16 bug shape, a
    # builder computing the payload the caller dumps. Side-effect-only
    # calls (append, lock ops, logging) stay out; a tainted ARGUMENT
    # passed into a member dies at the boundary (function-granular
    # under-approximation, same posture as the resolver).
    j = 0
    used_frontier = list(frontier)
    while j < len(used_frontier):
        cur = used_frontier[j]
        j += 1
        fn = project.functions.get(cur)
        if fn is None:
            continue
        mod = project.modules.get(fn.relpath)
        if mod is None:
            continue
        env = project.function_env(mod, fn)
        parents = _parent_map(fn.node)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(parents.get(node), ast.Expr):
                continue  # result discarded — nothing flows back
            callee = project.resolve_call(mod, fn, node, env)
            if callee is None or callee in towards:
                continue
            towards[callee] = (cur, node.lineno)
            sink_of[callee] = sink_of[cur]
            used_frontier.append(callee)
    result = SinkReach(towards=towards, sink_of=sink_of)
    project._determinism_reach = result
    return result


# -- intra-function source scanning -------------------------------------------

def _terminal_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_fs_enum_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in FS_ENUM_GLOB_NAMES
    if not isinstance(f, ast.Attribute):
        return False
    head = _dotted_head(f)
    if f.attr in FS_ENUM_OS_NAMES and head in (None, "os"):
        return head == "os"
    if f.attr in FS_ENUM_GLOB_NAMES and head == "glob":
        return True
    return f.attr in FS_ENUM_PATH_METHODS


def _is_sanitizer_call(call: ast.Call) -> bool:
    """``sorted(...)`` or a canonical-order helper: the result is in a
    deterministic order regardless of the argument's."""
    name = _terminal_name(call)
    if name is None:
        return False
    return name == "sorted" or "canonical" in name or "sorted" in name


def _parent_map(root: ast.AST) -> Dict[ast.AST, ast.AST]:
    return {child: parent
            for parent in ast.walk(root)
            for child in ast.iter_child_nodes(parent)}


class _FnScan:
    """One function's determinism scan: classify unordered expressions,
    track materialized taint through local names, and report every
    order-sensitive consumption that no sanitizer discharges."""

    def __init__(self, fn: FunctionInfo) -> None:
        self.fn = fn
        self.parents = _parent_map(fn.node)
        #: local name -> source kind; "set" names still hold a set object
        #: (later set algebra keeps working), the rest hold materialized
        #: sequences whose arbitrary order is now observable.
        self.tainted: Dict[str, str] = {}
        #: (line, col, kind) — order-sensitive consumptions to report.
        self.hits: List[Tuple[int, int, str]] = []
        #: (line, col, desc) — wall-clock/identity value reads (KA025).
        self.value_hits: List[Tuple[int, int, str]] = []

    # -- classification ------------------------------------------------------

    def unordered_kind(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return "set"
        if isinstance(node, ast.Name) and node.id in self.tainted:
            return self.tainted[node.id]
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.Sub)):
            if self.unordered_kind(node.left) == "set" \
                    or self.unordered_kind(node.right) == "set":
                return "set"
            return None
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return "set"
        if _is_fs_enum_call(node):
            return "fs"
        name = _terminal_name(node)
        if name == "as_completed":
            return "queue"
        if name in ("get", "get_nowait") and isinstance(f, ast.Attribute):
            # queue drain: only when the receiver is nameably a queue —
            # anything else (dict.get!) must stay silent
            recv = f.value
            recv_name = recv.id if isinstance(recv, ast.Name) \
                else getattr(recv, "attr", None)
            if recv_name is not None and "queue" in recv_name.lower():
                return "queue"
        if name in ("union", "intersection", "difference",
                    "symmetric_difference", "copy") \
                and isinstance(f, ast.Attribute) \
                and self.unordered_kind(f.value) == "set":
            return "set"
        return None

    # -- consumption ---------------------------------------------------------

    def _comprehension_owner(self, comp: ast.comprehension) -> Optional[ast.AST]:
        owner = self.parents.get(comp)
        return owner

    def _consumer(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def _sanitized_up(self, node: ast.AST) -> bool:
        """True when ``node``'s value flows straight into a sanitizer:
        ``sorted(S)``, ``sorted(f(x) for x in S)`` (the per-element map
        commutes with the sort), or a canonical-order helper call."""
        cur = node
        parent = self.parents.get(cur)
        # climb through the generator plumbing of a comprehension built
        # directly over the source
        while isinstance(parent, (ast.comprehension, ast.GeneratorExp,
                                  ast.ListComp)):
            cur = parent if not isinstance(parent, ast.comprehension) \
                else self.parents.get(parent)
            if cur is None:
                return False
            parent = self.parents.get(cur)
        if isinstance(parent, ast.Call) and cur in parent.args \
                and _is_sanitizer_call(parent):
            return True
        if isinstance(parent, ast.Starred):
            grand = self.parents.get(parent)
            if isinstance(grand, ast.Call) and _is_sanitizer_call(grand):
                return True
        return False

    def _order_insensitive(self, node: ast.AST, consumer: ast.AST) -> bool:
        if isinstance(consumer, ast.Call) and node in consumer.args:
            name = _terminal_name(consumer)
            if name in ORDER_INSENSITIVE_CALLS or _is_sanitizer_call(consumer):
                return True
        if isinstance(consumer, ast.Compare):
            # membership / equality never observe order
            return True
        if isinstance(consumer, (ast.BinOp, ast.BoolOp, ast.UnaryOp)):
            return True  # set algebra / truthiness
        if isinstance(consumer, ast.Subscript):
            return True  # d[k] on a dict keyed by the set — not iteration
        return False

    def record(self, node: ast.AST, kind: str) -> None:
        self.hits.append((node.lineno, node.col_offset + 1, kind))

    def consume(self, node: ast.AST, kind: str) -> None:
        """Judge one classified unordered expression at its consumer."""
        consumer = self._consumer(node)
        if consumer is None:
            return
        if self._order_insensitive(node, consumer):
            return
        if self._sanitized_up(node):
            return
        # iteration: for-loop or comprehension generator
        if isinstance(consumer, (ast.For, ast.AsyncFor)) \
                and consumer.iter is node:
            self.record(node, kind)
            return
        if isinstance(consumer, ast.comprehension) and consumer.iter is node:
            owner = self._comprehension_owner(consumer)
            if isinstance(owner, (ast.SetComp,)):
                return  # a set built over a set is still just a set
            if owner is not None and self._sanitized_up(owner):
                return  # sorted(f(x) for x in S)
            self.record(node, kind)
            return
        if isinstance(consumer, ast.Call) and node in consumer.args:
            name = _terminal_name(consumer)
            if _sink_call_desc(consumer, self.fn.relpath) is not None:
                # handing the arbitrary order straight to the sink —
                # json.dumps(list(s)) and json.dumps(items) alike
                self.record(node, kind)
                return
            if name in MATERIALIZING_CALLS:
                # list(S): the arbitrary order becomes an observable
                # sequence — legal only when the result is immediately
                # sorted or bound to a name that is sorted before use
                grand = self._consumer(consumer)
                if grand is not None and isinstance(grand, ast.Call) \
                        and consumer in grand.args \
                        and _is_sanitizer_call(grand):
                    return
                if isinstance(grand, ast.Assign) and len(grand.targets) == 1 \
                        and isinstance(grand.targets[0], ast.Name):
                    # the pre-pass already tainted the target (and saw
                    # any later .sort() discharge) — no state change here
                    return
                self.record(node, kind)
            return
        if isinstance(consumer, ast.Starred) or isinstance(
                consumer, ast.YieldFrom):
            self.record(node, kind)
            return
        if isinstance(consumer, ast.Assign) and len(consumer.targets) == 1 \
                and isinstance(consumer.targets[0], ast.Name):
            self.tainted[consumer.targets[0].id] = kind
            return
        if isinstance(consumer, ast.Return) and kind != "set":
            # returning a SET is returning a set (the caller's own use is
            # judged there if it is in this project); returning an already
            # MATERIALIZED arbitrary order hands the bug to every caller
            self.record(node, kind)
            return

    # -- the walk ------------------------------------------------------------

    def run(self) -> None:
        for stmt in ast.walk(self.fn.node):
            self._statement_effects(stmt)
        for node in ast.walk(self.fn.node):
            kind = self.unordered_kind(node)
            if kind is not None and not (
                    isinstance(node, ast.Name)):
                self.consume(node, kind)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in self.tainted:
                self.consume(node, self.tainted[node.id])
            if isinstance(node, ast.Call):
                self._value_source(node)

    def _statement_effects(self, stmt: ast.AST) -> None:
        """Pre-pass, in source order: name bindings, ``.sort()``
        discharges, ``random.shuffle`` re-taints. ``ast.walk`` is
        breadth-first but assignments and their uses are judged against
        the FINAL state only in straight-line code; the repo's (and the
        fixtures') taint-relevant flows are straight-line, and a
        flow-join miss under-approximates, which is the house posture."""
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            kind = self.unordered_kind(stmt.value)
            if kind is None and isinstance(stmt.value, ast.Call):
                inner = stmt.value
                tname = _terminal_name(inner)
                if tname in MATERIALIZING_CALLS and inner.args:
                    kind = self.unordered_kind(inner.args[0])
            if kind is not None:
                self.tainted[name] = kind
            elif name in self.tainted and not (
                    isinstance(stmt.value, ast.Name)
                    and stmt.value.id == name):
                del self.tainted[name]
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "sort" \
                    and isinstance(f.value, ast.Name):
                self.tainted.pop(f.value.id, None)
            name = _terminal_name(call)
            if name == "shuffle" and _dotted_head(f) == "random" \
                    and call.args and isinstance(call.args[0], ast.Name):
                self.tainted[call.args[0].id] = "shuffled"

    # -- KA025 value sources -------------------------------------------------

    def _value_source(self, call: ast.Call) -> None:
        desc = self._value_source_desc(call)
        if desc is None:
            return
        if "identity read" in desc and self._identity_token_use(call):
            return  # memo key / membership token — never becomes bytes
        if self._ts_allowlisted(call):
            return
        self.value_hits.append(
            (call.lineno, call.col_offset + 1, desc))

    def _identity_token_use(self, call: ast.Call) -> bool:
        """``id(x)``/``hash(x)`` consumed as an identity TOKEN — a set
        membership test, a memo subscript, a dict key, a ``.add(...)`` —
        names an object, it does not produce a value that could land in
        output bytes."""
        parent = self.parents.get(call)
        if isinstance(parent, ast.Compare):
            return True
        if isinstance(parent, ast.Subscript):
            return True
        if isinstance(parent, ast.Dict) and call in parent.keys:
            return True
        if isinstance(parent, ast.Call) and call in parent.args:
            name = _terminal_name(parent)
            if name in ("add", "discard", "remove", "get", "pop",
                        "setdefault"):
                return True
        return False

    def _value_source_desc(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in BUILTIN_VALUE_NAMES and len(call.args) == 1:
                return f"{f.id}() identity read"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        head = _dotted_head(f)
        if f.attr in WALL_CLOCK_NAMES and head in WALL_CLOCK_MODULES:
            return f"wall-clock read {head}.{f.attr}()"
        if f.attr in RANDOM_VALUE_NAMES and head == "random":
            return f"random.{f.attr}() draw"
        if f.attr in UUID_VALUE_NAMES and head == "uuid":
            return f"uuid.{f.attr}() draw"
        return None

    def _ts_allowlisted(self, node: ast.AST) -> bool:
        """True when the value lands in a DECLARED timestamp/identity
        field: the nearest dict-literal key, keyword argument, call-chain
        attribute, assignment target, or the enclosing function's own
        name matches the allowlist."""
        names: List[str] = []
        cur: ast.AST = node
        for _ in range(32):
            parent = self.parents.get(cur)
            if parent is None:
                break
            if isinstance(parent, ast.Dict):
                for k, v in zip(parent.keys, parent.values):
                    if v is cur and isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        names.append(k.value)
            if isinstance(parent, ast.keyword) and parent.arg:
                names.append(parent.arg)
            if isinstance(parent, ast.Call):
                recv = parent.func
                if isinstance(recv, ast.Attribute):
                    names.append(recv.attr)
                    # d.setdefault("ts", value): the FIELD is the first
                    # positional arg, the value rides behind it
                    if recv.attr in ("setdefault", "set") and parent.args \
                            and cur is not parent.args[0] \
                            and isinstance(parent.args[0], ast.Constant) \
                            and isinstance(parent.args[0].value, str):
                        names.append(parent.args[0].value)
            if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = parent.targets if isinstance(parent, ast.Assign) \
                    else [parent.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.append(t.attr)
                    elif isinstance(t, ast.Subscript) \
                            and isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str):
                        names.append(t.slice.value)  # d["ts"] = value
                break  # the statement boundary ends the flow
            if isinstance(parent, (ast.stmt,)):
                break
            cur = parent
        names.append(self.fn.name)
        return any(_ts_field_ok(n) for n in names)


def _ts_field_ok(name: str) -> bool:
    low = name.lower().lstrip("_")
    if low in TS_FIELD_ALLOWLIST:
        return True
    return any(tok in low for tok in TS_FIELD_TOKENS)


# -- KA027: thread-racy collections at a sink ---------------------------------

#: Attribute-view drains whose result is an iteration of the backing dict.
DICT_VIEW_NAMES = frozenset({"keys", "values", "items"})


def _iterated_attr_nodes(fn: FunctionInfo,
                         parents: Dict[ast.AST, ast.AST]
                         ) -> List[Tuple[ast.Attribute, str]]:
    """``self.<attr>`` loads consumed by iteration — directly (``for``/
    comprehension/``list()``/``sorted()``), or through a dict view
    (``.items()`` &c). Returns (node, how)."""
    out: List[Tuple[ast.Attribute, str]] = []
    for node in ast.walk(fn.node):
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            continue
        consumer = parents.get(node)
        if isinstance(consumer, ast.Attribute) \
                and consumer.attr in DICT_VIEW_NAMES:
            call = parents.get(consumer)
            if isinstance(call, ast.Call) and call.func is consumer:
                out.append((node, f".{consumer.attr}() view drain"))
            continue
        if isinstance(consumer, (ast.For, ast.AsyncFor)) \
                and consumer.iter is node:
            out.append((node, "direct iteration"))
        elif isinstance(consumer, ast.comprehension) \
                and consumer.iter is node:
            out.append((node, "comprehension iteration"))
        elif isinstance(consumer, ast.Call) and node in consumer.args \
                and _terminal_name(consumer) in (
                    MATERIALIZING_CALLS | {"sorted", "dict"}):
            out.append((node, f"{_terminal_name(consumer)}(...) "
                              "materialization"))
    return out


def _check_racy_iteration(project: Project, reach: SinkReach,
                          display: Dict[str, str]) -> List[Finding]:
    """KA027: a collection attribute written from another thread entry,
    iterated (or view-drained) in a sink-reaching function with no lock
    in common with every foreign write — iteration is not atomic, so the
    drain can tear or raise mid-mutation and the surface bytes become a
    race result. ``sorted()`` does NOT discharge this; a snapshot taken
    while holding the writers' lock does. Attributes KA021/KA022 already
    convict are skipped — one rule per defect."""
    from .threads import thread_model

    model = thread_model(project)
    out: List[Finding] = []

    def tid(entry_key: str) -> str:
        e = model.entry_by_key.get(entry_key)
        return "<main>" if (e is not None and e.kind == "main") \
            else entry_key

    groups: Dict[Tuple[Tuple[str, str], str], List] = {}
    for acc in model.accesses:
        groups.setdefault((acc.owner, acc.attr), []).append(acc)

    # replicate the KA021/KA022 convictions to stay disjoint from them
    def convicted_elsewhere(writes) -> bool:
        writer_tids = {tid(a.entry) for a in writes} | {
            a.entry for a in writes
            if (e := model.entry_by_key.get(a.entry)) is not None
            and e.concurrent
        }
        common_w = frozenset.intersection(*[a.locks for a in writes])
        if len(writer_tids) >= 2 and not common_w:
            return True  # KA021 territory
        return bool(common_w)  # KA022 owns inconsistent guarding

    seen: Set[Tuple[str, int, int]] = set()
    for (owner, attr), accs in sorted(groups.items()):
        writes = [a for a in accs if a.write]
        if not writes:
            continue
        if convicted_elsewhere(writes):
            continue
        for acc in accs:
            if acc.write or acc.funckey not in reach:
                continue
            foreign = [w for w in writes if tid(w.entry) != tid(acc.entry)
                       or ((e := model.entry_by_key.get(w.entry))
                           is not None and e.concurrent)]
            if not foreign:
                continue
            safe = any(
                lock in acc.locks
                and all(lock in w.locks for w in foreign)
                for lock in frozenset.union(*[w.locks for w in foreign])
            ) if any(w.locks for w in foreign) else False
            if safe:
                continue
            fn = project.functions.get(acc.funckey)
            if fn is None:
                continue
            parents = _parent_map(fn.node)
            for node, how in _iterated_attr_nodes(fn, parents):
                if node.attr != attr:
                    continue
                if node.lineno != acc.line or node.col_offset + 1 != acc.col:
                    continue
                key = (acc.funckey, node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                orel, ocls = owner
                writers = "; ".join(sorted(
                    {model.entry_by_key[w.entry].label
                     if w.entry in model.entry_by_key else w.entry
                     for w in foreign}))
                out.append(Finding(
                    "KA027",
                    display.get(fn.relpath, fn.relpath),
                    node.lineno, node.col_offset + 1,
                    f"thread-racy collection {ocls}.{attr} ({orel}) "
                    f"{how} on the way to a byte-pinned sink "
                    f"({reach.describe(acc.funckey)}) while "
                    f"{writers} can mutate it, with no lock in common "
                    "with the writers: the drain can tear or raise "
                    "mid-mutation and the surface bytes become a race "
                    "result — snapshot under the writers' lock first, "
                    "or suppress citing the happens-before protocol",
                    chain=reach.chain(acc.funckey),
                ))
    return out


# -- the rule pass ------------------------------------------------------------

def check_determinism(project: Project,
                      display: Dict[str, str]) -> List[Finding]:
    """KA024–KA027 over one resolved project (module docstring has the
    taxonomy). Findings carry the function→…→sink chain."""
    reach = sink_reach(project)
    out: List[Finding] = []
    for key in sorted(reach.towards):
        fn = project.functions.get(key)
        if fn is None:
            continue
        scan = _FnScan(fn)
        scan.run()
        path = display.get(fn.relpath, fn.relpath)
        chain = reach.chain(key)
        where = reach.describe(key)
        for line, col, kind in sorted(set(scan.hits)):
            rule = _KIND_RULE[kind]
            fixup = (
                "wrap the producer in sorted(...) or a canonical-order "
                "helper (a later sort on a different axis discharges "
                "nothing), or suppress citing the source→sink chain"
            )
            out.append(Finding(
                rule, path, line, col,
                f"{_KIND_DESC[kind]} reaches the byte-pinned sink "
                f"({where}) unsanitized: {fixup}",
                chain=chain,
            ))
        for line, col, desc in sorted(set(scan.value_hits)):
            out.append(Finding(
                "KA025", path, line, col,
                f"{desc} flows toward pinned output bytes ({where}) "
                "outside every declared timestamp/identity field "
                f"(allowlist: {', '.join(sorted(TS_FIELD_ALLOWLIST))} "
                f"plus *{'*, *'.join(TS_FIELD_TOKENS)}* tokens): stamp "
                "it into a declared envelope field, derive it "
                "deterministically, or suppress citing the chain",
                chain=chain,
            ))
    out.extend(_check_racy_iteration(project, reach, display))
    return out
