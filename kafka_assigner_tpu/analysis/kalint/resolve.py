"""Project-wide resolution layer: import graph, symbol tables, call graph.

This is the substrate the interprocedural rules stand on. It parses every
module under one package root ONCE, then resolves, statically and
conservatively:

- **import graph** — which project modules a module imports (cycles are
  fine: resolution is a lookup over the fully-parsed set, never a load);
- **symbol tables** — what each top-level name in a module refers to:
  an in-project module, function, or class, through ``import``/
  ``from x import y as z`` aliasing and re-export chains;
- **call graph** — for every top-level function and method, the set of
  in-project callees it can statically reach, with the first call-site
  line per edge (chains for ``--explain``). Resolution covers direct
  names, module-attribute calls (``mod.f()``), ``self.method()`` through
  in-project base classes, constructor calls (edge to ``__init__``), and
  one level of instance typing: parameter annotations, ``x = Class(...)``
  locals, and ``self.attr = Class(...)`` instance attributes.

Everything unresolvable (duck-typed attribute calls on unknown objects,
dynamic dispatch tables, ``getattr``) contributes NO edge — the analysis
under-approximates reachability rather than drowning the rules in false
positives. The rules that consume it (KA002/KA007 taint, KA012 transitive,
KA015-017) are tripwires over the statically-knowable graph, not a sound
whole-program analysis; the suppression mechanism covers the gap the other
way.

Function identity is ``"<relpath>::<qualname>"`` (e.g.
``daemon/supervisor.py::ClusterSupervisor._run_plan``) — stable across
runs, JSON-friendly, human-readable in chains. Nested functions are folded
into their enclosing definition (their bodies are walked as part of it):
what a closure does, its owner is accountable for.
"""
from __future__ import annotations

import ast
import hashlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

FUNC_SEP = "::"

#: Symbol-table target kinds (first tuple element).
MOD, FUNC, CLS = "mod", "func", "class"

Target = Tuple  # (MOD, relpath) | (FUNC, funckey) | (CLS, relpath, name)


def func_key(relpath: str, qualname: str) -> str:
    return f"{relpath}{FUNC_SEP}{qualname}"


def split_key(key: str) -> Tuple[str, str]:
    relpath, _, qual = key.partition(FUNC_SEP)
    return relpath, qual


@dataclass
class FunctionInfo:
    key: str
    relpath: str
    qualname: str          # "f" or "Class.m"
    name: str              # terminal name
    node: ast.AST          # FunctionDef | AsyncFunctionDef
    cls: Optional[str] = None  # owning class name, None for module functions


@dataclass
class ClassInfo:
    name: str
    relpath: str
    node: ast.ClassDef
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    base_exprs: List[ast.expr] = field(default_factory=list)
    resolved_bases: List[Tuple[str, str]] = field(default_factory=list)
    #: instance-attribute types gathered from ``self.x = Class(...)``,
    #: ``self.x: Class`` and annotated-parameter assignment in any method.
    attr_types: Dict[str, Tuple[str, str]] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    relpath: str           # package-root-relative posix path
    dotted: str            # package-relative dotted name ("" = root __init__)
    src: str
    sha: str
    tree: ast.Module
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    func_by_name: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: unresolved import records: (bound_name, kind, payload)
    raw_imports: List[Tuple] = field(default_factory=list)
    bindings: Dict[str, Target] = field(default_factory=dict)


class _LocalEnv:
    """Per-function resolution context: function-local imports and the
    one-level instance types of parameters and locals."""

    __slots__ = ("bindings", "types")

    def __init__(self) -> None:
        self.bindings: Dict[str, Target] = {}
        self.types: Dict[str, Tuple[str, str]] = {}


def _module_dotted(relpath: str) -> str:
    parts = relpath[:-3].split("/")  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _pkg_of(dotted: str, relpath: str) -> str:
    """The package a module's relative imports resolve against."""
    if relpath.endswith("/__init__.py") or relpath == "__init__.py":
        return dotted
    return dotted.rpartition(".")[0]


class Project:
    """The parsed-and-resolved package tree. Build with
    :func:`build_project`; the taint sets (traced / lock-held) are computed
    lazily by :mod:`.taint` and memoized here."""

    def __init__(self, root: Path, pkg_name: str):
        self.root = root
        self.pkg_name = pkg_name
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: caller key -> {callee key: first call-site line}
        self.call_graph: Dict[str, Dict[str, int]] = {}
        #: module relpath -> imported project-module relpaths
        self.import_graph: Dict[str, Set[str]] = {}
        # taint memos (filled by .taint)
        self._traced = None
        self._lock_held = None
        self._gate_held = None
        #: thread/shared-state model memo (filled by .threads)
        self._threads = None
        #: byte-pinned sink reachability memo (filled by .determinism)
        self._determinism_reach = None
        #: top-level dotted names of injected out-of-package modules
        #: (``scripts`` for the smoke harnesses) — absolute imports of
        #: these resolve in-project even though they sit outside
        #: ``pkg_name``'s namespace.
        self.extra_tops: Set[str] = set()
        #: post-resolution _LocalEnv memo (see :meth:`function_env`)
        self._env_cache: Dict[str, _LocalEnv] = {}

    # -- queries -----------------------------------------------------------

    def module(self, relpath: str) -> Optional[ModuleInfo]:
        return self.modules.get(relpath)

    def function(self, key: str) -> Optional[FunctionInfo]:
        return self.functions.get(key)

    def callees(self, key: str) -> Dict[str, int]:
        return self.call_graph.get(key, {})

    def resolve_module(self, dotted: str) -> Optional[str]:
        return self.by_dotted.get(dotted)

    def lookup(self, relpath: str, name: str) -> Optional[Target]:
        """``name`` in module ``relpath``'s namespace: own def, own class,
        submodule, or binding (re-export chains are already flattened into
        ``bindings`` by the ``_resolve_bindings`` fixpoint — no recursion
        here)."""
        mod = self.modules.get(relpath)
        if mod is None:
            return None
        if name in mod.func_by_name:
            return (FUNC, mod.func_by_name[name].key)
        if name in mod.classes:
            return (CLS, relpath, name)
        sub = self.by_dotted.get(
            (mod.dotted + "." + name) if mod.dotted else name
        )
        if sub is not None:
            return (MOD, sub)
        t = mod.bindings.get(name)
        return t

    def class_info(self, relpath: str, name: str) -> Optional[ClassInfo]:
        mod = self.modules.get(relpath)
        return mod.classes.get(name) if mod else None

    def find_method(self, relpath: str, clsname: str, method: str,
                    _seen: Optional[Set[Tuple[str, str]]] = None
                    ) -> Optional[FunctionInfo]:
        """Method lookup through in-project base classes (BFS)."""
        _seen = _seen or set()
        if (relpath, clsname) in _seen:
            return None
        _seen.add((relpath, clsname))
        ci = self.class_info(relpath, clsname)
        if ci is None:
            return None
        if method in ci.methods:
            return ci.methods[method]
        for brp, bname in ci.resolved_bases:
            hit = self.find_method(brp, bname, method, _seen)
            if hit is not None:
                return hit
        return None

    # -- construction ------------------------------------------------------

    def _add_module(self, relpath: str, src: str) -> None:
        try:
            tree = ast.parse(src)
        except SyntaxError:
            return  # KA000 is the per-module pass's job; no graph facts
        dotted = _module_dotted(relpath)
        mod = ModuleInfo(
            relpath=relpath, dotted=dotted, src=src,
            sha=hashlib.sha256(src.encode("utf-8")).hexdigest(), tree=tree,
        )
        self._collect_defs(mod, tree.body)
        self._collect_imports(mod, tree)
        self.modules[relpath] = mod
        self.by_dotted[dotted] = relpath

    def _collect_defs(self, mod: ModuleInfo, stmts: Sequence[ast.stmt],
                      ) -> None:
        """Top-level functions and classes, looking through module-level
        ``if``/``try`` wrappers (version-compat defs are still defs)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(
                    key=func_key(mod.relpath, stmt.name),
                    relpath=mod.relpath, qualname=stmt.name,
                    name=stmt.name, node=stmt,
                )
                mod.functions[stmt.name] = info
                mod.func_by_name[stmt.name] = info
            elif isinstance(stmt, ast.ClassDef):
                ci = ClassInfo(
                    name=stmt.name, relpath=mod.relpath, node=stmt,
                    base_exprs=list(stmt.bases),
                )
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        qual = f"{stmt.name}.{sub.name}"
                        info = FunctionInfo(
                            key=func_key(mod.relpath, qual),
                            relpath=mod.relpath, qualname=qual,
                            name=sub.name, node=sub, cls=stmt.name,
                        )
                        ci.methods[sub.name] = info
                        mod.functions[qual] = info
                mod.classes[stmt.name] = ci
            else:
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list):
                        self._collect_defs(mod, sub)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._collect_defs(mod, handler.body)

    def _collect_imports(self, mod: ModuleInfo, scope: ast.AST) -> None:
        """Module-level import records (function-local imports are gathered
        per function at call-graph time with the same resolver)."""
        mod.raw_imports = self._import_records(mod, scope, module_level=True)

    def _import_records(self, mod: ModuleInfo, scope: ast.AST,
                        module_level: bool) -> List[Tuple]:
        deferred: Set[int] = set()
        if module_level:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    for sub in ast.walk(node):
                        if sub is not node:
                            deferred.add(id(sub))
        records: List[Tuple] = []
        for node in ast.walk(scope):
            if module_level and id(node) in deferred:
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    records.append(
                        (alias.asname or alias.name.split(".")[0],
                         "import", alias.name, alias.asname)
                    )
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(mod, node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    records.append(
                        (alias.asname or alias.name,
                         "from", base, alias.name)
                    )
        return records

    def _from_base(self, mod: ModuleInfo,
                   node: ast.ImportFrom) -> Optional[str]:
        """The project-relative dotted base an ImportFrom resolves against,
        or None for out-of-project imports."""
        if node.level:
            pkg = _pkg_of(mod.dotted, mod.relpath)
            parts = pkg.split(".") if pkg else []
            up = node.level - 1
            if up > len(parts):
                return None
            parts = parts[:len(parts) - up] if up else parts
            if node.module:
                parts = parts + node.module.split(".")
            return ".".join(parts)
        if node.module is None:
            return None
        if node.module == self.pkg_name:
            return ""
        if node.module.startswith(self.pkg_name + "."):
            return node.module[len(self.pkg_name) + 1:]
        if node.module.split(".")[0] in self.extra_tops:
            # injected module namespace (smoke scripts import each other
            # as `from scripts.health_smoke import ...`): their dotted
            # names ARE their project-relative names
            return node.module
        return None

    def _resolve_record(self, record: Tuple) -> Optional[Target]:
        _bound, kind, a, b = record
        if kind == "import":
            dotted_abs = a
            if dotted_abs == self.pkg_name:
                rel = ""
            elif dotted_abs.startswith(self.pkg_name + "."):
                rel = dotted_abs[len(self.pkg_name) + 1:]
            else:
                return None
            if b is None and "." in dotted_abs:
                # plain `import pkg.sub.mod` binds the ROOT name only
                rel = ""
            rp = self.by_dotted.get(rel)
            return (MOD, rp) if rp else None
        # kind == "from": base dotted `a`, symbol `b`
        sub_rp = self.by_dotted.get((a + "." + b) if a else b)
        if sub_rp is not None:
            return (MOD, sub_rp)
        base_rp = self.by_dotted.get(a)
        if base_rp is None:
            return None
        return self.lookup(base_rp, b)

    def _resolve_bindings(self) -> None:
        """Module-level symbol tables, iterated to a fixpoint so re-export
        chains (``from .x import y`` where x's y is itself imported)
        resolve. Termination is guaranteed without a pass cap: bindings
        only ever GROW, and a pass that adds none breaks — cycles just
        stop making progress."""
        while True:
            changed = False
            for mod in self.modules.values():
                for record in mod.raw_imports:
                    bound = record[0]
                    if bound in mod.bindings:
                        continue
                    t = self._resolve_record(record)
                    if t is not None:
                        mod.bindings[bound] = t
                        changed = True
            if not changed:
                break

    def _resolve_classes(self) -> None:
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for base in ci.base_exprs:
                    t = self._resolve_expr_target(mod, base, _LocalEnv())
                    if t and t[0] == CLS:
                        ci.resolved_bases.append((t[1], t[2]))

    def _annotation_class(self, mod: ModuleInfo, ann: Optional[ast.expr],
                          env: _LocalEnv) -> Optional[Tuple[str, str]]:
        """A parameter/attribute annotation resolved to an in-project
        class, looking through Optional[...]/``X | None`` wrappers."""
        if ann is None:
            return None
        if isinstance(ann, ast.Subscript):
            return self._annotation_class(mod, ann.slice, env)
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._annotation_class(mod, ann.left, env)
                    or self._annotation_class(mod, ann.right, env))
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
            return self._annotation_class(mod, ann, env)
        t = self._resolve_expr_target(mod, ann, env)
        if t and t[0] == CLS:
            return (t[1], t[2])
        return None

    def _resolve_expr_target(self, mod: ModuleInfo, expr: ast.expr,
                             env: _LocalEnv) -> Optional[Target]:
        """A Name/Attribute expression resolved to a project target."""
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in env.bindings:
                return env.bindings[n]
            if n in mod.func_by_name:
                return (FUNC, mod.func_by_name[n].key)
            if n in mod.classes:
                return (CLS, mod.relpath, n)
            return mod.bindings.get(n)
        if isinstance(expr, ast.Attribute):
            base = self._resolve_expr_target(mod, expr.value, env)
            if base and base[0] == MOD:
                return self.lookup(base[1], expr.attr)
            if base and base[0] == CLS:
                # ClassName.method reference
                hit = self.find_method(base[1], base[2], expr.attr)
                return (FUNC, hit.key) if hit else None
            return None
        return None

    def _collect_attr_types(self) -> None:
        """``self.x = Class(...)`` / annotated-parameter assignment /
        ``self.x: Class`` across every method of every class."""
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for stmt in ci.node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        t = self._annotation_class(
                            mod, stmt.annotation, _LocalEnv())
                        if t:
                            ci.attr_types.setdefault(stmt.target.id, t)
                for m in ci.methods.values():
                    env = self._function_env(mod, m)
                    for node in ast.walk(m.node):
                        target = None
                        value = None
                        if isinstance(node, ast.Assign) \
                                and len(node.targets) == 1:
                            target, value = node.targets[0], node.value
                        elif isinstance(node, ast.AnnAssign):
                            target, value = node.target, node.value
                            if isinstance(target, ast.Attribute) \
                                    and isinstance(target.value, ast.Name) \
                                    and target.value.id == "self":
                                t = self._annotation_class(
                                    mod, node.annotation, env)
                                if t:
                                    ci.attr_types.setdefault(target.attr, t)
                        if not (isinstance(target, ast.Attribute)
                                and isinstance(target.value, ast.Name)
                                and target.value.id == "self"):
                            continue
                        t = self._value_type(mod, value, env)
                        if t:
                            ci.attr_types.setdefault(target.attr, t)

    def _value_type(self, mod: ModuleInfo, value: Optional[ast.expr],
                    env: _LocalEnv) -> Optional[Tuple[str, str]]:
        """The in-project class an assigned value is an instance of, when
        statically evident: ``Class(...)`` constructor calls and names the
        env already typed."""
        if value is None:
            return None
        if isinstance(value, ast.Call):
            t = self._resolve_expr_target(mod, value.func, env)
            if t and t[0] == CLS:
                return (t[1], t[2])
            return None
        if isinstance(value, ast.Name):
            return env.types.get(value.id)
        return None

    def function_env(self, mod: ModuleInfo, fn: FunctionInfo) -> _LocalEnv:
        """Memoized :meth:`_function_env` for AFTER construction finishes:
        the env is a pure function of the frozen module state once
        ``_collect_attr_types`` has run (which itself must keep calling
        the uncached builder — attr types are still being filled then)."""
        env = self._env_cache.get(fn.key)
        if env is None:
            env = self._env_cache[fn.key] = self._function_env(mod, fn)
        return env

    def _function_env(self, mod: ModuleInfo, fn: FunctionInfo) -> _LocalEnv:
        """Local imports + one-level instance types for one function."""
        env = _LocalEnv()
        for record in self._import_records(mod, fn.node, module_level=False):
            if record[0] in env.bindings:
                continue
            t = self._resolve_record(record)
            if t is not None:
                env.bindings[record[0]] = t
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            t = self._annotation_class(mod, a.annotation, env)
            if t:
                env.types[a.arg] = t
        # two passes so `x = Backend(...)` typed above its uses regardless
        # of walk order, and chained `y = x` picks up x's type
        for _ in range(2):
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    t = self._value_type(mod, node.value, env)
                    if t is None and fn.cls is not None \
                            and isinstance(node.value, ast.Attribute) \
                            and isinstance(node.value.value, ast.Name) \
                            and node.value.value.id == "self":
                        ci = mod.classes.get(fn.cls)
                        if ci:
                            t = ci.attr_types.get(node.value.attr)
                    if t:
                        env.types.setdefault(name, t)
        return env

    def resolve_call(self, mod: ModuleInfo, fn: FunctionInfo,
                     call: ast.Call, env: _LocalEnv) -> Optional[str]:
        """The in-project FuncKey a call dispatches to, or None."""
        f = call.func
        target: Optional[Target] = None
        if isinstance(f, ast.Name):
            target = self._resolve_expr_target(mod, f, env)
        elif isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Name):
                if v.id in ("self", "cls") and fn.cls is not None:
                    hit = self.find_method(mod.relpath, fn.cls, f.attr)
                    if hit is not None:
                        return hit.key
                    return None
                if v.id in env.types:
                    rp, cn = env.types[v.id]
                    hit = self.find_method(rp, cn, f.attr)
                    return hit.key if hit else None
                target = self._resolve_expr_target(mod, f, env)
            elif isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name):
                if v.value.id == "self" and fn.cls is not None:
                    ci = mod.classes.get(fn.cls)
                    t = ci.attr_types.get(v.attr) if ci else None
                    if t is None:
                        # inherited instance attribute: search bases
                        seen: Set[Tuple[str, str]] = set()
                        stack = list(ci.resolved_bases) if ci else []
                        while stack:
                            brp, bcn = stack.pop()
                            if (brp, bcn) in seen:
                                continue
                            seen.add((brp, bcn))
                            bci = self.class_info(brp, bcn)
                            if bci is None:
                                continue
                            if v.attr in bci.attr_types:
                                t = bci.attr_types[v.attr]
                                break
                            stack.extend(bci.resolved_bases)
                    if t is not None:
                        hit = self.find_method(t[0], t[1], f.attr)
                        return hit.key if hit else None
                    return None
                target = self._resolve_expr_target(mod, f, env)
            else:
                return None
        if target is None:
            return None
        if target[0] == FUNC:
            return target[1]
        if target[0] == CLS:
            hit = self.find_method(target[1], target[2], "__init__")
            return hit.key if hit else None
        return None

    def _build_call_graph(self) -> None:
        for mod in self.modules.values():
            for fn in mod.functions.values():
                self.functions[fn.key] = fn
        for mod in self.modules.values():
            for fn in mod.functions.values():
                env = self.function_env(mod, fn)
                edges: Dict[str, int] = {}
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self.resolve_call(mod, fn, node, env)
                    if callee is not None and callee != fn.key:
                        edges.setdefault(callee, node.lineno)
                self.call_graph[fn.key] = edges

    def _build_import_graph(self) -> None:
        for mod in self.modules.values():
            deps: Set[str] = set()
            for t in mod.bindings.values():
                deps.add(t[1] if t[0] != FUNC else split_key(t[1])[0])
            deps.discard(mod.relpath)
            self.import_graph[mod.relpath] = deps


def build_project(root: Path | str,
                  pkg_name: Optional[str] = None,
                  extra_modules: Sequence[Tuple[str, Path]] = (),
                  ) -> Project:
    """Parse and resolve every ``*.py`` under ``root`` (one package tree).
    ``pkg_name`` defaults to the root directory's name — what absolute
    imports of the package are matched against. ``extra_modules`` grafts
    out-of-package files (the ``scripts/*_smoke.py`` harnesses) into the
    same graph under their given relpaths: their top directory becomes an
    importable namespace (``from scripts.health_smoke import ...``) and
    their absolute ``pkg_name.*`` imports resolve like anyone else's."""
    root = Path(root).resolve()
    project = Project(root, pkg_name or root.name)
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        rel = p.relative_to(root).as_posix()
        try:
            src = p.read_text(encoding="utf-8")
        except OSError:  # kalint: disable=KA008 -- file raced away mid-walk; no module to add
            continue
        project._add_module(rel, src)
    for rel, path in extra_modules:
        try:
            src = Path(path).read_text(encoding="utf-8")
        except OSError:  # kalint: disable=KA008 -- file raced away mid-walk; no module to add
            continue
        project.extra_tops.add(rel.split("/", 1)[0])
        project._add_module(rel, src)
    project._resolve_bindings()
    project._resolve_classes()
    project._collect_attr_types()
    project._build_call_graph()
    project._build_import_graph()
    return project
