"""Content-hash-keyed analysis cache.

The interprocedural pass re-reads and re-resolves the whole package; the
cache keeps ``scripts/lint.sh`` inside its wall-clock budget by keying the
COMPLETE finding set on a fingerprint of everything that can change it:

- every ``*.py`` under the analyzed root (path + content sha),
- the kalint implementation itself (rule changes invalidate),
- the live registries the rules consult (knobs, metric/span names,
  unitless allowlist),
- the README text (KA004 reads it),
- the analysis schema version (bumped on format changes).

A hit returns the stored findings verbatim (chains included); any edit
anywhere misses and re-analyzes. Entries are whole-tree — correct by
construction, no per-file invalidation logic to get wrong — and pruned to
the newest few so the directory stays small. Writes are atomic
(tmp+rename) and corruption-tolerant on read (drop + re-analyze).
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

#: Bump on any change to the finding schema or rule semantics that a file
#: hash would not capture (kalint's own sources are hashed too, so this is
#: belt-and-braces for out-of-tree callers).
ANALYSIS_SCHEMA = 1

#: Cache entries kept (newest by mtime); the rest are pruned on store.
KEEP_ENTRIES = 8


def default_cache_dir(repo_root: Path) -> Path:
    from ...utils.env import env_str

    configured = env_str("KA_LINT_CACHE_DIR")
    if configured:
        return Path(configured)
    return repo_root / ".kalint-cache"


def cache_enabled() -> bool:
    from ...utils.env import env_bool

    return env_bool("KA_LINT_CACHE")


def _file_sha(path: Path) -> Optional[str]:
    try:
        return hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        return None


def tree_fingerprint(root: Path, extra_files: Sequence[Path] = (),
                     registry_blob: str = "") -> str:
    """One sha over every analysis input under ``root`` plus the kalint
    implementation, the extra files (README) and the registry snapshot."""
    h = hashlib.sha256()
    h.update(f"schema={ANALYSIS_SCHEMA}\n".encode())
    kalint_dir = Path(__file__).resolve().parent
    seen = set()
    for base in (root, kalint_dir):
        for p in sorted(base.rglob("*.py")):
            if "__pycache__" in p.parts or p in seen:
                continue
            seen.add(p)
            sha = _file_sha(p)
            if sha is None:
                continue
            h.update(f"{p.as_posix()}={sha}\n".encode())
    for p in extra_files:
        sha = _file_sha(Path(p))
        h.update(f"{Path(p).as_posix()}={sha}\n".encode())
    h.update(registry_blob.encode())
    return h.hexdigest()


def registry_blob(knobs, metric_names, span_names, unitless) -> str:
    # kalint: disable=KA005 -- cache-key fingerprint input, not a Kafka plan payload
    return json.dumps({
        "knobs": sorted(knobs),
        "metric_names": sorted(metric_names),
        "span_names": sorted(span_names),
        "unitless": sorted(unitless),
    }, sort_keys=True)


def load(cache_dir: Path, key: str) -> Optional[List[Finding]]:
    entry = cache_dir / f"{key}.json"
    try:
        payload = json.loads(entry.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    if payload.get("schema") != ANALYSIS_SCHEMA or payload.get("key") != key:
        return None
    try:
        findings = [Finding.from_dict(d) for d in payload["findings"]]
    except (KeyError, TypeError, ValueError):
        return None
    try:
        os.utime(entry)  # LRU recency for the prune below
    except OSError:  # kalint: disable=KA008 -- recency refresh is advisory
        pass
    return findings


def store(cache_dir: Path, key: str, findings: Sequence[Finding]) -> None:
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
        payload = {
            "schema": ANALYSIS_SCHEMA,
            "key": key,
            "findings": [f.to_dict() for f in findings],
        }
        fd, tmp = tempfile.mkstemp(
            dir=str(cache_dir), prefix=".tmp-", suffix=".json"
        )
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            # kalint: disable=KA005 -- analysis cache entry, not a Kafka plan payload
            json.dump(payload, fh)
        os.replace(tmp, cache_dir / f"{key}.json")
        _prune(cache_dir)
    except OSError:
        # A read-only or full cache dir must never fail the lint run; the
        # next run simply re-analyzes.
        return


def _prune(cache_dir: Path) -> None:
    entries: List[Tuple[float, Path]] = []
    for p in cache_dir.glob("*.json"):
        try:
            entries.append((p.stat().st_mtime, p))
        except OSError:  # kalint: disable=KA008 -- entry raced away; nothing to prune
            pass
    entries.sort(reverse=True)
    for _, p in entries[KEEP_ENTRIES:]:
        try:
            p.unlink()
        except OSError:  # kalint: disable=KA008 -- concurrent prune won; goal state reached
            pass
