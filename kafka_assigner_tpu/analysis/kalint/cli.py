"""The ``python -m kafka_assigner_tpu.analysis.kalint`` entry point.

Modes:

- no paths — interprocedural package lint (import graph + call graph +
  taint sets) served through the content-hash cache; ``--root`` points it
  at another package tree (fixtures, tests).
- explicit paths — single-file mode: per-module rules only, no graph, no
  cache (the pre-ISSUE-12 behavior; fast editor integration).

Output:

- text (default) — one ``path:line:col: RULE message`` per finding.
- ``--format json [--out FILE]`` — machine-readable, deterministic:
  findings sorted by (path, line, rule), duplicate reports of one
  violation (same rule/path/line/col — e.g. a graph finding's per-module
  twin) merged chain-preferentially, chains included. Cache status goes
  to stderr only, so two identical runs produce byte-identical payloads.
- ``--explain KA0NN`` (repeatable) — after the findings, print every
  offending call chain (entry → … → sink) for that rule's graph-backed
  findings.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from .driver import lint_package, lint_source
from .findings import Finding, finalize
from .rules import RULES


def _print_explanations(findings: Sequence[Finding], rule: str) -> None:
    picked = [f for f in findings if f.rule == rule]
    if not picked:
        print(f"--explain {rule}: no findings for this rule")
        return
    for f in picked:
        print(f"{rule} at {f.path}:{f.line}: {f.message}")
        if f.chain:
            print("  chain:")
            for i, hop in enumerate(f.chain):
                arrow = "  " if i == 0 else "→ "
                print(f"    {arrow}{hop}")
        else:
            print("  (per-module rule: no call chain — the finding site "
                  "is the whole story)")


def _json_payload(findings: Sequence[Finding], root: str) -> dict:
    return {
        "schema_version": 1,
        "tool": "kalint",
        "root": root,
        "count": len(findings),
        "findings": [f.to_dict() for f in findings],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kalint", description="project-native static analysis "
        "(knob registry + jit-boundary + interprocedural taint/lock/"
        "bulkhead house rules)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files to lint in single-file mode (default: "
                             "the whole package, interprocedurally, plus "
                             "the README knob check)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", metavar="DIR",
                        help="package tree to lint instead of the installed "
                             "kafka_assigner_tpu (fixture trees, tests)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="fmt")
    parser.add_argument("--out", metavar="FILE",
                        help="write the report there instead of stdout")
    parser.add_argument("--explain", action="append", default=[],
                        metavar="KA0NN",
                        help="print the offending call chain for every "
                             "graph-backed finding of this rule "
                             "(repeatable)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the content-hash analysis cache")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}  {desc}")
        return 0
    for rule in args.explain:
        if rule not in RULES:
            parser.error(f"--explain {rule}: unknown rule "
                         f"(see --list-rules)")
    status: dict = {}
    if args.paths:
        pkg = Path(__file__).resolve().parents[2]
        findings: List[Finding] = []
        for raw in args.paths:
            p = Path(raw).resolve()
            try:
                rel = p.relative_to(pkg).as_posix()
            except ValueError:
                rel = p.name
            findings.extend(
                lint_source(p.read_text(encoding="utf-8"), rel, path=raw)
            )
        root_desc = "<files>"
    else:
        findings = lint_package(
            root=args.root,
            use_cache=False if args.no_cache else None,
            _status=status,
        )
        root_desc = args.root or "kafka_assigner_tpu"
    findings = finalize(findings)
    if args.fmt == "json":
        import json as _json

        # kalint: disable=KA005 -- lint report for CI, not a Kafka plan payload
        text = _json.dumps(_json_payload(findings, root_desc), indent=1,
                           sort_keys=True)
        if args.out:
            Path(args.out).write_text(text + "\n", encoding="utf-8")
        else:
            print(text)
    else:
        out_lines = [str(f) for f in findings]
        if args.out:
            Path(args.out).write_text(
                "".join(line + "\n" for line in out_lines),
                encoding="utf-8",
            )
        else:
            for line in out_lines:
                print(line)
    for rule in args.explain:
        _print_explanations(findings, rule)
    n = len(findings)
    if status.get("cache"):
        print(
            f"kalint: analysis cache {status['cache']}"
            + (f" ({status['key'][:12]})" if status.get("key") else ""),
            file=sys.stderr,
        )
    print(
        f"kalint: {n} finding(s)" if n else "kalint: clean",
        file=sys.stderr,
    )
    return 1 if findings else 0
