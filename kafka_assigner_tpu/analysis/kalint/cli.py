"""The ``python -m kafka_assigner_tpu.analysis.kalint`` entry point.

Modes:

- no paths — interprocedural package lint (import graph + call graph +
  taint sets) served through the content-hash cache; ``--root`` points it
  at another package tree (fixtures, tests).
- explicit paths — single-file mode: per-module rules only, no graph, no
  cache (the pre-ISSUE-12 behavior; fast editor integration).

Output:

- text (default) — one ``path:line:col: RULE message`` per finding.
- ``--format json [--out FILE]`` — machine-readable, deterministic:
  findings sorted by (path, line, rule), duplicate reports of one
  violation (same rule/path/line/col — e.g. a graph finding's per-module
  twin) merged chain-preferentially, chains included. Cache status goes
  to stderr only, so two identical runs produce byte-identical payloads.
- ``--format sarif [--out FILE]`` — SARIF 2.1.0 for code-scanning UIs:
  one run, one result per finding, chains rendered as the result's
  ``codeFlows`` thread-flow locations. Deterministic like the JSON.
- ``--explain KA0NN`` (repeatable) — after the findings, print every
  offending call chain (entry → … → sink) for that rule's graph-backed
  findings.
- ``--changed-only`` — restrict the REPORT (never the analysis: graph
  rules need the whole tree) to findings in files modified since the
  analysis cache entry was last written, OR dirty per ``git status``
  (untracked + modified — a checkout rewinds mtimes; git's view does
  not) — the fast pre-commit loop. With no cache baseline (cache
  off/cold) every finding is kept.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from .driver import lint_package, lint_source
from .findings import Finding, finalize
from .rules import RULES


def _print_explanations(findings: Sequence[Finding], rule: str) -> None:
    picked = [f for f in findings if f.rule == rule]
    if not picked:
        print(f"--explain {rule}: no findings for this rule")
        return
    for f in picked:
        print(f"{rule} at {f.path}:{f.line}: {f.message}")
        if f.chain:
            print("  chain:")
            for i, hop in enumerate(f.chain):
                arrow = "  " if i == 0 else "→ "
                print(f"    {arrow}{hop}")
        else:
            print("  (per-module rule: no call chain — the finding site "
                  "is the whole story)")


def _json_payload(findings: Sequence[Finding], root: str) -> dict:
    # "rules" is additive to schema_version 1: CI annotation steps get
    # the catalog (id -> one-line meaning) without re-importing kalint.
    return {
        "schema_version": 1,
        "tool": "kalint",
        "root": root,
        "count": len(findings),
        "rules": dict(sorted(RULES.items())),
        "findings": [f.to_dict() for f in findings],
    }


#: The SARIF version/schema pair the ``--format sarif`` payload declares.
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://docs.oasis-open.org/sarif/sarif/v2.1.0/errata01/os/schemas/"
    "sarif-schema-2.1.0.json"
)


def _sarif_payload(findings: Sequence[Finding]) -> dict:
    """SARIF 2.1.0: the whole rule catalog in the driver (stable ids for
    scanning UIs), one ``result`` per finding, the provenance chain as a
    single thread flow (each ``key@line`` hop located in its module)."""
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": max(f.col, 1),
                    },
                },
            }],
        }
        if f.chain:
            flow = []
            for hop in f.chain:
                key, _, line = hop.rpartition("@")
                relpath = key.partition("::")[0]
                try:
                    lineno = max(int(line), 1)
                except ValueError:
                    lineno = 1
                flow.append({
                    "location": {
                        "physicalLocation": {
                            "artifactLocation": {"uri": relpath},
                            "region": {"startLine": lineno},
                        },
                        "message": {"text": hop},
                    },
                })
            result["codeFlows"] = [
                {"threadFlows": [{"locations": flow}]}
            ]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "kalint",
                "informationUri":
                    "https://github.com/SiftScience/kafka-assigner",
                "rules": [
                    {"id": rule,
                     "shortDescription": {"text": desc}}
                    for rule, desc in sorted(RULES.items())
                ],
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }


def _git_dirty_paths(repo: Path) -> frozenset:
    """Repo-relative posix paths ``git status`` reports as modified or
    untracked. A ``git checkout``/branch switch REWINDS mtimes, so the
    mtime-vs-baseline test alone would serve a stale CLEAN verdict for
    exactly the files that just changed under it; git's own view closes
    that hole. Empty on any failure (no git, not a repo) — the mtime
    baseline then stands alone, the pre-ISSUE-17 behavior."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "-C", str(repo), "status", "--porcelain",
             "--untracked-files=all", "--no-renames"],
            capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):  # kalint: disable=KA008 -- no git here: fall back to the mtime baseline
        return frozenset()
    if proc.returncode != 0:
        return frozenset()
    paths = set()
    for line in proc.stdout.splitlines():
        if len(line) > 3:
            paths.add(line[3:].strip().strip('"'))
    return frozenset(paths)


def _changed_only(findings: Sequence[Finding], repo: Path,
                  baseline: Optional[float]) -> List[Finding]:
    """Drop findings in files not modified since ``baseline`` (the cache
    entry's pre-run mtime) AND not dirty per ``git status`` (untracked +
    modified — mtime rewinds under checkout, git does not). No baseline,
    or an unstattable path, keeps the finding — restriction must only
    ever hide KNOWN-stale results."""
    if baseline is None:
        return list(findings)
    dirty = _git_dirty_paths(repo)
    kept = []
    for f in findings:
        if f.path in dirty:
            kept.append(f)
            continue
        try:
            if (repo / f.path).stat().st_mtime <= baseline:
                continue
        except OSError:  # kalint: disable=KA008 -- unstattable paths stay reported
            pass
        kept.append(f)
    return kept


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kalint", description="project-native static analysis "
        "(knob registry + jit-boundary + interprocedural taint/lock/"
        "bulkhead house rules)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files to lint in single-file mode (default: "
                             "the whole package, interprocedurally, plus "
                             "the README knob check)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", metavar="DIR",
                        help="package tree to lint instead of the installed "
                             "kafka_assigner_tpu (fixture trees, tests)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", dest="fmt")
    parser.add_argument("--out", metavar="FILE",
                        help="write the report there instead of stdout")
    parser.add_argument("--explain", action="append", default=[],
                        metavar="KA0NN",
                        help="print the offending call chain for every "
                             "graph-backed finding of this rule "
                             "(repeatable)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the content-hash analysis cache")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only findings in files modified "
                             "since the analysis cache entry (analysis "
                             "still runs whole-tree; package mode only)")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}  {desc}")
        return 0
    for rule in args.explain:
        if rule not in RULES:
            parser.error(f"--explain {rule}: unknown rule "
                         f"(see --list-rules)")
    status: dict = {}
    if args.paths:
        pkg = Path(__file__).resolve().parents[2]
        findings: List[Finding] = []
        for raw in args.paths:
            p = Path(raw).resolve()
            try:
                rel = p.relative_to(pkg).as_posix()
            except ValueError:
                rel = p.name
            findings.extend(
                lint_source(p.read_text(encoding="utf-8"), rel, path=raw)
            )
        root_desc = "<files>"
    else:
        findings = lint_package(
            root=args.root,
            use_cache=False if args.no_cache else None,
            _status=status,
        )
        root_desc = args.root or "kafka_assigner_tpu"
    findings = finalize(findings)
    if args.changed_only and not args.paths:
        repo = Path(args.root).resolve().parent if args.root \
            else Path(__file__).resolve().parents[3]
        findings = _changed_only(
            findings, repo, status.get("baseline_mtime"))
    if args.fmt in ("json", "sarif"):
        import json as _json

        payload = (_sarif_payload(findings) if args.fmt == "sarif"
                   else _json_payload(findings, root_desc))
        # kalint: disable=KA005 -- lint report for CI, not a Kafka plan payload
        text = _json.dumps(payload, indent=1, sort_keys=True)
        if args.out:
            Path(args.out).write_text(text + "\n", encoding="utf-8")
        else:
            print(text)
    else:
        out_lines = [str(f) for f in findings]
        if args.out:
            Path(args.out).write_text(
                "".join(line + "\n" for line in out_lines),
                encoding="utf-8",
            )
        else:
            for line in out_lines:
                print(line)
    for rule in args.explain:
        _print_explanations(findings, rule)
    n = len(findings)
    if status.get("cache"):
        print(
            f"kalint: analysis cache {status['cache']}"
            + (f" ({status['key'][:12]})" if status.get("key") else ""),
            file=sys.stderr,
        )
    print(
        f"kalint: {n} finding(s)" if n else "kalint: clean",
        file=sys.stderr,
    )
    return 1 if findings else 0
