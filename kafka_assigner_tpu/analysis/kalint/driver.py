"""Lint drivers: single-module (``lint_source``) and whole-package
(``lint_package``, interprocedural + cached).

``lint_source`` is the fixture-friendly single-file mode: no project graph,
the same-module jit closure approximates the traced set (exactly the
pre-ISSUE-12 behavior). ``lint_package`` builds the project resolution
layer, runs the per-module rules WITH the graph rules re-founded on real
reachability, applies suppressions, and serves/stores the result through
the content-hash cache.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set

from . import cache as _cache
from .findings import Finding, SuppressionIndex, finalize, sort_findings
from .resolve import Project, build_project
from . import rules as _r


def _registries(knobs=None, metric_names=None, span_names=None):
    if knobs is None:
        from ...utils.env import KNOBS

        knobs = set(KNOBS)
    if metric_names is None or span_names is None:
        from ...obs.names import METRIC_NAMES, SPAN_NAMES

        if metric_names is None:
            metric_names = METRIC_NAMES
        if span_names is None:
            span_names = SPAN_NAMES
    return set(knobs), set(metric_names), set(span_names)


def _module_findings(
    tree: ast.AST, relpath: str, path: str,
    knobs: Set[str], metric_names: Set[str], span_names: Set[str],
    interprocedural: bool,
) -> List[Finding]:
    return (
        _r.check_ka001(tree, relpath, path)
        + _r.check_ka002(tree, relpath, path,
                         interprocedural=interprocedural)
        + _r.check_ka003(tree, knobs, path)
        + _r.check_ka005(tree, relpath, path)
        + _r.check_ka006(tree, path)
        + _r.check_ka007(tree, path, interprocedural=interprocedural)
        + _r.check_ka008(tree, path)
        + _r.check_ka009(tree, relpath, path)
        + _r.check_ka010(tree, relpath, path)
        + _r.check_ka011(tree, path)
        + _r.check_ka012(tree, relpath, path)
        + _r.check_ka013(tree, path, metric_names, span_names)
        + _r.check_ka030(tree, relpath, path)
    )


def _smoke_scripts(repo: Path) -> List[tuple]:
    """The repo's smoke-test harnesses (``scripts/*_smoke.py``), as
    ``(relpath, path)`` pairs for :func:`~.resolve.build_project`'s
    ``extra_modules``: resolving them into the project graph puts their
    hand-rolled request plumbing under the interprocedural sweeps
    (KA013/KA015/KA019 and friends) instead of leaving it invisible."""
    scripts = repo / "scripts"
    if not scripts.is_dir():
        return []
    return [(f"scripts/{p.name}", p)
            for p in sorted(scripts.glob("*_smoke.py"))]


def _script_module_findings(
    tree: ast.AST, relpath: str, path: str,
    knobs: Set[str], metric_names: Set[str], span_names: Set[str],
) -> List[Finding]:
    """The per-module rule subset for injected smoke scripts: the
    hygiene rules that travel (raw knob reads KA001, knob-name typos
    KA003, swallowed exceptions KA008, unbounded blocking loops KA011,
    obs-name typos KA013). The package house rules stay out of scope —
    a test harness legitimately emits its own JSON (KA005), shells out
    (KA015 sinks), and never touches kernels or the wire client."""
    return (
        _r.check_ka001(tree, relpath, path)
        + _r.check_ka003(tree, knobs, path)
        + _r.check_ka008(tree, path)
        + _r.check_ka011(tree, path)
        + _r.check_ka013(tree, path, metric_names, span_names)
    )


def lint_source(
    src: str,
    relpath: str,
    *,
    knobs: Optional[Set[str]] = None,
    metric_names: Optional[Set[str]] = None,
    span_names: Optional[Set[str]] = None,
    path: Optional[str] = None,
) -> List[Finding]:
    """Lint one module in isolation. ``relpath`` is the package-relative
    posix path (it selects the module class: registry / kernel / json
    boundary); ``path`` is the display path for findings (defaults to
    ``relpath``)."""
    path = path or relpath
    knobs, metric_names, span_names = _registries(
        knobs, metric_names, span_names
    )
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(
            "KA000", path, e.lineno or 1, (e.offset or 0) + 1,
            f"syntax error: {e.msg}",
        )]
    suppress = SuppressionIndex(src, path, tree)
    raw = _module_findings(
        tree, relpath, path, knobs, metric_names, span_names,
        interprocedural=False,
    )
    findings = list(suppress.metas) + suppress.apply(raw)
    return sort_findings(findings)


def _display_path(p: Path, repo: Path) -> str:
    try:
        return p.relative_to(repo).as_posix()
    except ValueError:
        return str(p)


def lint_tree(root: Path, *, project: Optional[Project] = None,
              ) -> List[Finding]:
    """The uncached whole-tree pass: per-module rules (graph-aware mode) +
    project graph rules + README/registry checks, suppressions applied."""
    root = Path(root).resolve()
    repo = root.parent
    knobs, metric_names, span_names = _registries()
    if project is None:
        project = build_project(root,
                                extra_modules=_smoke_scripts(repo))
    display: Dict[str, str] = {}
    indexes: Dict[str, SuppressionIndex] = {}
    findings: List[Finding] = []
    for relpath in sorted(project.modules):
        mod = project.modules[relpath]
        injected = relpath.split("/", 1)[0] in project.extra_tops
        # injected modules live under the REPO (scripts/), not the
        # package root: their relpath already IS the repo-relative path
        path = relpath if injected \
            else _display_path(root / relpath, repo)
        display[relpath] = path
        idx = SuppressionIndex(mod.src, path, mod.tree)
        indexes[path] = idx
        findings.extend(idx.metas)
        if injected:
            findings.extend(idx.apply(_script_module_findings(
                mod.tree, relpath, path, knobs, metric_names,
                span_names,
            )))
        else:
            findings.extend(idx.apply(_module_findings(
                mod.tree, relpath, path, knobs, metric_names,
                span_names, interprocedural=True,
            )))
    # unparsable files never make it into the project: lint them alone so
    # their KA000 still surfaces
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        rel = p.relative_to(root).as_posix()
        if rel in project.modules:
            continue
        try:
            src = p.read_text(encoding="utf-8")
        except OSError:  # kalint: disable=KA008 -- file raced away mid-walk; nothing to lint
            continue
        findings.extend(lint_source(
            src, rel, knobs=knobs, metric_names=metric_names,
            span_names=span_names, path=_display_path(p, repo),
        ))
    graph = _r.project_findings(project, display)
    for f in graph:
        idx = indexes.get(f.path)
        if idx is not None and idx.covers(f.rule, f.line):
            continue
        findings.append(f)
    # Registry-level checks (KA004 README drift, KA014 metric units) only
    # make sense against the REAL package and its repo README — a fixture
    # tree under --root must not be judged against the live registries'
    # documentation state.
    if root == Path(__file__).resolve().parents[2]:
        readme = repo / "README.md"
        if readme.is_file():
            findings.extend(
                _r.check_readme(readme.read_text(encoding="utf-8"))
            )
        findings.extend(_r.check_metric_units())
        # KA018 dead-knob sweep: every registered knob must be read
        # somewhere in the package (fixture trees exercise the checker
        # directly — their registries are not the live one).
        findings.extend(_r.check_dead_knobs(
            {rel: m.tree for rel, m in project.modules.items()},
            display=display,
        ))
    return sort_findings(findings)


def lint_package(root: Optional[Path | str] = None,
                 use_cache: Optional[bool] = None,
                 _status: Optional[dict] = None) -> List[Finding]:
    """Lint a package tree (default: the installed ``kafka_assigner_tpu``)
    plus the README knob check; the empty list is the green state
    ``scripts/lint.sh`` gates on. Results are served from the content-hash
    cache unless disabled (``use_cache=False`` or ``KA_LINT_CACHE=0``);
    ``_status`` (when given) receives ``{"cache": "hit"|"miss"|"off"}``."""
    pkg = Path(root).resolve() if root else \
        Path(__file__).resolve().parents[2]
    repo = pkg.parent
    if use_cache is None:
        use_cache = _cache.cache_enabled()
    status = _status if _status is not None else {}
    if not use_cache:
        status["cache"] = "off"
        return lint_tree(pkg)
    knobs, metric_names, span_names = _registries()
    from ...obs.names import UNITLESS_METRICS

    blob = _cache.registry_blob(
        knobs, metric_names, span_names, UNITLESS_METRICS
    )
    readme = repo / "README.md"
    extra = [readme] if readme.is_file() else []
    # the injected smoke scripts are analysis inputs too: editing one
    # must invalidate the cached result like editing a package module
    extra.extend(p for _rel, p in _smoke_scripts(repo))
    key = _cache.tree_fingerprint(pkg, extra_files=extra,
                                  registry_blob=blob)
    cache_dir = _cache.default_cache_dir(
        Path(__file__).resolve().parents[3]
    )
    # --changed-only baseline: the cache entry's mtime marks the last
    # time this exact tree state was analyzed/validated. Stat BEFORE
    # load() — a hit re-stamps the entry (LRU freshness), which would
    # otherwise collapse the "changed since" window to zero. On a miss
    # (tree edited), the newest surviving entry marks the previous run.
    entry = cache_dir / f"{key}.json"
    try:
        status["baseline_mtime"] = entry.stat().st_mtime
    except OSError:
        mtimes = []
        for p in cache_dir.glob("*.json"):
            try:
                mtimes.append(p.stat().st_mtime)
            except OSError:  # kalint: disable=KA008 -- entry pruned mid-scan; not a baseline
                continue
        if mtimes:
            status["baseline_mtime"] = max(mtimes)
    cached = _cache.load(cache_dir, key)
    if cached is not None:
        status["cache"] = "hit"
        status["key"] = key
        return cached
    findings = lint_tree(pkg)
    _cache.store(cache_dir, key, findings)
    status["cache"] = "miss"
    status["key"] = key
    return findings
