"""Taint engine over the project call graph: the two transitive fact sets
the graph rules consume.

- **traced set** — every function reachable from a jit entry: a function
  decorated with (or handed to) ``jax.jit``/``pjit``/``shard_map`` —
  including ``@partial(jax.jit, ...)`` and the ``*_jit = jax.jit(f, ...)``
  binding idiom — closed transitively over the call graph ACROSS module
  boundaries. Code in this set runs under trace: host-sync (KA002),
  mutable-global capture (KA007), trace-time knob reads (KA016) and
  metric emission (KA017) all freeze or leak there.

- **lock-held set** — every function reachable from a ``with <solve-lock>``
  region in ``daemon/``: the shared solve lock serializes every solve-
  bearing request across all clusters, so anything blocking in this set
  (KA015) multiplies into every client's tail latency — the invariant the
  request-coalescing refactor depends on staying machine-checked.

Both sets carry parent pointers so every membership has a demonstrable
chain (entry → … → function) for ``--explain`` and the finding payload.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .resolve import FUNC, Project, _LocalEnv

#: Callable names that wrap a function for tracing.
JIT_WRAPPER_NAMES = frozenset({"jit", "pjit", "shard_map"})

#: Lock-name fragment that marks a ``with`` region as solve-lock-held.
SOLVE_LOCK_FRAGMENT = "solve_lock"

#: Daemon package prefix the lock scan is confined to.
DAEMON_PREFIX = "daemon/"

#: Host-only boundaries the TRACED closure does not descend into: calling
#: into the knob registry or the obs plane from traced code is itself the
#: finding (KA016/KA017 fire at the call site); their internals are host
#: implementation by construction (obs/ never touches jax — KA006/KA013
#: docs) and re-reporting them adds noise, not signal.
TRACED_STOP_PREFIXES = ("obs/",)
TRACED_STOP_MODULES = frozenset({"utils/env.py"})


def _traced_stops_at(callee_key: str) -> bool:
    relpath = callee_key.partition("::")[0]
    return relpath in TRACED_STOP_MODULES or any(
        relpath.startswith(p) for p in TRACED_STOP_PREFIXES
    )


def is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``pjit`` / ``shard_map`` in any spelling: a bare name
    (``from jax import jit``) or the terminal attribute of any dotted path
    (``jax.jit``, ``jax.experimental.shard_map.shard_map``)."""
    if isinstance(node, ast.Name):
        return node.id in JIT_WRAPPER_NAMES
    return isinstance(node, ast.Attribute) and node.attr in JIT_WRAPPER_NAMES


@dataclass
class TaintResult:
    """A reachability closure with provenance. ``parents`` maps each member
    to its (caller key, call-site line); roots map to (None, root line).
    ``entry_of`` names the root that first reached each member."""
    members: Set[str] = field(default_factory=set)
    parents: Dict[str, Tuple[Optional[str], int]] = field(
        default_factory=dict)
    entry_of: Dict[str, str] = field(default_factory=dict)
    #: root key -> human label ("jit entry solve_batched_jit", ...)
    root_labels: Dict[str, str] = field(default_factory=dict)

    def chain(self, key: str) -> List[Tuple[str, int]]:
        """(func key, call-site line) hops from the entry to ``key``
        inclusive; the entry's line is its root line."""
        hops: List[Tuple[str, int]] = []
        cur: Optional[str] = key
        seen: Set[str] = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            parent, line = self.parents.get(cur, (None, 0))
            hops.append((cur, line))
            cur = parent
        hops.reverse()
        return hops

    def chain_strs(self, key: str) -> Tuple[str, ...]:
        return tuple(f"{k}@{line}" for k, line in self.chain(key))


def _expand(project: Project, result: TaintResult,
            frontier: List[str], stop=None) -> None:
    """The shared closure loop: grow ``result`` over the call graph from
    ``frontier`` (whose members/parents/entries are already seeded);
    ``stop(callee_key)`` prunes traversal INTO a callee (boundary rules
    fire at the call site instead). One implementation so the traced and
    lock-held sets can never diverge on traversal semantics."""
    while frontier:
        cur = frontier.pop()
        for callee, line in project.callees(cur).items():
            if callee in result.members:
                continue
            if stop is not None and stop(callee):
                continue
            result.members.add(callee)
            result.parents[callee] = (cur, line)
            result.entry_of[callee] = result.entry_of[cur]
            frontier.append(callee)


def _closure(project: Project,
             roots: Dict[str, Tuple[int, str]],
             stop=None) -> TaintResult:
    """BFS over the call graph from ``roots`` ({key: (line, label)})."""
    result = TaintResult()
    frontier: List[str] = []
    for key, (line, label) in roots.items():
        if key not in project.functions:
            continue
        result.members.add(key)
        result.parents[key] = (None, line)
        result.entry_of[key] = key
        result.root_labels[key] = label
        frontier.append(key)
    _expand(project, result, frontier, stop=stop)
    return result


# -- jit entries -------------------------------------------------------------

def jit_roots(project: Project) -> Dict[str, Tuple[int, str]]:
    """Every function the project hands to a tracing wrapper, resolved
    ACROSS modules: decorators (``@jax.jit``, ``@jax.jit(...)``,
    ``@partial(jax.jit, ...)``) and call-argument form
    (``f_jit = jax.jit(f, ...)`` — ``f`` may be imported)."""
    roots: Dict[str, Tuple[int, str]] = {}

    def add(key: Optional[str], line: int, label: str) -> None:
        if key is not None and key not in roots:
            roots[key] = (line, label)

    for mod in project.modules.values():
        for fn in list(mod.functions.values()):
            for dec in fn.node.decorator_list:
                wrapped = None
                if is_jit_expr(dec):
                    wrapped = dec
                elif isinstance(dec, ast.Call):
                    if is_jit_expr(dec.func):
                        wrapped = dec.func
                    elif (
                        (isinstance(dec.func, ast.Name)
                         and dec.func.id == "partial")
                        or (isinstance(dec.func, ast.Attribute)
                            and dec.func.attr == "partial")
                    ) and dec.args and is_jit_expr(dec.args[0]):
                        wrapped = dec.args[0]
                if wrapped is not None:
                    add(fn.key, fn.node.lineno,
                        f"jit entry {fn.qualname} ({mod.relpath})")
        # call-argument form anywhere in the module (module scope AND
        # inside functions — a local `fn = jax.jit(_fresh_solve, ...)`
        # still traces _fresh_solve)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and is_jit_expr(node.func)
                    and node.args):
                continue
            arg = node.args[0]
            target = None
            if isinstance(arg, (ast.Name, ast.Attribute)):
                target = project._resolve_expr_target(
                    mod, arg, _LocalEnv())
            if target is not None and target[0] == FUNC:
                fi = project.function(target[1])
                if fi is not None:
                    add(fi.key, node.lineno,
                        f"jit entry {fi.qualname} "
                        f"(wrapped at {mod.relpath}:{node.lineno})")
    # NOTE: the `*_jit` ENTRY idiom (`solve_batched_jit = jax.jit(
    # solve_batched, ...)`) is covered above by resolving the wrapper's
    # call argument — a mere `*_jit`-NAMED def is a host-side dispatch
    # wrapper (solvers/tpu.py `_fresh_solve_jit`, programstore `wrap_jit`)
    # and must NOT seed the traced set.
    return roots


def traced_set(project: Project) -> TaintResult:
    if project._traced is None:
        project._traced = _closure(
            project, jit_roots(project), stop=_traced_stops_at
        )
    return project._traced


# -- solve-lock regions ------------------------------------------------------

@dataclass
class LockRegion:
    """One ``with <solve-lock>`` block: the function holding it, the with
    statement, and every node that executes UNDER the lock — the body
    statements plus the context expressions of with-items listed AFTER
    the lock item (``with self._solve_lock, obs.run_capture(...)``: the
    second manager enters while the lock is already held)."""
    funckey: str
    relpath: str
    line: int
    held_nodes: List[ast.AST]


def _mentions_solve_lock(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) \
                and SOLVE_LOCK_FRAGMENT in node.attr:
            return True
        if isinstance(node, ast.Name) and SOLVE_LOCK_FRAGMENT in node.id:
            return True
    return False


def lock_regions(project: Project) -> List[LockRegion]:
    regions: List[LockRegion] = []
    for relpath, mod in sorted(project.modules.items()):
        if not relpath.startswith(DAEMON_PREFIX):
            continue
        for fn in mod.functions.values():
            for node in ast.walk(fn.node):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                lock_idx = next(
                    (i for i, item in enumerate(node.items)
                     if _mentions_solve_lock(item.context_expr)),
                    None,
                )
                if lock_idx is None:
                    continue
                held: List[ast.AST] = [
                    item.context_expr
                    for item in node.items[lock_idx + 1:]
                ]
                held.extend(node.body)
                regions.append(LockRegion(
                    funckey=fn.key, relpath=relpath,
                    line=node.lineno, held_nodes=held,
                ))
    return regions


# -- inflight-gate regions (KA019, the KA015 twin) ---------------------------

#: The supervisor's admission call: code AFTER a successful ``_gate()``
#: holds one of the cluster's bounded inflight slots until ``_release()``.
GATE_CALL_NAME = "_gate"


@dataclass
class GateRegion:
    """One admission region: the daemon function calling ``_gate()`` and
    every statement that executes AFTER the call in the same block (the
    release lives in a ``finally``, so to a static pass the rest of the
    function body runs admitted — a deliberate over-approximation, same
    posture as treating a whole lock body as held)."""
    funckey: str
    relpath: str
    line: int
    held_nodes: List[ast.AST]


def _calls_gate(stmt: ast.AST) -> Optional[int]:
    for node in ast.walk(stmt):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == GATE_CALL_NAME
        ):
            return node.lineno
    return None


def gate_regions(project: Project) -> List[GateRegion]:
    regions: List[GateRegion] = []
    for relpath, mod in sorted(project.modules.items()):
        if not relpath.startswith(DAEMON_PREFIX):
            continue
        for fn in mod.functions.values():
            for node in ast.walk(fn.node):
                for field in ("body", "orelse", "finalbody"):
                    block = getattr(node, field, None)
                    if not isinstance(block, list):
                        continue
                    for i, stmt in enumerate(block):
                        line = _calls_gate(stmt)
                        if line is None:
                            continue
                        held = list(block[i + 1:])
                        if held:
                            regions.append(GateRegion(
                                funckey=fn.key, relpath=relpath,
                                line=line, held_nodes=held,
                            ))
    return regions


def _region_closure(project: Project, regions, label_fn) -> TaintResult:
    """The shared held-region closure: seed every call inside each
    region's held statements, root the holder functions themselves
    (``label_fn(region)`` names them), and expand over the call graph.
    One implementation for the solve-lock and inflight-gate sets so the
    twin rules (KA015/KA019) can never diverge on traversal or
    provenance semantics."""
    result = TaintResult()
    seeds: List[Tuple[str, str, int]] = []
    for region in regions:
        mod = project.modules[region.relpath]
        fn = project.functions[region.funckey]
        env = project.function_env(mod, fn)
        for stmt in region.held_nodes:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    callee = project.resolve_call(mod, fn, node, env)
                    if callee is not None:
                        seeds.append(
                            (callee, region.funckey, node.lineno))
        result.members.add(region.funckey)
        result.parents.setdefault(region.funckey, (None, region.line))
        result.entry_of[region.funckey] = region.funckey
        result.root_labels[region.funckey] = label_fn(region)
    frontier: List[str] = []
    for callee, holder, line in seeds:
        if callee in result.members or callee not in project.functions:
            continue
        result.members.add(callee)
        result.parents[callee] = (holder, line)
        result.entry_of[callee] = result.entry_of.get(holder, holder)
        frontier.append(callee)
    _expand(project, result, frontier)
    return result


def gate_held_set(project: Project) -> Tuple[TaintResult, List[GateRegion]]:
    """The closure of functions reachable from inside any inflight-gate
    admission region — structurally the lock-held set's twin (KA019):
    admitted slots are the per-cluster backpressure budget, so a blocked
    admitted request starves the gate exactly like a blocked solve-lock
    holder starves the solve."""
    if project._gate_held is None:
        regions = gate_regions(project)
        result = _region_closure(
            project, regions,
            lambda r: (f"inflight-gate region {r.funckey} "
                       f"(_gate at line {r.line})"),
        )
        project._gate_held = (result, regions)
    return project._gate_held


def lock_held_set(project: Project) -> Tuple[TaintResult, List[LockRegion]]:
    """The closure of functions reachable from inside any solve-lock
    region. The REGION-HOLDING functions themselves are roots (labelled
    with the with-statement line); direct in-region sinks are the rule
    pass's job since only part of the holder's body is under the lock."""
    if project._lock_held is None:
        regions = lock_regions(project)
        result = _region_closure(
            project, regions,
            lambda r: (f"solve-lock region {r.funckey} "
                       f"(with at line {r.line})"),
        )
        project._lock_held = (result, regions)
    return project._lock_held
