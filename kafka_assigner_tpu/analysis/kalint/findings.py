"""Finding model, suppression handling, and deterministic output shaping.

A :class:`Finding` is one rule violation at one source location; graph-backed
rules attach the offending call ``chain`` (entry → … → sink) so ``--explain``
and the JSON output can show WHY a cross-module fact fired, not just where.

Suppression contract (unchanged since ISSUE 1, extended for wrapped
statements in ISSUE 12): ``# kalint: disable=KA0NN -- <reason>`` on the
offending line, on the line directly above, or — for a statement wrapped
over several physical lines — on ANY physical line the statement spans
(the reported line is always the statement's first line, but a trailing
comment naturally lands on the last). A reasonless suppression is itself a
finding (KA000) and suppresses nothing.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*kalint:\s*disable=([A-Z0-9, ]+?)\s*(?:--\s*(\S.*))?$"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    #: Offending call chain for graph-backed findings (entry → … → sink),
    #: each hop ``"<relpath>::<qualname>@<line>"``; empty for single-file
    #: rules. Compared/hased like any other field, but excluded from the
    #: identity dedupe key (two chains to one sink are still one finding).
    chain: Tuple[str, ...] = field(default=())

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.chain:
            d["chain"] = list(self.chain)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(
            rule=d["rule"], path=d["path"], line=int(d["line"]),
            col=int(d["col"]), message=d["message"],
            chain=tuple(d.get("chain") or ()),
        )


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    """Deterministic order: (path, line, rule) first — the stable-diff
    contract for ``--format json`` — with col/message as tiebreakers so the
    order is total regardless of dict/set iteration order or Python
    version."""
    return sorted(
        findings, key=lambda f: (f.path, f.line, f.rule, f.col, f.message)
    )


def dedupe_findings(findings: Sequence[Finding]) -> List[Finding]:
    """Drop duplicate reports of one violation — identical
    (rule, path, line, col) — keeping, per group, a chain-bearing finding
    when one exists (the chain is the explanation; the per-module twin of
    a graph finding anchors to the SAME call node and adds nothing). The
    col in the key is what keeps two DISTINCT sinks sharing a source line
    both reported. Input order is preserved for the survivors; callers
    sort first."""
    best: Dict[Tuple[str, str, int, int], Finding] = {}
    order: List[Tuple[str, str, int, int]] = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col)
        if key not in best:
            best[key] = f
            order.append(key)
        elif f.chain and not best[key].chain:
            best[key] = f
    return [best[k] for k in order]


def finalize(findings: Iterable[Finding]) -> List[Finding]:
    """sort + dedupe: the printed/serialized form."""
    return dedupe_findings(sort_findings(findings))


def _effective_span(stmt: ast.stmt) -> Tuple[int, int]:
    """The physical lines a suppression comment may ride on for ``stmt``:
    the full span for simple statements (a wrapped call's trailing comment
    sits on its last line), the HEADER only for compound statements (a
    comment inside a ``while``/``with`` body must not suppress a finding
    anchored on the header — the body's own statements carry their own
    spans)."""
    body = getattr(stmt, "body", None)
    if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
        end = max(stmt.lineno, body[0].lineno - 1)
    else:
        end = getattr(stmt, "end_lineno", None) or stmt.lineno
    return stmt.lineno, end


class SuppressionIndex:
    """Per-module suppression state: the comment-line table (a comment
    covers its own line and the one below), the statement spans that widen
    coverage to every physical line a wrapped statement occupies, and the
    KA000 metas for reasonless suppressions."""

    def __init__(self, src: str, path: str, tree: ast.AST | None = None):
        self.path = path
        self.table: Dict[int, Set[str]] = {}
        self.metas: List[Finding] = []
        self._spans: List[Tuple[int, int]] = []
        self._scan_comments(src, path)
        if tree is not None:
            for node in ast.walk(tree):
                if isinstance(node, ast.stmt):
                    self._spans.append(_effective_span(node))

    def _scan_comments(self, src: str, path: str) -> None:
        try:
            comments = [
                t for t in tokenize.generate_tokens(io.StringIO(src).readline)
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []  # unparsable source is KA000 via ast.parse already
        for tok in comments:
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            lineno = tok.start[0]
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.metas.append(Finding(
                    "KA000", path, lineno, tok.start[1] + m.start() + 1,
                    "suppression requires a reason: "
                    "'# kalint: disable=KAnnn -- <why>'",
                ))
                continue
            self.table.setdefault(lineno, set()).update(rules)
            self.table.setdefault(lineno + 1, set()).update(rules)

    def _enclosing_span(self, line: int) -> Tuple[int, int]:
        """The innermost statement span containing ``line`` (smallest, then
        latest-starting), or the line itself when no statement matches."""
        best: Tuple[int, int] | None = None
        for start, end in self._spans:
            if start <= line <= end:
                if best is None or (end - start, -start) < (
                    best[1] - best[0], -best[0]
                ):
                    best = (start, end)
        return best or (line, line)

    def covers(self, rule: str, line: int) -> bool:
        span = self._enclosing_span(line)
        return any(
            rule in self.table.get(ln, ())
            for ln in range(span[0], span[1] + 1)
        )

    def apply(self, findings: Iterable[Finding]) -> List[Finding]:
        """Findings that survive suppression (metas NOT included)."""
        return [f for f in findings if not self.covers(f.rule, f.line)]
