"""Thread-topology and shared-state layer (ISSUE 16): who runs where,
what they share, and under which locks.

The PR 12 graph knows what is *reachable*; this layer adds *from which
thread* and *holding which locks*, the two facts the RacerD/Eraser-style
rules (KA021/KA022/KA023) consume:

- **thread entries** — every statically-resolvable ``threading.Thread(
  target=...)``, ``threading.Timer(...)``, and ``executor.submit(...)``
  in the project, plus two seeded surfaces the resolver cannot see: the
  HTTP handler surface (the handler classes are closure-nested inside
  ``_build_http_server`` — their bodies fold into it, but the routed
  ``sup.<method>()`` calls are untyped, so the supervisor request methods
  are seeded explicitly) and the daemon main thread. Unresolvable targets
  (closure-nested functions like the warm-up worker and the watchdog
  timer body, out-of-project callables like ``serve_forever``) contribute
  no entry — the model under-approximates, same posture as the resolver.

- **lock registry** — every in-project ``threading.Lock``/``RLock``/
  ``Condition`` bound to a ``self.<attr>`` or a module global, identified
  BY NAME: the tree passes locks around under their defining name
  (``service._solve_lock`` becomes ``supervisor._solve_lock``), so
  same-named attributes unify into one may-alias lock. Coarser than true
  identity — two unrelated ``_lock`` attributes unify — which makes the
  race rules *miss* cross-class confusions rather than invent them.

- **lock-set inference** — per call site and per attribute access, the
  set of locks LEXICALLY held (enclosing ``with`` items that mention a
  known lock name — exact, or as a ``name_``-prefixed helper like
  ``_solve_lock_scope()``), combined per thread entry with MUST-hold
  dataflow: a function's incoming lock set is the intersection over
  every reaching call site (lexical locks at the site plus the caller's
  own must-hold set), iterated to a fixpoint.

- **shared-state model** — ``self.attr`` (and one-level ``self.x.attr``
  through the resolver's instance typing) reads/writes on classes in the
  concurrent subsystems (``daemon/``, ``exec/``), each stamped with its
  thread entry and effective lock set. ``__init__`` bodies are excluded
  (construction happens-before any thread start); attribute loads that
  resolve to methods are calls, not state — and a ``@property`` load IS
  traversed as a call edge, so a property-guarded read's body joins the
  reachable set.

Everything is memoized on the :class:`~.resolve.Project` (one model per
analysis) and every fact carries provenance: entry → … → access chains
for ``--explain`` and the finding payloads.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .resolve import FUNC, FunctionInfo, ModuleInfo, Project, _LocalEnv
from .taint import TaintResult

#: threading constructors whose assignment defines an in-project lock.
LOCK_CTOR_NAMES = frozenset({"Lock", "RLock", "Condition"})

#: Module prefixes whose classes constitute the shared-state model: the
#: concurrent subsystems the daemon's threads actually share. Classes
#: elsewhere (solvers, io, obs internals) are reached too, but their
#: state discipline is owned by their own module contracts — modelling
#: them would trade triage signal for noise.
SHARED_STATE_PREFIXES = ("daemon/", "exec/")

#: The HTTP handler surface, seeded: the handler classes are nested inside
#: ``_build_http_server`` (invisible to the resolver as classes, folded
#: into the builder as code), and their routed ``sup.<method>()`` calls
#: are untyped — so the request methods handlers dispatch into are listed
#: here and existence-checked against the analyzed tree (fixture trees
#: simply match none of them). Every handler thread is CONCURRENT with
#: itself: ThreadingHTTPServer runs one thread per connection.
HTTP_SURFACE_SEEDS: Tuple[Tuple[str, Optional[str], str], ...] = (
    ("daemon/service.py", None, "_build_http_server"),
    ("daemon/supervisor.py", "ClusterSupervisor", "handle"),
    ("daemon/supervisor.py", "ClusterSupervisor", "recommendations"),
    ("daemon/supervisor.py", "ClusterSupervisor", "groups_request"),
    ("daemon/supervisor.py", "ClusterSupervisor", "controller_request"),
    ("daemon/supervisor.py", "ClusterSupervisor", "controller_view"),
    ("daemon/supervisor.py", "ClusterSupervisor", "prepare_execute"),
    ("daemon/supervisor.py", "ClusterSupervisor", "run_execute"),
    ("daemon/supervisor.py", "ClusterSupervisor", "abort_execute"),
    ("daemon/supervisor.py", "ClusterSupervisor", "state_view"),
    ("daemon/supervisor.py", "ClusterSupervisor", "healthz_view"),
    ("daemon/supervisor.py", "ClusterSupervisor", "lifecycle"),
    ("daemon/supervisor.py", "ClusterSupervisor", "stale"),
    ("daemon/supervisor.py", "ClusterSupervisor", "active_requests"),
    ("daemon/supervisor.py", "ClusterSupervisor", "counters"),
)

#: The daemon main thread: process entry, lifecycle, drain.
MAIN_THREAD_SEEDS: Tuple[Tuple[str, Optional[str], str], ...] = (
    ("daemon/service.py", None, "run_daemon_process"),
    ("daemon/service.py", "AssignerDaemon", "serve"),
    ("daemon/service.py", "AssignerDaemon", "start"),
    ("daemon/service.py", "AssignerDaemon", "shutdown"),
)


@dataclass(frozen=True)
class ThreadEntry:
    """One discovered or seeded thread root."""
    key: str              # target funckey (the entry's identity)
    kind: str             # "thread" | "timer" | "executor" | "http" | "main"
    line: int             # creation/seed site line
    relpath: str          # module of the creation/seed site
    label: str            # human label for messages and --explain roots
    #: True when more than one OS thread runs this entry against the SAME
    #: objects (the HTTP surface): its writes race with themselves.
    concurrent: bool = False


@dataclass
class SharedAccess:
    """One attribute read/write, stamped with thread and lock context."""
    owner: Tuple[str, str]        # (relpath, class) of the attribute owner
    attr: str
    entry: str                    # ThreadEntry.key that reaches it
    funckey: str
    line: int
    col: int
    write: bool
    locks: FrozenSet[str]         # effective lock set (lexical ∪ must-hold)


@dataclass
class LockEdge:
    """Lock-order fact: ``inner`` can be acquired while ``outer`` is
    held, witnessed at one concrete acquisition site."""
    outer: str
    inner: str
    funckey: str
    relpath: str
    line: int
    chain: Tuple[str, ...]


@dataclass
class _FnFacts:
    """Per-function lexical facts, entry-independent and memoized:
    resolved call sites (including ``@property`` loads — a property read
    executes its body), raw attribute accesses, and ``with``-acquisitions,
    each with the lock set LEXICALLY held at that point."""
    calls: List[Tuple[str, int, FrozenSet[str]]] = field(
        default_factory=list)
    accesses: List[Tuple[Tuple[str, str], str, int, int, bool,
                         FrozenSet[str]]] = field(default_factory=list)
    withs: List[Tuple[str, int, FrozenSet[str]]] = field(
        default_factory=list)


@dataclass
class ThreadModel:
    entries: List[ThreadEntry]
    #: entry key -> reachable-set closure (with provenance chains)
    reach: Dict[str, TaintResult]
    #: lock name -> definition sites [(relpath, class-or-None, line)]
    locks: Dict[str, List[Tuple[str, Optional[str], int]]]
    #: every access from every entry, lock sets resolved
    accesses: List[SharedAccess]
    #: (outer, inner) -> first witnessing edge
    lock_edges: Dict[Tuple[str, str], LockEdge]
    entry_by_key: Dict[str, ThreadEntry] = field(default_factory=dict)


# -- lock discovery ----------------------------------------------------------

def _is_lock_ctor(value: Optional[ast.expr]) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return name in LOCK_CTOR_NAMES


def discover_locks(project: Project
                   ) -> Dict[str, List[Tuple[str, Optional[str], int]]]:
    """Every ``self.X = threading.Lock()``-style instance attribute and
    every module-global lock binding, keyed by NAME (see module doc for
    the may-alias rationale)."""
    locks: Dict[str, List[Tuple[str, Optional[str], int]]] = {}

    def add(name: str, relpath: str, cls: Optional[str],
            line: int) -> None:
        locks.setdefault(name, []).append((relpath, cls, line))

    for relpath, mod in sorted(project.modules.items()):
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and _is_lock_ctor(stmt.value):
                add(stmt.targets[0].id, relpath, None, stmt.lineno)
        for ci in mod.classes.values():
            for m in ci.methods.values():
                for node in ast.walk(m.node):
                    target = value = None
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1:
                        target, value = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        target, value = node.target, node.value
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                        and _is_lock_ctor(value)
                    ):
                        add(target.attr, relpath, ci.name, node.lineno)
    return locks


def _lock_names_in(expr: ast.AST, known: FrozenSet[str]) -> Set[str]:
    """The known locks a ``with``-item context expression mentions: an
    identifier equal to a lock name, or a ``<name>_``-prefixed helper
    (``self._solve_lock_scope()`` acquires ``_solve_lock``'s regime)."""
    hits: Set[str] = set()
    idents: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            idents.add(node.attr)
        elif isinstance(node, ast.Name):
            idents.add(node.id)
    for ident in idents:
        if ident in known:
            hits.add(ident)
            continue
        for name in known:
            if ident.startswith(name) and \
                    ident[len(name):len(name) + 1] == "_":
                hits.add(name)
    return hits


# -- thread-entry discovery --------------------------------------------------

def _resolve_callable(project: Project, mod: ModuleInfo, fn: FunctionInfo,
                      expr: ast.expr, env: _LocalEnv) -> Optional[str]:
    """A callable-valued expression (a thread target, a timer body, a
    submit argument) resolved to an in-project funckey — the bare-expr
    twin of :meth:`Project.resolve_call`."""
    if isinstance(expr, ast.Attribute):
        v = expr.value
        if isinstance(v, ast.Name):
            if v.id in ("self", "cls") and fn.cls is not None:
                hit = project.find_method(mod.relpath, fn.cls, expr.attr)
                return hit.key if hit else None
            if v.id in env.types:
                rp, cn = env.types[v.id]
                hit = project.find_method(rp, cn, expr.attr)
                return hit.key if hit else None
        if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self" and fn.cls is not None:
            ci = mod.classes.get(fn.cls)
            t = ci.attr_types.get(v.attr) if ci else None
            if t is not None:
                hit = project.find_method(t[0], t[1], expr.attr)
                return hit.key if hit else None
    target = project._resolve_expr_target(mod, expr, env)
    if target is not None and target[0] == FUNC:
        return target[1]
    return None


def _ctor_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def discover_thread_entries(project: Project) -> List[ThreadEntry]:
    """Every statically-resolvable thread root (see module doc). One
    entry per distinct target function — re-spawns of the same target
    are the same thread class; the first creation site labels it."""
    entries: Dict[str, ThreadEntry] = {}

    def add(entry: ThreadEntry) -> None:
        entries.setdefault(entry.key, entry)

    for relpath, mod in sorted(project.modules.items()):
        for fn in mod.functions.values():
            env = project.function_env(mod, fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                ctor = _ctor_name(node)
                target: Optional[ast.expr] = None
                kind = None
                tname: Optional[str] = None
                if ctor == "Thread":
                    kind = "thread"
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                        elif kw.arg == "name":
                            tname = _const_str(kw.value)
                elif ctor == "Timer":
                    kind = "timer"
                    if len(node.args) >= 2:
                        target = node.args[1]
                    for kw in node.keywords:
                        if kw.arg == "function":
                            target = kw.value
                elif ctor == "submit" and isinstance(node.func,
                                                     ast.Attribute):
                    kind = "executor"
                    if node.args:
                        target = node.args[0]
                if kind is None or target is None:
                    continue
                key = _resolve_callable(project, mod, fn, target, env)
                if key is None:
                    continue  # nested/out-of-project target: no entry
                what = f"{kind} {tname!r}" if tname else kind
                add(ThreadEntry(
                    key=key, kind=kind, line=node.lineno, relpath=relpath,
                    label=f"{what} entry {key} "
                          f"(spawned at {relpath}:{node.lineno})",
                ))

    def seed(table, kind: str, label_fmt: str, concurrent: bool) -> None:
        for relpath, cls, name in table:
            if cls is None:
                m = project.module(relpath)
                fi = m.func_by_name.get(name) if m else None
            else:
                fi = project.find_method(relpath, cls, name)
            if fi is None:
                continue
            add(ThreadEntry(
                key=fi.key, kind=kind, line=fi.node.lineno,
                relpath=relpath, label=label_fmt.format(key=fi.key),
                concurrent=concurrent,
            ))

    seed(HTTP_SURFACE_SEEDS, "http",
         "HTTP handler surface {key} (one thread per connection)",
         concurrent=True)
    seed(MAIN_THREAD_SEEDS, "main", "daemon main thread {key}",
         concurrent=False)
    return sorted(entries.values(), key=lambda e: (e.relpath, e.line,
                                                   e.key))


# -- per-function lexical facts ----------------------------------------------

def _is_property(fi: FunctionInfo) -> bool:
    for dec in fi.node.decorator_list:
        if isinstance(dec, ast.Name) and dec.id == "property":
            return True
        if isinstance(dec, ast.Attribute) and dec.attr in (
                "setter", "deleter", "getter"):
            return True
    return False


def _fn_facts(project: Project, funckey: str,
              lock_names: FrozenSet[str]) -> _FnFacts:
    """Lexical walk of one function: call sites, attribute accesses and
    with-acquisitions, each stamped with the locks held at that point."""
    facts = _FnFacts()
    fn = project.functions.get(funckey)
    if fn is None:
        return facts
    mod = project.modules[fn.relpath]
    env = project.function_env(mod, fn)
    ci = mod.classes.get(fn.cls) if fn.cls else None
    in_init = fn.name == "__init__"

    def owner_of(node: ast.Attribute
                 ) -> Optional[Tuple[Tuple[str, str], str]]:
        v = node.value
        if isinstance(v, ast.Name):
            if v.id == "self" and fn.cls is not None:
                return (fn.relpath, fn.cls), node.attr
            t = env.types.get(v.id)
            if t is not None:
                return t, node.attr
        elif isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self" and ci is not None:
            t = ci.attr_types.get(v.attr)
            if t is not None:
                return t, node.attr
        return None

    def record_attr(node: ast.Attribute, held: FrozenSet[str]) -> bool:
        """Record a shared-state access (or a property call edge).
        Returns True when the attribute resolved to a method/property —
        i.e. it is code, not state."""
        hit = owner_of(node)
        if hit is None:
            return False
        (orel, ocls), attr = hit
        m = project.find_method(orel, ocls, attr)
        if m is not None:
            if _is_property(m) and isinstance(node.ctx, ast.Load):
                facts.calls.append((m.key, node.lineno, held))
            return True
        if attr in lock_names:
            return True  # the lock itself is synchronization, not state
        if in_init:
            return True  # happens-before any thread start
        if not orel.startswith(SHARED_STATE_PREFIXES):
            return True
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        facts.accesses.append(
            ((orel, ocls), attr, node.lineno, node.col_offset + 1,
             write, held))
        return True

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: Set[str] = set()
            for item in node.items:
                visit(item.context_expr, held | frozenset(acquired))
                names = _lock_names_in(item.context_expr, lock_names)
                for name in names:
                    facts.withs.append(
                        (name, item.context_expr.lineno,
                         held | frozenset(acquired)))
                acquired |= names
                if item.optional_vars is not None:
                    visit(item.optional_vars, held | frozenset(acquired))
            inner = held | frozenset(acquired)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Call):
            callee = project.resolve_call(mod, fn, node, env)
            if callee is not None and callee != funckey:
                facts.calls.append((callee, node.lineno, held))
        elif isinstance(node, ast.Attribute):
            record_attr(node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.node.body:
        visit(stmt, frozenset())
    return facts


# -- the model ---------------------------------------------------------------

def _entry_closure(entry: ThreadEntry,
                   facts_of, project: Project) -> TaintResult:
    """Reachable set from one entry over the facts call sites (the call
    graph plus property edges), with provenance."""
    result = TaintResult()
    result.members.add(entry.key)
    result.parents[entry.key] = (None, entry.line)
    result.entry_of[entry.key] = entry.key
    result.root_labels[entry.key] = entry.label
    frontier = [entry.key]
    while frontier:
        cur = frontier.pop()
        for callee, line, _locks in facts_of(cur).calls:
            if callee in result.members \
                    or callee not in project.functions:
                continue
            result.members.add(callee)
            result.parents[callee] = (cur, line)
            result.entry_of[callee] = entry.key
            frontier.append(callee)
    return result


def _must_hold(entry: ThreadEntry, reach: TaintResult,
               facts_of) -> Dict[str, FrozenSet[str]]:
    """Per-function MUST-hold lock sets within one entry's reachable set:
    the intersection, over every reaching call site, of the caller's
    must-hold set plus the locks lexically held at the site. Iterated to
    a fixpoint (sets only shrink once assigned; the entry root holds
    nothing)."""
    must: Dict[str, Optional[FrozenSet[str]]] = {
        key: None for key in reach.members
    }
    must[entry.key] = frozenset()
    work = [entry.key]
    while work:
        cur = work.pop()
        base = must[cur]
        if base is None:
            continue
        for callee, _line, locks in facts_of(cur).calls:
            if callee not in must:
                continue
            cand = base | locks
            prev = must[callee]
            new = cand if prev is None else (prev & cand)
            if new != prev:
                must[callee] = new
                work.append(callee)
    return {k: (v if v is not None else frozenset())
            for k, v in must.items()}


def _lock_order_edges(locks: Dict[str, List[Tuple[str, Optional[str],
                                                  int]]],
                      facts_of, all_keys: Sequence[str],
                      ) -> Dict[Tuple[str, str], LockEdge]:
    """May-hold lock-order facts: an acquisition of B lexically under A,
    or anywhere in a function reachable from a call site where A is
    held. One witnessing edge per (A, B); self-edges are excluded (an
    RLock — and any same-named alias — re-enters legally)."""
    edges: Dict[Tuple[str, str], LockEdge] = {}

    def add(outer: str, inner: str, funckey: str, line: int,
            chain: Tuple[str, ...]) -> None:
        if outer == inner:
            return
        edges.setdefault((outer, inner), LockEdge(
            outer=outer, inner=inner, funckey=funckey,
            relpath=funckey.partition("::")[0], line=line, chain=chain,
        ))

    # Lexical: a with-acquisition whose held set is non-empty.
    for key in all_keys:
        for name, line, held in facts_of(key).withs:
            for outer in sorted(held):
                add(outer, name, key, line, (f"{key}@{line}",))
    # Transitive: close over calls made while each lock is held.
    for outer in sorted(locks):
        result = TaintResult()
        frontier: List[str] = []
        for key in all_keys:
            for callee, line, held in facts_of(key).calls:
                if outer not in held or callee in result.members:
                    continue
                result.members.add(callee)
                result.parents[callee] = (key, line)
                result.entry_of[callee] = key
                result.root_labels.setdefault(
                    key, f"lock {outer} held in {key}")
                frontier.append(callee)
        while frontier:
            cur = frontier.pop()
            for callee, line, _held in facts_of(cur).calls:
                if callee in result.members:
                    continue
                result.members.add(callee)
                result.parents[callee] = (cur, line)
                result.entry_of[callee] = result.entry_of[cur]
                frontier.append(callee)
        for key in sorted(result.members):
            for name, line, _held in facts_of(key).withs:
                add(outer, name, key, line,
                    result.chain_strs(key) + (f"{key}@{line}",))
    return edges


def _resident_classes(project: Project,
                      entries: Sequence[ThreadEntry]
                      ) -> Set[Tuple[str, str]]:
    """Classes whose instances can actually be SHARED between threads:
    the classes owning thread-entry methods, closed transitively over
    their instance-attribute types (``self.x = Class(...)``) and their
    in-project bases. An instance of any other class only ever lives in
    function locals (e.g. the ``PlanExecutor`` a handler constructs,
    drives, and drops within one request) — thread-confined by
    construction, so its attributes are not shared state."""
    resident: Set[Tuple[str, str]] = set()
    work: List[Tuple[str, str]] = []
    for e in entries:
        fn = project.functions.get(e.key)
        if fn is not None and fn.cls is not None:
            work.append((fn.relpath, fn.cls))
    while work:
        rc = work.pop()
        if rc in resident:
            continue
        resident.add(rc)
        ci = project.class_info(*rc)
        if ci is None:
            continue
        work.extend(ci.attr_types.values())
        work.extend(ci.resolved_bases)
    return resident


def thread_model(project: Project) -> ThreadModel:
    """Build (once per project) the full thread/shared-state model."""
    cached = getattr(project, "_threads", None)
    if cached is not None:
        return cached

    lock_defs = discover_locks(project)
    lock_names = frozenset(lock_defs)
    facts_cache: Dict[str, _FnFacts] = {}

    def facts_of(key: str) -> _FnFacts:
        if key not in facts_cache:
            facts_cache[key] = _fn_facts(project, key, lock_names)
        return facts_cache[key]

    entries = discover_thread_entries(project)
    resident = _resident_classes(project, entries)
    reach: Dict[str, TaintResult] = {}
    accesses: List[SharedAccess] = []
    for entry in entries:
        if entry.key not in project.functions:
            continue
        closure = _entry_closure(entry, facts_of, project)
        reach[entry.key] = closure
        must = _must_hold(entry, closure, facts_of)
        for key in sorted(closure.members):
            base = must.get(key, frozenset())
            for owner, attr, line, col, write, held in \
                    facts_of(key).accesses:
                if owner not in resident:
                    continue  # thread-confined (function-local) object
                accesses.append(SharedAccess(
                    owner=owner, attr=attr, entry=entry.key,
                    funckey=key, line=line, col=col, write=write,
                    locks=frozenset(base | held),
                ))

    edges = _lock_order_edges(
        lock_defs, facts_of, sorted(project.functions))
    model = ThreadModel(
        entries=[e for e in entries if e.key in reach],
        reach=reach, locks=lock_defs, accesses=accesses,
        lock_edges=edges,
        entry_by_key={e.key: e for e in entries},
    )
    project._threads = model
    return model
