"""All kalint rule passes: the per-module AST checks (KA000–KA014) and the
project-wide graph rules (interprocedural KA002/KA007/KA012, plus
KA015–KA017) that run over the :mod:`.resolve` call graph and the
:mod:`.taint` traced / lock-held sets.

Per-module checks are pure functions of one module's AST (plus the live
knob/name registries); the graph passes are functions of the whole
:class:`~.resolve.Project` and attach the offending call chain
(entry → … → sink) to every finding they emit.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding
from .resolve import Project, split_key
from .taint import (
    gate_held_set,
    is_jit_expr,
    lock_held_set,
    traced_set,
)

RULES = {
    "KA000": "meta finding (syntax error / reasonless suppression)",
    "KA001": "raw os.environ access to a KA_* knob outside the registry",
    "KA002": "host-sync or nondeterminism call in traced kernel code",
    "KA003": "KA_* string literal does not resolve to a registered knob",
    "KA004": "registered knob missing from the README knob table",
    "KA005": "plan JSON emission outside io/json_io.py",
    "KA006": "jnp./jax.numpy call at module import time",
    "KA007": "jit-traced function closes over a mutable module-level global",
    "KA008": "except clause swallows the exception silently (pass/continue)",
    "KA009": "ops/ jit entry dispatched outside a bucket-boundary module",
    "KA010": "ZooKeeper write opcode outside the serial write path",
    "KA011": "unbounded blocking recv/poll loop (no deadline knob consulted)",
    "KA012": "cross-bulkhead access: daemon handler reaches into a "
             "supervisor's backend/cache",
    "KA013": "metric/span name literal not declared in the obs name "
             "registry (obs/names.py)",
    "KA014": "registered metric carries no unit suffix and is not in the "
             "unitless allowlist (obs/names.py)",
    "KA015": "blocking call reachable while the shared solve lock is held",
    "KA016": "KA_* knob accessor called inside jit-traced code "
             "(trace-time freeze)",
    "KA017": "obs write API called inside jit-traced code "
             "(host-sync hazard)",
    "KA018": "dead knob: registered in utils/env.py but never read "
             "through an accessor anywhere in the project",
    "KA019": "blocking call reachable while a supervisor's inflight-gate "
             "admission is held",
    "KA020": "blocking-call budget: a chain under the solve lock, an "
             "inflight-gate admission, or a controller loop whose "
             "worst-case timeout/retry envelope exceeds its deadline "
             "budget (KA_DAEMON_REQUEST_TIMEOUT / KA_CONTROLLER_INTERVAL)",
    "KA021": "shared attribute written by >=2 threads with an empty "
             "common lock-set (data race)",
    "KA022": "shared attribute guarded by a lock on some reaching paths "
             "and unguarded on others (forgotten lock)",
    "KA023": "lock-order cycle across the discovered lock set "
             "(potential deadlock)",
    "KA024": "unordered iteration (set / queue-drain order) reaches a "
             "byte-pinned serialization sink unsanitized",
    "KA025": "wall-clock/random/uuid/id()/hash() value flows into pinned "
             "output bytes outside a declared timestamp field",
    "KA026": "filesystem-enumeration order (os.listdir/glob/iterdir) "
             "reaches a byte-pinned sink unsanitized",
    "KA027": "thread-racy collection iterated at a byte-pinned sink "
             "without a snapshot under the writers' lock",
    "KA028": "deadline cross-pricing: the controller act path's "
             "worst-case execution envelope exceeds the rolling "
             "move-window budget (KA_CONTROLLER_WINDOW)",
    "KA029": "device dispatch (*_jit / store-backed program entry) "
             "reachable from a daemon handler outside the dispatcher "
             "seam",
    "KA030": "fleet-ledger file referenced outside daemon/fleet.py "
             "(the fleet admission bulkhead)",
}

#: One-line meaning + example offending chain per rule — the source of the
#: generated README rule table (``python -m
#: kafka_assigner_tpu.analysis.ruledoc --write``).
RULE_DOCS: Dict[str, Tuple[str, str]] = {
    "KA000": (
        "meta: unparsable file, or a suppression comment without a reason "
        "(the reason IS the audit trail)",
        "`# kalint: disable=KA005` with no `-- why`",
    ),
    "KA001": (
        "no raw `os.environ`/`os.getenv` access to a `KA_*` knob outside "
        "the registry module (`utils/env.py`) — raw reads bypass the "
        "loud-ignore house rule",
        "`os.environ.get(\"KA_WAVE_MODE\")` in `solvers/tpu.py`",
    ),
    "KA002": (
        "no host-sync or nondeterminism call (`jax.device_get`, `.item()`, "
        "`np.asarray`, `time.*` clocks, `random.*`) anywhere in the traced "
        "set — any function reachable, across modules, from a "
        "`jax.jit`/`pjit`/`shard_map` entry — nor anywhere in the kernel "
        "modules (`ops/`)",
        "`solve_batched_jit` (ops/assignment.py) → `helper()` "
        "(models/problem.py) → `time.time()`",
    ),
    "KA003": (
        "every `KA_*` string literal resolves to a registered knob (a "
        "typo'd knob name is a lint error, not a silently-unset knob)",
        "`env_int(\"KA_PLACE_CHUNKK\")`",
    ),
    "KA004": (
        "every registered knob appears in the README knob table "
        "(generated — `knobdoc --write`)",
        "`KA_NEW_KNOB` registered but table stale",
    ),
    "KA005": (
        "no plan/golden JSON emission (`json.dumps`/`json.dump`) outside "
        "`io/json_io.py`'s byte-compat helpers",
        "`json.dumps(plan)` in `generator.py`",
    ),
    "KA006": (
        "no `jnp.`/`jax.numpy` calls at module import time (module scope, "
        "class bodies, decorators, default arguments) — imports stay cheap "
        "and backend-agnostic",
        "`ZEROS = jnp.zeros((8,))` at module scope",
    ),
    "KA007": (
        "no function in the traced set may close over a mutable "
        "module-level global (list/dict/set reads, or any `global` "
        "rebinding) — trace-time capture freezes the value into every "
        "cached executable; pass it as an argument or bind it immutably",
        "`kernel_jit` → `resolve()` → reads module dict `MODES`",
    ),
    "KA008": (
        "no `except` clause may swallow its exception silently (a body "
        "that is nothing but `pass` or a bare `continue`) — log it, count "
        "it, re-raise, or suppress with a written reason",
        "`except OSError: pass`",
    ),
    "KA009": (
        "no jitted `ops/` entry point (a `*_jit` name from "
        "`ops.assignment`) dispatched outside the registered "
        "bucket-boundary modules (`solvers/tpu.py`, `solvers/warmup.py`, "
        "`parallel/whatif.py`) whose shapes the program store "
        "contract-checks at runtime",
        "`solve_batched_jit(...)` called from `generator.py`",
    ),
    "KA010": (
        "no ZooKeeper WRITE opcode (`OP_CREATE`/`OP_SET_DATA`/`OP_DELETE`) "
        "referenced outside the wire client's serial write methods "
        "(`io/zkwire.py` `create`/`set_data`/`delete`) — writes are never "
        "pipelined and never blindly replayed",
        "`zkwire.OP_CREATE` referenced in `io/zk.py`",
    ),
    "KA011": (
        "no `while True` loop with a blocking socket/poll call whose "
        "enclosing function consults no deadline — no TIMEOUT/INTERVAL/"
        "RETRIES/DEADLINE knob, no `.settimeout(...)`, and (one hop "
        "through the call graph) no helper that does",
        "`while True: sock.recv(4)` with no deadline in scope",
    ),
    "KA012": (
        "no daemon request-handling code (modules under `daemon/` except "
        "`supervisor.py`/`state.py`) may read a supervisor's `.backend`/"
        "`.state` — directly OR through any helper chain that does it on "
        "its behalf (cross-bulkhead access)",
        "`service.do_plan()` → `helper(sup)` → `sup.backend`",
    ),
    "KA013": (
        "every metric/span name passed as a LITERAL to the obs write API "
        "must be declared in the name registry (`obs/names.py`) — a typo'd "
        "name vanishes silently; dynamic names are the registered "
        "composition points",
        "`counter_add(\"daemon.requestz\")`",
    ),
    "KA014": (
        "every registered metric states its unit (`_ms`/`_bytes`/`_frac`/"
        "`_total`/`_seconds` suffix on its last dotted segment) or sits in "
        "the `UNITLESS_METRICS` allowlist; stale and double-declared "
        "allowlist entries are findings too",
        "`foo.latency` registered with no unit and no allowlist entry",
    ),
    "KA015": (
        "no blocking call — socket read/accept/poll/select, `sleep`, "
        "`subprocess`, or a ZooKeeper write — reachable while the shared "
        "solve lock is held: the lock serializes every solve-bearing "
        "request across all clusters, so one blocked holder stalls the "
        "whole daemon",
        "`_handle_admitted` [with solve-lock] → `fault_point()` → "
        "`time.sleep()`",
    ),
    "KA016": (
        "no `KA_*` knob accessor (`env_int`/`env_float`/`env_bool`/"
        "`env_choice`/`env_str`) called inside the traced set — trace-time "
        "freeze means the cached executable silently ignores later env "
        "changes (KA007's twin for knobs); hoist the read outside the "
        "trace or suppress citing the program-store re-key",
        "`solve_batched_jit` → `dense_mask_budget()` → "
        "`env_int(\"KA_DENSE_MASK_BUDGET\")`",
    ),
    "KA017": (
        "no `obs/` write API call (`counter_add`/`gauge_set`/"
        "`hist_observe`/`hist_ms`/`span`/`record_span`) inside the traced "
        "set — metrics emission from traced code is a host-sync hazard "
        "KA013 cannot see (it fires at trace time only, then never again)",
        "`kernel_jit` → `helper()` → `counter_add(\"solve.steps\")`",
    ),
    "KA018": (
        "every knob registered in `utils/env.py` must be READ somewhere "
        "in the project — a typed-accessor call (`env_int`/.../`env_str`, "
        "`knob_default`) with that literal name outside the registry "
        "module; a registered-but-never-read knob is dead configuration "
        "surface operators will set to no effect (the dual of KA003's "
        "read-without-registration)",
        "`KA_OLD_TUNABLE` registered, no accessor reads it anywhere",
    ),
    "KA019": (
        "no blocking call — socket read/accept/poll/select, `sleep`, "
        "`subprocess`, or a ZooKeeper write — reachable while a "
        "supervisor's `_gate()` admission is held (KA015's twin for the "
        "per-cluster inflight gate): an admitted request occupies one of "
        "the cluster's bounded backpressure slots until `_release()`, so "
        "a blocked holder starves the gate and sheds healthy clients",
        "`handle` [after `_gate()`] → `helper()` → `time.sleep()`",
    ),
    "KA020": (
        "blocking-call budget (KA015/KA019's quantitative twin): along "
        "any chain reachable under the shared solve lock, an "
        "inflight-gate admission, or a controller-loop thread entry, the "
        "summed worst-case wall clock of the `KA_*` deadline knobs the "
        "chain consults — each function's TIMEOUT knob defaults times "
        "(1 + its RETRIES knob default), `*_MS` names read as "
        "milliseconds — must not exceed the region's deadline budget: "
        "`KA_DAEMON_REQUEST_TIMEOUT` for held regions (a chain that can "
        "legally block longer than the watchdog's patience turns every "
        "overrun into a flagged-but-unfixable alert), "
        "`KA_CONTROLLER_INTERVAL` for controller loops (a tick that can "
        "legally outlast the cadence starves every later tick)",
        "`handle` [after `_gate()`] → `poll_loop()` consulting "
        "`KA_EXEC_POLL_TIMEOUT` (600 s > 30 s budget)",
    ),
    "KA021": (
        "no mutable shared attribute (a `self.attr` on a `daemon/`/"
        "`exec/` class, per the one-level instance typing) may be "
        "WRITTEN by two or more thread entries — discovered "
        "`Thread`/`Timer`/executor targets, the HTTP handler surface "
        "(concurrent with itself), the daemon main thread — with an "
        "empty common lock-set across the writes (`__init__` bodies are "
        "happens-before and excluded); guard every write with one lock "
        "or suppress citing the serializing protocol",
        "`watch thread → _watch_loop → self._generation += 1` vs "
        "`HTTP handle → self._generation = 0`, no common lock",
    ),
    "KA022": (
        "no shared attribute whose WRITES all agree on a guarding lock "
        "may be touched on some reaching path with that lock NOT held "
        "(lexically or by must-hold inference along every reaching call "
        "chain) — the classic forgotten-lock bug; take the lock on the "
        "unguarded path or suppress citing why that path cannot race",
        "`self._counters` guarded by `_counters_lock` in 6 writers, "
        "read bare in `healthz_view`",
    ),
    "KA023": (
        "no cycle in the lock-order graph — an edge A→B wherever lock B "
        "is acquired while A is held, lexically or anywhere in the "
        "call closure of an A-held region; locks are identified by name "
        "(may-alias), self-edges are re-entry, not inversion — a cycle "
        "means two threads can each hold one lock and wait on the other "
        "(deadlock); impose a global acquisition order or suppress "
        "citing the protocol that keeps the cycle unreachable",
        "`_plan_mu` → `_cv` in `submit()` but `_cv` → `_plan_mu` in "
        "`_loop()`",
    ),
    "KA024": (
        "no unordered iteration — a set (literal, comprehension, "
        "`set()`/`frozenset()` call, set algebra), a queue drain, or "
        "`as_completed` completion order — may reach a byte-pinned sink "
        "(`json.dumps`, stdout emission, promtext rendering) without a "
        "sanitizer: `sorted(...)` on THAT expression, `.sort()` on the "
        "materialized sequence, or a canonical-order helper; sorting a "
        "different axis (or re-shuffling after the sort) discharges "
        "nothing, and `list(S)` merely freezes the arbitrary order",
        "`for t in {p.topic for p in parts}:` → `emit()` → "
        "`json.dumps(...)`",
    ),
    "KA025": (
        "no wall-clock (`time.time`, `datetime.now`), `random.*` draw, "
        "`uuid.uuid1/uuid4`, `id()` or `hash()` value may flow toward "
        "pinned output bytes except into a DECLARED timestamp/identity "
        "field (`ts`/`t`/`request_id`/`*_uptime_*`/… — the allowlist in "
        "`determinism.py`); monotonic clocks are exempt (they price "
        "deadlines, never serialize)",
        "`\"build\": time.time()` in an envelope builder → "
        "`json.dumps(env)`",
    ),
    "KA026": (
        "no filesystem-enumeration order (`os.listdir`/`os.scandir`/"
        "`glob.*`/`Path.iterdir`/`Path.rglob`) may reach a byte-pinned "
        "sink unsanitized — the OS returns directory entries in "
        "arbitrary order, so wrap the enumeration in `sorted(...)` or "
        "suppress citing the chain",
        "`for f in os.listdir(d):` → `report()` → `json.dumps(...)`",
    ),
    "KA027": (
        "no collection attribute written from another thread entry may "
        "be iterated (or `.keys()`/`.values()`/`.items()`-drained) in a "
        "sink-reaching function without a lock common to the reader and "
        "every foreign writer — iteration is not atomic, the drain can "
        "tear or raise mid-mutation and the surface bytes become a race "
        "result; `sorted()` does NOT discharge this (the sanitizer is a "
        "snapshot under the writers' lock); attributes KA021/KA022 "
        "already convict are skipped",
        "HTTP `handle` → `render()` iterating `self._flights` while the "
        "worker thread appends, no common lock",
    ),
    "KA028": (
        "deadline cross-pricing (KA020's twin for the act path): the "
        "worst-case timeout/retry envelope of every chain reachable "
        "from the controller's `_act` — bridged through "
        "`controller_execute` into the executor, where "
        "`KA_EXEC_POLL_TIMEOUT` lives — must not exceed the rolling "
        "move-window budget (`KA_CONTROLLER_WINDOW`): an action that "
        "can legally outlast the window corrupts the move-ledger "
        "accounting every cooldown and blast-radius decision reads",
        "`_act` → `controller_execute` → `_await_convergence` "
        "consulting `KA_EXEC_POLL_TIMEOUT` (6000 s > 3600 s window)",
    ),
    "KA029": (
        "every device entry point reachable from daemon request/"
        "controller handlers must ride the dispatcher seam "
        "(`daemon/dispatch.py` plus the bucket-boundary modules "
        "`solvers/tpu.py`, `solvers/warmup.py`, `parallel/whatif.py`): "
        "a `*_jit` program call or a store-backed `_program`/"
        "`_sweep_program` entry reached from daemon code outside that "
        "seam bypasses the gather queue — the solve monopolizes the "
        "device behind the coalescing plane's back, invisible to the "
        "dispatch metrics and the solo-fallback accounting",
        "`daemon/service.py handle_plan` → `helper()` calling "
        "`place_scan_narrow_jit(...)` directly",
    ),
    "KA030": (
        "the fleet admission ledger (`ka-fleet.json`) is read and "
        "written ONLY by `daemon/fleet.py` — the KA012 bulkhead posture "
        "one layer up: any other package module naming the ledger file "
        "(a string literal containing `ka-fleet`) can reach it behind "
        "the FleetScheduler's back, bypassing the mutex + atomic "
        "tmp+rename discipline that keeps daemon-wide lease and budget "
        "accounting untearable",
        "`open(os.path.join(jdir, \"ka-fleet.json\"))` in "
        "`daemon/service.py`",
    ),
}

#: Modules whose ENTIRE body is treated as traced kernel code (KA002): these
#: compile under jit wholesale, and even their module-level helpers feed
#: trace-time constants, so host clocks/randomness have no business anywhere
#: in them.
KERNEL_MODULES = frozenset({"ops/assignment.py", "ops/pallas_leadership.py"})
#: The one module allowed to touch os.environ for KA_* knobs (KA001).
REGISTRY_MODULE = "utils/env.py"
#: The one module allowed to emit plan JSON (KA005).
JSON_BOUNDARY_MODULE = "io/json_io.py"
#: Modules allowed to dispatch the jitted ops/ entry points (KA009).
BUCKET_BOUNDARY_MODULES = frozenset({
    "solvers/tpu.py", "solvers/warmup.py", "parallel/whatif.py",
})
#: The wire-client module and the only functions in it allowed to reference
#: the ZooKeeper WRITE opcodes (KA010).
WIRE_MODULE = "io/zkwire.py"
WRITE_OPCODES = frozenset({"OP_CREATE", "OP_SET_DATA", "OP_DELETE"})
SERIAL_WRITE_FUNCS = frozenset({"create", "set_data", "delete"})
#: KA012: the daemon package's bulkhead boundary.
DAEMON_PKG_PREFIX = "daemon/"
DAEMON_BULKHEAD_MODULES = frozenset({
    "daemon/supervisor.py", "daemon/state.py",
})
BULKHEAD_ATTRS = frozenset({"backend", "state"})
#: The supervisor class whose internals the bulkhead protects: attribute
#: reads on values of this type are cross-bulkhead wherever they happen.
SUPERVISOR_CLASS = ("daemon/supervisor.py", "ClusterSupervisor")

#: KA030: the fleet-ledger bulkhead. Any string literal containing this
#: token names the fleet admission ledger file — only the fleet module
#: may spell it (plus this rules module, which must spell the token to
#: define and explain the rule).
FLEET_LEDGER_TOKEN = "ka-fleet"
FLEET_BULKHEAD_MODULE = "daemon/fleet.py"
FLEET_TOKEN_EXEMPT_MODULES = frozenset({
    FLEET_BULKHEAD_MODULE, "analysis/kalint/rules.py",
})

#: KA029: the dispatch-plane seam — the ONLY modules through which device
#: dispatch (a ``*_jit`` program call, or a store-backed ``_program``/
#: ``_sweep_program`` entry) may be reached from daemon request/controller
#: handlers. ``daemon/dispatch.py`` is the gather queue itself; the
#: bucket-boundary modules own the padding + program-store discipline and
#: route their rows through the installed broker.
DISPATCH_SEAM_MODULES = (
    frozenset({"daemon/dispatch.py"}) | BUCKET_BOUNDARY_MODULES
)
#: ``*_jit``-suffixed names that BUILD programs rather than dispatch them.
DISPATCH_BUILDER_NAMES = frozenset({"wrap_jit"})
#: Store-backed program entry getters (solvers/tpu.py / parallel/whatif.py
#: module conventions): acquiring one outside the seam is the finding.
DISPATCH_STORE_ENTRY_NAMES = frozenset({"_program", "_sweep_program"})

#: KA016: the typed accessors whose call inside traced code freezes a knob.
ENV_ACCESSOR_NAMES = frozenset({
    "env_int", "env_float", "env_bool", "env_choice", "env_str",
})
#: KA017: the obs WRITE api (counter_value is a read and exempt).
OBS_WRITE_NAMES = frozenset({
    "counter_add", "gauge_set", "hist_observe", "hist_ms", "span",
    "record_span",
})
#: KA015: functions in the wire module whose reachability under the solve
#: lock IS a finding (a ZK write on the request path).
ZK_WRITE_FUNC_NAMES = frozenset({
    "create", "set_data", "delete", "_write_call",
})

_KNOB_RE = re.compile(r"KA_[A-Z][A-Z0-9_]*")
_TIME_CALLS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns", "sleep",
})
_NUMPY_ALIASES = frozenset({"np", "numpy", "onp"})


def _is_name(node: ast.AST, name: str) -> bool:
    return isinstance(node, ast.Name) and node.id == name


def _const_str(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _knob_literal(node: ast.AST):
    v = _const_str(node)
    return v if v is not None and _KNOB_RE.fullmatch(v) else None


def _call_terminal_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# --- KA002 machinery --------------------------------------------------------

def _banned_call(node: ast.Call):
    """Message when ``node`` is one of the banned host-sync/nondeterminism
    calls, else None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "device_get" and _is_name(f.value, "jax"):
        return "jax.device_get(...) host sync"
    if f.attr == "item" and not node.args and not node.keywords:
        return ".item() host sync"
    if f.attr == "asarray" and isinstance(f.value, ast.Name) \
            and f.value.id in _NUMPY_ALIASES:
        return f"{f.value.id}.asarray(...) host materialization"
    if _is_name(f.value, "time") and f.attr in _TIME_CALLS:
        return f"time.{f.attr}() wall clock / host nondeterminism"
    if _is_name(f.value, "random"):
        return f"random.{f.attr}() nondeterminism"
    if (
        isinstance(f.value, ast.Attribute)
        and f.value.attr == "random"
        and isinstance(f.value.value, ast.Name)
        and f.value.value.id in _NUMPY_ALIASES
    ):
        return f"{f.value.value.id}.random.{f.attr}() nondeterminism"
    return None


def _jit_roots(tree: ast.AST) -> Set[str]:
    """Function names handed to a tracing wrapper in this module — as call
    arguments (``f_jit = jax.jit(f, ...)``) or decorators (``@jax.jit``,
    ``@jax.jit(...)``, ``@partial(jax.jit, ...)``)."""
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_jit_expr(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                roots.add(node.args[0].id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_expr(dec):
                    roots.add(node.name)
                elif isinstance(dec, ast.Call):
                    if is_jit_expr(dec.func):
                        roots.add(node.name)
                    elif (
                        (_is_name(dec.func, "partial")
                         or (isinstance(dec.func, ast.Attribute)
                             and dec.func.attr == "partial"))
                        and dec.args and is_jit_expr(dec.args[0])
                    ):
                        roots.add(node.name)
    return roots


def _traced_functions(tree: ast.AST):
    """Transitive closure of jit roots over same-module calls-by-name: the
    single-file approximation used when no project graph is available (the
    project-wide traced set supersedes this in package mode)."""
    funcs = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    traced = {name for name in _jit_roots(tree) if name in funcs}
    frontier = list(traced)
    while frontier:
        fn = funcs[frontier.pop()]
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                if callee in funcs and callee not in traced:
                    traced.add(callee)
                    frontier.append(callee)
    return [funcs[name] for name in sorted(traced)]


# --- rule passes (per-module) -----------------------------------------------

def _os_bindings(tree: ast.AST):
    os_mods = {"os"}
    environs: Set[str] = set()
    getenvs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "os":
                    os_mods.add(alias.asname or "os")
        elif isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "environ":
                    environs.add(bound)
                elif alias.name == "getenv":
                    getenvs.add(bound)
    return os_mods, environs, getenvs


def check_ka001(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath == REGISTRY_MODULE:
        return []
    os_mods, environs, getenvs = _os_bindings(tree)

    def is_environ(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id in environs:
            return True
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id in os_mods
        )

    def is_getenv(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id in getenvs:
            return True
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "getenv"
            and isinstance(node.value, ast.Name)
            and node.value.id in os_mods
        )

    out: List[Finding] = []

    def hit(node, key):
        out.append(Finding(
            "KA001", path, node.lineno, node.col_offset + 1,
            f"raw os.environ access to {key!r}; use the typed accessors in "
            "utils/env.py (env_int/env_float/env_bool/env_choice/env_str)",
        ))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "pop", "setdefault")
                and is_environ(f.value)
                and node.args
            ):
                key = _knob_literal(node.args[0])
                if key:
                    hit(node, key)
            elif is_getenv(f) and node.args:
                key = _knob_literal(node.args[0])
                if key:
                    hit(node, key)
        elif isinstance(node, ast.Subscript) and is_environ(node.value):
            key = _knob_literal(node.slice)
            if key:
                hit(node, key)
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1:
            if (
                isinstance(node.ops[0], (ast.In, ast.NotIn))
                and is_environ(node.comparators[0])
            ):
                key = _knob_literal(node.left)
                if key:
                    hit(node, key)
    return out


def check_ka002(tree: ast.AST, relpath: str, path: str,
                interprocedural: bool = False) -> List[Finding]:
    """Kernel modules are checked wholesale always; the same-module traced
    closure runs only when NO project graph exists (package mode replaces
    it with the real cross-module traced set in :func:`project_findings`)."""
    scopes: List = []
    where = "jit-traced function"
    if relpath in KERNEL_MODULES:
        scopes = [tree]
        where = "kernel module"
    elif not interprocedural:
        scopes = _traced_functions(tree)
    out: List[Finding] = []
    seen: Set[int] = set()
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and id(node) not in seen:
                seen.add(id(node))
                msg = _banned_call(node)
                if msg:
                    out.append(Finding(
                        "KA002", path, node.lineno, node.col_offset + 1,
                        f"{msg} in {where} (host work must stay outside the "
                        "traced solve)",
                    ))
    return out


def check_ka003(tree: ast.AST, knobs: Set[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        v = _knob_literal(node)
        if v is not None and v not in knobs:
            out.append(Finding(
                "KA003", path, node.lineno, node.col_offset + 1,
                f"{v!r} is not a registered knob (typo? declare it in "
                "utils/env.py)",
            ))
    return out


def check_ka005(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath == JSON_BOUNDARY_MODULE:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("dumps", "dump")
            and _is_name(node.func.value, "json")
        ):
            out.append(Finding(
                "KA005", path, node.lineno, node.col_offset + 1,
                f"json.{node.func.attr}(...) outside io/json_io.py; plan "
                "payloads must go through the byte-compat helpers (suppress "
                "with a reason for non-plan payloads)",
            ))
    return out


def _jnp_module_aliases(tree: ast.AST) -> Set[str]:
    aliases = {"jnp"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.numpy" and alias.asname:
                    aliases.add(alias.asname)
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "numpy":
                    aliases.add(alias.asname or "numpy")
    return aliases


def _deferred_nodes(tree: ast.AST) -> Set[int]:
    deferred: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    deferred.add(id(sub))
        elif isinstance(node, ast.Lambda):
            for sub in ast.walk(node.body):
                deferred.add(id(sub))
    return deferred


def check_ka006(tree: ast.AST, path: str) -> List[Finding]:
    aliases = _jnp_module_aliases(tree)
    deferred = _deferred_nodes(tree)
    out: List[Finding] = []
    for node in ast.walk(tree):
        if id(node) in deferred or not isinstance(node, ast.Call):
            continue
        f = node.func
        parts: List[str] = []
        while isinstance(f, ast.Attribute):
            parts.append(f.attr)
            f = f.value
        if not isinstance(f, ast.Name) or not parts:
            continue
        root = f.id
        if root in aliases or (root == "jax" and parts[-1] == "numpy"):
            dotted = ".".join([root] + list(reversed(parts)))
            out.append(Finding(
                "KA006", path, node.lineno, node.col_offset + 1,
                f"{dotted}(...) at module import time (imports must stay "
                "cheap and backend-agnostic; build arrays lazily inside "
                "functions)",
            ))
    return out


#: Constructors whose module-scope result is a mutable container (KA007).
_MUTABLE_CTORS = frozenset({
    "list", "dict", "set", "bytearray", "defaultdict", "deque", "Counter",
    "OrderedDict",
})


def _module_mutable_globals(tree: ast.AST) -> Set[str]:
    def value_is_mutable(node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None
            )
            return name in _MUTABLE_CTORS
        return False

    out: Set[str] = set()

    def scan(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and value_is_mutable(stmt.value):
                for target in stmt.targets:
                    for n in ast.walk(target):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and value_is_mutable(stmt.value) \
                    and isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
            for attr in ("body", "orelse", "finalbody"):
                scan(getattr(stmt, attr, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                scan(handler.body)

    scan(tree.body)  # type: ignore[attr-defined]
    return out


def _local_bindings(fn: ast.AST) -> Set[str]:
    bound: Set[str] = set()
    args = fn.args
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        bound.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            if node is not fn:
                bound.add(node.name)
        elif isinstance(node, ast.alias):
            bound.add(node.asname or node.name.split(".")[0])
    return bound


def _ka007_fn_findings(fn, fn_label: str, mutable: Set[str], path: str,
                       chain: Tuple[str, ...] = ()) -> List[Finding]:
    """KA007 findings for ONE function body against its module's mutable
    global set — shared by the single-file closure and the project-wide
    traced pass (which adds the reaching chain)."""
    out: List[Finding] = []
    globals_declared: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            globals_declared.update(node.names)
            out.append(Finding(
                "KA007", path, node.lineno, node.col_offset + 1,
                f"jit-traced function {fn_label!r} rebinds module "
                f"global(s) {', '.join(node.names)} via 'global' (the "
                "rebinding runs at trace time only; cached executables "
                "never see it — return the value instead)",
                chain=chain,
            ))
    if not mutable:
        return out
    local = _local_bindings(fn) - globals_declared
    seen_names: Set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in mutable
            and node.id not in local
            and node.id not in seen_names  # one finding per name per fn
        ):
            seen_names.add(node.id)
            out.append(Finding(
                "KA007", path, node.lineno, node.col_offset + 1,
                f"jit-traced function {fn_label!r} closes over mutable "
                f"module global {node.id!r} (its value is frozen into "
                "the compiled executable at trace time; later mutations "
                "are silently ignored — pass it as an argument or bind "
                "it immutably, e.g. tuple/frozenset/MappingProxyType)",
                chain=chain,
            ))
    return out


def check_ka007(tree: ast.AST, path: str,
                interprocedural: bool = False) -> List[Finding]:
    if interprocedural:
        return []  # the project-wide traced pass owns KA007 in package mode
    mutable = _module_mutable_globals(tree)
    out: List[Finding] = []
    for fn in _traced_functions(tree):
        out.extend(_ka007_fn_findings(fn, fn.name, mutable, path))
    return out


def check_ka008(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        body = node.body
        if len(body) == 1 and isinstance(body[0], (ast.Pass, ast.Continue)):
            what = "pass" if isinstance(body[0], ast.Pass) else "continue"
            out.append(Finding(
                "KA008", path, body[0].lineno, body[0].col_offset + 1,
                f"except clause swallows the exception silently (bare "
                f"{what}): log it, count it, re-raise, or suppress with a "
                "reason",
            ))
    return out


def _ops_jit_bindings(tree: ast.AST):
    entries: Set[str] = set()
    modules: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.endswith("ops.assignment"):
                for alias in node.names:
                    if alias.name.endswith("_jit"):
                        entries.add(alias.asname or alias.name)
            elif node.module.endswith("ops") or node.module == "ops":
                for alias in node.names:
                    if alias.name == "assignment":
                        modules.add(alias.asname or "assignment")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("ops.assignment") and alias.asname:
                    modules.add(alias.asname)
    return entries, modules


def check_ka009(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if relpath in BUCKET_BOUNDARY_MODULES or relpath in KERNEL_MODULES:
        return []
    entries, modules = _ops_jit_bindings(tree)
    if not entries and not modules:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        target = None
        if isinstance(f, ast.Name) and f.id in entries:
            target = f.id
        elif (
            isinstance(f, ast.Attribute)
            and f.attr.endswith("_jit")
            and isinstance(f.value, ast.Name)
            and f.value.id in modules
        ):
            target = f.attr
        if target:
            out.append(Finding(
                "KA009", path, node.lineno, node.col_offset + 1,
                f"ops kernel entry {target}(...) dispatched outside a "
                "bucket-boundary module (arrays crossing into ops/ must be "
                "padded to registered bucket sizes — models/problem.py "
                "_pad8/batch_bucket — and dispatched from "
                f"{sorted(BUCKET_BOUNDARY_MODULES)}, whose shapes the "
                "program store contract-checks at runtime)",
            ))
    return out


def check_ka010(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    out: List[Finding] = []

    def visit(node: ast.AST, func: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            child_func = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_func = child.name
            name = None
            if isinstance(child, ast.Name) \
                    and isinstance(child.ctx, ast.Load) \
                    and child.id in WRITE_OPCODES:
                name = child.id
            elif isinstance(child, ast.Attribute) \
                    and child.attr in WRITE_OPCODES:
                name = child.attr
            if name is not None and not (
                relpath == WIRE_MODULE and child_func in SERIAL_WRITE_FUNCS
            ):
                out.append(Finding(
                    "KA010", path, child.lineno, child.col_offset + 1,
                    f"ZooKeeper write opcode {name} referenced outside the "
                    "serial write path (io/zkwire.py "
                    f"{sorted(SERIAL_WRITE_FUNCS)}): writes are never "
                    "pipelined and never blindly replayed — route mutations "
                    "through the wire client's write methods",
                ))
            visit(child, child_func)

    visit(tree, None)
    return out


#: Call names that block on external progress (KA011/KA015 loop bodies).
_BLOCKING_NAMES = frozenset({"accept", "poll", "select", "sleep"})
#: Substrings of knob names that count as a deadline consult (KA011).
_DEADLINE_TOKENS = ("TIMEOUT", "INTERVAL", "RETRIES", "DEADLINE")


def _is_blocking_call(node: ast.Call) -> bool:
    f = node.func
    name = None
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name is None:
        return False
    return "recv" in name or name in _BLOCKING_NAMES


def _scope_consults_deadline(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        v = _knob_literal(node)
        if v is not None and any(tok in v for tok in _DEADLINE_TOKENS):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "settimeout"
        ):
            return True
    return False


def check_ka011(tree: ast.AST, path: str) -> List[Finding]:
    """A ``while True`` blocking loop must see a deadline consult in its
    enclosing function — directly, or (ISSUE 12) one hop away in a helper
    the function calls: a same-class method (``self._deadline_remaining()``)
    or a same-module function. One hop is deliberate: the bound must stay
    NEAR the loop to be auditable; deeper indirection carries a reasoned
    suppression naming where the bound lives."""
    out: List[Finding] = []
    consult_cache: dict = {}
    module_funcs = {
        n.name: n for n in tree.body  # type: ignore[attr-defined]
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    # function node id -> {method name: node} of its enclosing class
    class_methods: Dict[int, Dict[str, ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods = {
                m.name: m for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for m in methods.values():
                class_methods[id(m)] = methods

    def consults_direct(scope: ast.AST) -> bool:
        # kalint: disable=KA025 -- memo key through a local: id() names the AST node in consult_cache, it never reaches the findings payload (chain check_ka011 -> lint_source -> cli.main)
        key = id(scope)
        if key not in consult_cache:
            consult_cache[key] = _scope_consults_deadline(scope)
        return consult_cache[key]

    def consults(scope: ast.AST) -> bool:
        if consults_direct(scope):
            return True
        if scope is tree:
            return False
        siblings = class_methods.get(id(scope), {})
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            helper = None
            if isinstance(f, ast.Attribute) and _is_name(f.value, "self"):
                helper = siblings.get(f.attr)
            elif isinstance(f, ast.Name):
                helper = module_funcs.get(f.id)
            if helper is not None and helper is not scope \
                    and consults_direct(helper):
                return True
        return False

    def visit(node: ast.AST, scope: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child
            if (
                isinstance(child, ast.While)
                and isinstance(child.test, ast.Constant)
                and child.test.value in (True, 1)
                and any(
                    isinstance(n, ast.Call) and _is_blocking_call(n)
                    for n in ast.walk(child)
                )
                and not consults(child_scope)
            ):
                out.append(Finding(
                    "KA011", path, child.lineno, child.col_offset + 1,
                    "blocking recv/poll loop with no deadline: the "
                    "enclosing function consults no registered KA_* "
                    "timeout/interval/retries knob, sets no socket "
                    "timeout, and calls no helper that does — bound the "
                    "wait, or suppress with a reason naming where the "
                    "bound lives",
                ))
            visit(child, child_scope)

    visit(tree, tree)
    return out


def check_ka012(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    if not relpath.startswith(DAEMON_PKG_PREFIX) \
            or relpath in DAEMON_BULKHEAD_MODULES:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in BULKHEAD_ATTRS
        ):
            out.append(Finding(
                "KA012", path, node.lineno, node.col_offset + 1,
                f".{node.attr} read outside the bulkhead boundary "
                "(cross-bulkhead access): a supervisor's session/cache "
                "belongs to daemon/supervisor.py — route through the "
                "owning ClusterSupervisor's methods (handle, lifecycle, "
                "state_view, healthz_view, counters, ...)",
            ))
    return out


def check_ka030(tree: ast.AST, relpath: str, path: str) -> List[Finding]:
    """The fleet-ledger bulkhead (the KA012 posture one layer up): a
    string literal containing the ledger filename token anywhere but
    ``daemon/fleet.py`` is a module positioned to read or write
    ``ka-fleet.json`` behind the FleetScheduler's back — tearing the
    daemon-wide lease/budget accounting its mutex + atomic-write
    discipline exists to protect. Docstrings are exempt (prose that
    EXPLAINS the ledger is not code that touches it)."""
    if relpath in FLEET_TOKEN_EXEMPT_MODULES:
        return []
    doc_nodes = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                doc_nodes.add(id(body[0].value))
    out: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and FLEET_LEDGER_TOKEN in node.value
            and id(node) not in doc_nodes
        ):
            out.append(Finding(
                "KA030", path, node.lineno, node.col_offset + 1,
                f"string literal {node.value!r} names the fleet "
                "admission ledger outside the fleet bulkhead "
                f"({FLEET_BULKHEAD_MODULE}): reading or writing "
                "ka-fleet.json behind the FleetScheduler's back tears "
                "the daemon-wide lease/budget accounting — route "
                "through FleetScheduler methods (acquire, release, "
                "charge, view, recover)",
            ))
    return out


#: The obs write API whose literal first argument is a METRIC name (KA013).
METRIC_NAME_CALLS = frozenset({
    "counter_add", "gauge_set", "hist_observe", "hist_ms", "counter_value",
})
#: Calls whose literal first argument is a SPAN name.
SPAN_NAME_CALLS = frozenset({"span", "record_span"})
#: The daemon supervisor's name-composing wrappers.
EITHER_NAME_CALLS = frozenset({"_count", "_metric"})


def check_ka013(
    tree: ast.AST, path: str, metric_names, span_names
) -> List[Finding]:
    every = metric_names | span_names
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _call_terminal_name(node)
        if fname is None:
            continue
        table = table_desc = None
        if fname in METRIC_NAME_CALLS:
            table, table_desc = metric_names, "METRIC_NAMES"
        elif fname in SPAN_NAME_CALLS:
            table, table_desc = span_names, "SPAN_NAMES"
        elif fname in EITHER_NAME_CALLS:
            table, table_desc = every, "METRIC_NAMES/SPAN_NAMES"
        if table is not None:
            name_node = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None,
            )
            lit = _const_str(name_node) if name_node is not None else None
            if lit is not None and lit not in table:
                out.append(Finding(
                    "KA013", path, node.lineno, node.col_offset + 1,
                    f"{fname}({lit!r}) uses an undeclared name: a typo'd "
                    "metric/span vanishes silently — declare it in "
                    f"obs/names.py ({table_desc}) or fix the spelling",
                ))
        if fname in SPAN_NAME_CALLS:
            for kw in node.keywords:
                if kw.arg == "hist":
                    lit = _const_str(kw.value)
                    if lit is not None and lit not in metric_names:
                        out.append(Finding(
                            "KA013", path, kw.value.lineno,
                            kw.value.col_offset + 1,
                            f"span(hist={lit!r}) uses an undeclared "
                            "histogram name — declare it in obs/names.py "
                            "(METRIC_NAMES) or fix the spelling",
                        ))
    return out


#: Unit tokens KA014 recognizes on a metric name's LAST dotted segment.
METRIC_UNIT_TOKENS = ("ms", "bytes", "frac", "total", "seconds")


def _has_unit_suffix(name: str) -> bool:
    seg = name.rsplit(".", 1)[-1]
    return seg in METRIC_UNIT_TOKENS or any(
        seg.endswith("_" + tok) for tok in METRIC_UNIT_TOKENS
    )


def check_metric_units(
    metric_names=None, unitless=None,
    path: str = "kafka_assigner_tpu/obs/names.py",
) -> List[Finding]:
    """KA014 (registry-level, one pass per lint run)."""
    if metric_names is None or unitless is None:
        from ...obs.names import METRIC_NAMES, UNITLESS_METRICS

        if metric_names is None:
            metric_names = METRIC_NAMES
        if unitless is None:
            unitless = UNITLESS_METRICS
    out: List[Finding] = []
    for name in sorted(metric_names):
        if _has_unit_suffix(name):
            if name in unitless:
                out.append(Finding(
                    "KA014", path, 1, 1,
                    f"metric {name!r} carries a unit suffix AND sits in "
                    "UNITLESS_METRICS — pick one (the allowlist is for "
                    "names with genuinely no unit)",
                ))
            continue
        if name not in unitless:
            out.append(Finding(
                "KA014", path, 1, 1,
                f"metric {name!r} carries no unit suffix "
                f"({'/'.join('_' + t for t in METRIC_UNIT_TOKENS)} on its "
                "last segment) and is not declared in UNITLESS_METRICS — "
                "dashboards must never guess units: rename it or declare "
                "it unitless",
            ))
    for name in sorted(unitless):
        if name not in metric_names:
            out.append(Finding(
                "KA014", path, 1, 1,
                f"UNITLESS_METRICS entry {name!r} is not a registered "
                "metric (stale allowlist entry — remove it)",
            ))
    return out


def check_readme(readme_text: str, knobs=None, path: str = "README.md"):
    """KA004: every registered knob must appear in the README."""
    if knobs is None:
        from ...utils.env import KNOBS

        knobs = KNOBS
    names = knobs if not hasattr(knobs, "keys") else list(knobs)
    out: List[Finding] = []
    for name in names:
        pat = r"(?<![A-Z0-9_])" + re.escape(name) + r"(?![A-Z0-9_])"
        if not re.search(pat, readme_text):
            out.append(Finding(
                "KA004", path, 1, 1,
                f"registered knob {name} is missing from the README knob "
                "table (regenerate: python -m "
                "kafka_assigner_tpu.analysis.knobdoc --write)",
            ))
    return out


#: KA018: accessor call names whose literal first argument constitutes a
#: READ of a registered knob (the typed accessors plus the programmatic
#: default lookup the kernels use).
KNOB_READ_NAMES = ENV_ACCESSOR_NAMES | frozenset({"knob_default"})


def check_dead_knobs(
    trees: "Dict[str, ast.AST]",
    knobs=None,
    display: Optional[Dict[str, str]] = None,
    env_relpath: str = REGISTRY_MODULE,
) -> List[Finding]:
    """KA018: every registered ``KA_*`` knob must be READ somewhere in the
    project — an accessor/``knob_default`` call with that literal name in
    any module OUTSIDE the registry itself (registration is not a read).
    The dual of KA003: KA003 kills reads of unregistered names, this
    kills registrations nothing reads — dead configuration surface an
    operator will set to no effect. Findings anchor at the registration
    call in ``utils/env.py``.

    ``trees`` maps module relpaths to parsed ASTs (package mode hands the
    project's modules over; fixtures call this directly); ``knobs``
    overrides the live registry's name set for fixture trees."""
    if knobs is None:
        from ...utils.env import KNOBS

        knobs = list(KNOBS)
    display = display or {}
    reads: Set[str] = set()
    for relpath, tree in trees.items():
        if relpath == env_relpath:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_terminal_name(node)
            if name in KNOB_READ_NAMES and node.args:
                knob = _knob_literal(node.args[0])
                if knob is not None:
                    reads.add(knob)
    # Registration lines: the _knob("NAME", ...) calls in the registry
    # module (line 1 when the registry tree is absent — fixture trees).
    reg_lines: Dict[str, int] = {}
    env_tree = trees.get(env_relpath)
    if env_tree is not None:
        for node in ast.walk(env_tree):
            if (
                isinstance(node, ast.Call)
                and _call_terminal_name(node) == "_knob"
                and node.args
            ):
                knob = _knob_literal(node.args[0])
                if knob is not None:
                    reg_lines[knob] = node.lineno
    path = display.get(env_relpath, env_relpath)
    out: List[Finding] = []
    for name in knobs:
        if name in reads:
            continue
        out.append(Finding(
            "KA018", path, reg_lines.get(name, 1), 1,
            f"registered knob {name} is never read: no typed accessor "
            "(env_int/env_float/env_bool/env_choice/env_str/knob_default) "
            "consumes it anywhere in the project — delete the "
            "registration, or wire the read it was meant to gate",
        ))
    return out


# --- project-wide graph passes ----------------------------------------------

def _blocking_sink_desc(node: ast.Call) -> Optional[str]:
    """KA015 sink classification for one call node."""
    f = node.func
    name = _call_terminal_name(node)
    if name is None:
        return None
    if "recv" in name:
        return f"{name}() socket read"
    if name in ("accept", "poll", "select"):
        return f"{name}() blocking wait"
    if name == "sleep":
        return "sleep() stall"
    if name in ("run", "Popen", "call", "check_call", "check_output") \
            and isinstance(f, ast.Attribute) \
            and _is_name(f.value, "subprocess"):
        return f"subprocess.{name}() child process"
    return None


#: KA020 knob-name classification tokens.
_BUDGET_TIMEOUT_TOKEN = "TIMEOUT"
_BUDGET_RETRIES_TOKEN = "RETRIES"
#: The watchdog-budget knob KA020 compares held-region chain envelopes
#: against.
BUDGET_KNOB = "KA_DAEMON_REQUEST_TIMEOUT"
#: The controller-loop cadence knob KA020 compares controller-thread chain
#: envelopes against: a tick that can legally outlast one interval starves
#: every later tick (and the default envelope fallback, matching the
#: knob's registered default).
CONTROLLER_BUDGET_KNOB = "KA_CONTROLLER_INTERVAL"
CONTROLLER_MODULE = "daemon/controller.py"
#: The rolling move-window knob KA028 prices the controller act path
#: against: `_record_moves` timestamps land in a KA_CONTROLLER_WINDOW
#: ledger, so an action whose worst-case envelope outlasts the window
#: corrupts the accounting every cooldown/blast-radius decision reads.
ACT_BUDGET_KNOB = "KA_CONTROLLER_WINDOW"
#: The controller-module act-path entry function KA028 seeds at.
ACT_ENTRY_NAME = "_act"
#: The supervisor method the act path calls through the UNTYPED
#: ``self.sup`` ctor attribute — the resolver drops that edge (no
#: one-level type for ``sup``), so KA028 bridges it BY NAME: an
#: attribute call ``*.controller_execute(...)`` anywhere in the act
#: closure edges to every project function of that name. This is the
#: seam that kept the KA020 controller sweep vacuously clean of the
#: executor's 600 s poll envelope.
ACT_BRIDGE_NAME = "controller_execute"


def _knob_seconds(name: str, value) -> Optional[float]:
    """A knob default as seconds (``*_MS`` names are milliseconds); None
    when the default is not a priceable number."""
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return None
    v = float(value)
    return v / 1000.0 if name.endswith("_MS") else v


def _fn_budget_envelope(fn_node: ast.AST,
                        defaults) -> Tuple[float, List[str]]:
    """One function's worst-case blocking envelope from the deadline
    knobs IT consults: sum of its TIMEOUT knob defaults (seconds) times
    ``1 + max(RETRIES defaults)`` when it also consults a retries knob —
    the shape every retry loop in the tree has (each retry re-arms the
    timeout). Returns ``(seconds, [knob names that contributed])``."""
    timeouts: List[Tuple[str, float]] = []
    retries: List[Tuple[str, float]] = []
    for call in ast.walk(fn_node):
        # Anchored on typed-accessor CALLS (env_float("KA_..."), the KA016
        # pattern) — a knob name merely mentioned in a docstring or log
        # message is documentation, not a deadline consult, and must not
        # price into the envelope.
        if not isinstance(call, ast.Call) or not call.args:
            continue
        if _call_terminal_name(call) not in KNOB_READ_NAMES:
            continue
        name = _knob_literal(call.args[0])
        if name is None or name == BUDGET_KNOB:
            continue
        if _BUDGET_TIMEOUT_TOKEN in name:
            secs = _knob_seconds(name, defaults.get(name))
            if secs is not None:
                timeouts.append((name, secs))
        elif _BUDGET_RETRIES_TOKEN in name:
            val = defaults.get(name)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                retries.append((name, float(val)))
    if not timeouts:
        return 0.0, []
    mult = 1.0 + max((v for _n, v in retries), default=0.0)
    total = sum(v for _n, v in timeouts) * mult
    names = sorted({n for n, _v in timeouts} | {n for n, _v in retries})
    return total, names


def check_blocking_budget(
    project: Project,
    display: Dict[str, str],
    knob_defaults=None,
    budget: Optional[float] = None,
) -> List[Finding]:
    """KA020: the quantitative twin of KA015/KA019 — for every function
    reachable under the shared solve lock, an inflight-gate admission,
    or a controller-loop thread entry, sum the worst-case envelopes of
    the functions along its reaching chain; a total exceeding the
    region's deadline budget (``KA_DAEMON_REQUEST_TIMEOUT`` for held
    regions, ``KA_CONTROLLER_INTERVAL`` for controller loops) is a
    finding (anchored at the contributing function, chain attached).
    One finding per chain function that itself contributes envelope —
    pass-through hops stay silent so a deep chain reads as one finding
    per deadline consult, not one per hop."""
    from .taint import gate_held_set, lock_held_set
    from .threads import thread_model

    if knob_defaults is None:
        from ...utils.env import KNOBS

        knob_defaults = {name: k.default for name, k in KNOBS.items()}
    if budget is None:
        b = _knob_seconds(BUDGET_KNOB, knob_defaults.get(BUDGET_KNOB))
        budget = b if b is not None else 30.0
    cb = _knob_seconds(
        CONTROLLER_BUDGET_KNOB, knob_defaults.get(CONTROLLER_BUDGET_KNOB))
    controller_budget = cb if cb is not None else 30.0

    env_cache: Dict[str, Tuple[float, List[str]]] = {}

    def envelope(key: str) -> Tuple[float, List[str]]:
        if key not in env_cache:
            fn = project.functions.get(key)
            env_cache[key] = (
                _fn_budget_envelope(fn.node, knob_defaults)
                if fn is not None else (0.0, [])
            )
        return env_cache[key]

    held_tail = (
        "the request can legally block longer than the watchdog's "
        "patience — shrink the envelope, move the waiting off the held "
        "region, or suppress citing why the bound is unreachable"
    )
    sources: List[Tuple] = [
        (lock_held_set(project)[0],
         f"reachable while the shared solve lock is held exceeds the "
         f"{BUDGET_KNOB} watchdog budget",
         budget, held_tail),
        (gate_held_set(project)[0],
         f"reachable while an inflight-gate admission is held exceeds "
         f"the {BUDGET_KNOB} watchdog budget",
         budget, held_tail),
    ]
    # Controller loops (the carried ROADMAP KA020 extension): a thread
    # entry targeting the controller module runs on the loop cadence, so
    # its chains price against one interval, not the request watchdog.
    model = thread_model(project)
    for entry in model.entries:
        if split_key(entry.key)[0] != CONTROLLER_MODULE:
            continue
        sources.append((
            model.reach[entry.key],
            f"reachable on the controller loop ({entry.key}) exceeds "
            f"the {CONTROLLER_BUDGET_KNOB} loop-cadence budget",
            controller_budget,
            "one tick can legally outlast the loop cadence and starve "
            "every later tick — shrink the envelope, move the waiting "
            "off the loop thread, or suppress citing why the bound is "
            "unreachable",
        ))

    out: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for held, mid, src_budget, tail in sources:
        for key in sorted(held.members):
            fn = project.functions.get(key)
            if fn is None:
                continue
            own_secs, own_knobs = envelope(key)
            if own_secs <= 0.0:
                continue  # anchor findings where envelope is added
            chain = held.chain(key)
            total = 0.0
            knobs: List[str] = []
            for hop_key, _line in chain:
                secs, names = envelope(hop_key)
                total += secs
                knobs.extend(names)
            if total <= src_budget:
                continue
            dedupe = (mid, key)
            if dedupe in seen:
                continue
            seen.add(dedupe)
            out.append(Finding(
                "KA020", display.get(fn.relpath, fn.relpath),
                fn.node.lineno, fn.node.col_offset + 1,
                f"worst-case blocking envelope ~{total:g} s (deadline "
                f"knobs along the chain: {', '.join(sorted(set(knobs)))}) "
                f"{mid} ({src_budget:g} s): {tail}",
                chain=held.chain_strs(key),
            ))
    return out


def _act_closure(project: Project) -> Dict[str, Tuple[Optional[str], int]]:
    """Forward closure from every ``CONTROLLER_MODULE`` function named
    ``ACT_ENTRY_NAME``, with the by-name ``ACT_BRIDGE_NAME`` edge added
    wherever the resolver dropped it (untyped ``self.sup``). Returns
    member -> (parent member or None, call-site line) for chain
    reconstruction."""
    bridge_targets = sorted(
        k for k in project.functions
        if split_key(k)[1].split(".")[-1] == ACT_BRIDGE_NAME
    )
    parent: Dict[str, Tuple[Optional[str], int]] = {}
    order: List[str] = []
    for key in sorted(project.functions):
        relpath, qual = split_key(key)
        if relpath == CONTROLLER_MODULE \
                and qual.split(".")[-1] == ACT_ENTRY_NAME:
            parent[key] = (None, project.functions[key].node.lineno)
            order.append(key)
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        callees = dict(project.callees(cur))
        fn = project.functions.get(cur)
        if fn is not None and bridge_targets:
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == ACT_BRIDGE_NAME:
                    for target in bridge_targets:
                        callees.setdefault(target, node.lineno)
        for callee, line in sorted(callees.items()):
            if callee in parent or callee not in project.functions:
                continue
            parent[callee] = (cur, line)
            order.append(callee)
    return parent


def check_act_budget(
    project: Project,
    display: Dict[str, str],
    knob_defaults=None,
    budget: Optional[float] = None,
) -> List[Finding]:
    """KA028: deadline cross-pricing for the controller act path —
    KA020's machinery pointed at the seam KA020 cannot see. The act
    closure (``_act`` → name-bridged ``controller_execute`` → executor)
    is priced with :func:`_fn_budget_envelope` exactly like a held
    region, against the rolling move-window budget ``ACT_BUDGET_KNOB``:
    the ledger prunes entries older than one window, so an action that
    can legally still be executing when its own record expires makes
    the cooldown and blast-radius gates read phantom headroom. Findings
    anchor at the contributing function, chain attached."""
    if knob_defaults is None:
        from ...utils.env import KNOBS

        knob_defaults = {name: k.default for name, k in KNOBS.items()}
    if budget is None:
        b = _knob_seconds(ACT_BUDGET_KNOB, knob_defaults.get(ACT_BUDGET_KNOB))
        budget = b if b is not None else 3600.0

    parent = _act_closure(project)
    env_cache: Dict[str, Tuple[float, List[str]]] = {}

    def envelope(key: str) -> Tuple[float, List[str]]:
        if key not in env_cache:
            fn = project.functions.get(key)
            env_cache[key] = (
                _fn_budget_envelope(fn.node, knob_defaults)
                if fn is not None else (0.0, [])
            )
        return env_cache[key]

    def chain(key: str) -> Tuple[str, ...]:
        hops: List[str] = []
        cur: Optional[str] = key
        while cur is not None:
            par, line = parent[cur]
            hops.append(f"{cur}@{line}")
            cur = par
        return tuple(reversed(hops))

    out: List[Finding] = []
    for key in sorted(parent):
        fn = project.functions.get(key)
        if fn is None:
            continue
        own_secs, _own = envelope(key)
        if own_secs <= 0.0:
            continue  # anchor findings where envelope is added
        total = 0.0
        knobs: List[str] = []
        cur: Optional[str] = key
        while cur is not None:
            secs, names = envelope(cur)
            total += secs
            knobs.extend(names)
            cur = parent[cur][0]
        if total <= budget:
            continue
        out.append(Finding(
            "KA028", display.get(fn.relpath, fn.relpath),
            fn.node.lineno, fn.node.col_offset + 1,
            f"worst-case act-path execution envelope ~{total:g} s "
            f"(deadline knobs along the chain: "
            f"{', '.join(sorted(set(knobs)))}) exceeds the "
            f"{ACT_BUDGET_KNOB} rolling move-window budget "
            f"({budget:g} s): an action that can legally outlast the "
            "window corrupts the move-ledger accounting every cooldown "
            "and blast-radius decision reads — shrink the executor "
            "envelope, split the action, or suppress citing why the "
            "bound is unreachable",
            chain=chain(key),
        ))
    return out


def _scc_partition(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan SCCs (iterative) over a name digraph, for KA023."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work: List[Tuple[str, Iterable[str]]] = [
            (root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = low[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(sorted(graph.get(succ, ())))))
                    advanced = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    nodes = set(graph) | {i for succs in graph.values() for i in succs}
    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return sccs


def check_thread_safety(project: Project,
                        display: Dict[str, str]) -> List[Finding]:
    """KA021/KA022/KA023 over the :mod:`.threads` model.

    Per shared attribute (grouped across every thread entry that reaches
    it, a CONCURRENT entry — the HTTP surface — counting as two threads
    since it races with itself):

    - **KA021** fires when two or more thread-weights WRITE it and the
      intersection of the write lock-sets is empty — nothing serializes
      the writes. One finding per attribute, anchored at the first write.
    - **KA022** fires when the writes DO agree on a common lock but some
      reaching access holds none of it — the forgotten-lock path.
      Anchored at the unguarded access. Mutually exclusive with KA021.

    Attributes never written outside ``__init__``, or reached by fewer
    than two thread-weights, are skipped: single-writer flag patterns
    (one loop publishing, readers polling a bool) are a deliberate
    non-goal — flagging them would drown the triage in benign reads.

    **KA023** is entry-independent: an edge A→B wherever B is acquired
    with A held (lexically, or anywhere in the call closure of an A-held
    region); a strongly-connected component with ≥2 locks is a cycle —
    two threads can each hold one lock and wait on the other. One
    finding per SCC, anchored at the first witnessing acquisition."""
    from .threads import thread_model

    model = thread_model(project)
    out: List[Finding] = []

    def disp(relpath: str) -> str:
        return display.get(relpath, relpath)

    def tid(entry_key: str) -> str:
        # every "main"-kind seed is the SAME OS thread (run_daemon_process
        # calls serve/start/shutdown in sequence) — collapse them to one
        # identity so main-only writes never count as a race
        e = model.entry_by_key.get(entry_key)
        return "<main>" if (e is not None and e.kind == "main") \
            else entry_key

    def thread_weight(entry_keys) -> int:
        # distinct OS threads that can touch the attribute; a CONCURRENT
        # entry (HTTP surface) races with itself and counts as two
        by_tid: Dict[str, bool] = {}
        for ek in entry_keys:
            e = model.entry_by_key.get(ek)
            conc = bool(e is not None and e.concurrent)
            by_tid[tid(ek)] = by_tid.get(tid(ek), False) or conc
        return sum(2 if conc else 1 for conc in by_tid.values())

    def entry_label(entry_key: str) -> str:
        e = model.entry_by_key.get(entry_key)
        return e.label if e is not None else entry_key

    def acc_chain(acc) -> Tuple[str, ...]:
        reach = model.reach.get(acc.entry)
        return reach.chain_strs(acc.funckey) if reach else ()

    groups: Dict[Tuple[Tuple[str, str], str], List] = {}
    for acc in model.accesses:
        groups.setdefault((acc.owner, acc.attr), []).append(acc)

    for (owner, attr), accs in sorted(groups.items()):
        orel, ocls = owner
        writes = [a for a in accs if a.write]
        if not writes:
            continue
        entries = sorted({a.entry for a in accs})
        if thread_weight(entries) < 2:
            continue  # single-threaded state
        writer_entries = sorted({a.entry for a in writes})
        writer_weight = thread_weight(writer_entries)
        common_w = frozenset.intersection(*[a.locks for a in writes])
        sortkey = lambda a: (disp(split_key(a.funckey)[0]), a.line,  # noqa: E731
                             a.col, a.funckey)
        if writer_weight >= 2 and not common_w:
            w = min(writes, key=sortkey)
            threads_desc = "; ".join(
                entry_label(e) for e in writer_entries)
            locks_seen = sorted({n for a in writes for n in a.locks})
            held_desc = (
                f" (locks held on SOME writes: {', '.join(locks_seen)})"
                if locks_seen else ""
            )
            out.append(Finding(
                "KA021", disp(split_key(w.funckey)[0]), w.line, w.col,
                f"shared attribute {ocls}.{attr} ({orel}) is written by "
                f"{writer_weight} thread(s) — {threads_desc} — with an "
                f"empty common lock-set{held_desc}: the writes race; "
                "guard every write with one lock, or suppress citing "
                "the happens-before protocol that serializes them",
                chain=acc_chain(w),
            ))
            continue  # an attribute is either unserialized or misguarded
        if common_w:
            bad = [a for a in accs if not (a.locks & common_w)]
            if not bad:
                continue
            a = min(bad, key=sortkey)
            guard = ", ".join(sorted(common_w))
            kind = "written" if a.write else "read"
            out.append(Finding(
                "KA022", disp(split_key(a.funckey)[0]), a.line, a.col,
                f"shared attribute {ocls}.{attr} ({orel}) is guarded by "
                f"{guard} on every write but {kind} here with no common "
                f"lock held (reached from {entry_label(a.entry)}): the "
                "forgotten-lock path can observe torn state — take "
                f"{guard} on this path, or suppress citing why it "
                "cannot race",
                chain=acc_chain(a),
            ))

    # -- KA023: lock-order cycles --------------------------------------------
    digraph: Dict[str, Set[str]] = {}
    for (outer, inner) in model.lock_edges:
        digraph.setdefault(outer, set()).add(inner)
    for scc in _scc_partition(digraph):
        if len(scc) < 2:
            continue
        names = sorted(scc)
        # reconstruct one concrete cycle from the least lock for the
        # message: min-name → … → min-name through SCC-internal edges
        start = names[0]
        path = [start]
        seen_nodes = {start}
        cur = start
        while True:
            nxt = next(
                (i for i in sorted(digraph.get(cur, ()))
                 if i in scc and (i == start or i not in seen_nodes)),
                None,
            )
            if nxt is None or nxt == start:
                path.append(start)
                break
            path.append(nxt)
            seen_nodes.add(nxt)
            cur = nxt
        first = None
        for outer, inner in zip(path, path[1:]):
            edge = model.lock_edges.get((outer, inner))
            if edge is not None and first is None:
                first = edge
        if first is None:  # SCC via edges the walk skipped; take any
            first = next(
                e for (o, i), e in sorted(model.lock_edges.items())
                if o in scc and i in scc
            )
        cycle_desc = " -> ".join(path)
        sites = []
        for outer, inner in zip(path, path[1:]):
            edge = model.lock_edges.get((outer, inner))
            if edge is not None:
                sites.append(
                    f"{inner} under {outer} at "
                    f"{disp(edge.relpath)}:{edge.line}")
        out.append(Finding(
            "KA023", disp(first.relpath), first.line, 1,
            f"lock-order cycle {cycle_desc} (locks identified by name, "
            f"may-alias): {'; '.join(sites)} — two threads can each "
            "hold one lock and wait on the other (deadlock); impose a "
            "global acquisition order, or suppress citing the protocol "
            "that keeps the inversion unreachable",
            chain=first.chain,
        ))
    return out


def project_findings(project: Project,
                     display: Dict[str, str]) -> List[Finding]:
    """Every graph-backed finding over one resolved project: the traced-set
    rules (KA002/KA007/KA016/KA017), the lock-held rule (KA015), the
    budget rules (KA020/KA028), the thread-safety rules
    (KA021/KA022/KA023), the determinism taint layer (KA024–KA027),
    transitive bulkhead reachability (KA012), and dispatch-plane seam
    reachability (KA029). ``display`` maps module
    relpaths to the path
    findings should print (suppressions are applied by the caller, which
    owns the per-module suppression indexes)."""
    out: List[Finding] = []
    traced = traced_set(project)
    mutable_cache: Dict[str, Set[str]] = {}

    def disp(relpath: str) -> str:
        return display.get(relpath, relpath)

    def entry_label(taint, key: str) -> str:
        entry = taint.entry_of.get(key, key)
        return taint.root_labels.get(entry, entry)

    # -- traced-set rules: KA002, KA007, KA016, KA017 ------------------------
    for key in sorted(traced.members):
        fn = project.functions.get(key)
        if fn is None:
            continue
        relpath = fn.relpath
        path = disp(relpath)
        chain = traced.chain_strs(key)
        label = entry_label(traced, key)
        mod = project.modules[relpath]
        if relpath not in mutable_cache:
            mutable_cache[relpath] = _module_mutable_globals(mod.tree)
        out.extend(_ka007_fn_findings(
            fn.node, fn.qualname, mutable_cache[relpath], path, chain=chain,
        ))
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            msg = _banned_call(node)
            if msg:
                out.append(Finding(
                    "KA002", path, node.lineno, node.col_offset + 1,
                    f"{msg} in jit-traced code reachable from {label} "
                    "(host work must stay outside the traced solve)",
                    chain=chain,
                ))
            name = _call_terminal_name(node)
            if name in ENV_ACCESSOR_NAMES:
                knob = _knob_literal(node.args[0]) if node.args else None
                what = f"{name}({knob!r})" if knob else f"{name}(...)"
                out.append(Finding(
                    "KA016", path, node.lineno, node.col_offset + 1,
                    f"trace-time knob read {what} inside jit-traced code "
                    f"reachable from {label}: the value is frozen into the "
                    "cached executable and later env changes are silently "
                    "ignored — hoist the read outside the trace and pass "
                    "it as a static argument, or suppress with a reason "
                    "citing what re-keys the compiled program",
                    chain=chain,
                ))
            if name in OBS_WRITE_NAMES:
                out.append(Finding(
                    "KA017", path, node.lineno, node.col_offset + 1,
                    f"obs write {name}(...) inside jit-traced code "
                    f"reachable from {label}: metrics emission from traced "
                    "code fires at trace time only (then never again per "
                    "cached executable) and forces host sync — emit from "
                    "the dispatching host code instead",
                    chain=chain,
                ))

    # -- KA015 + KA019: blocking work inside a held region --------------------
    # One emission pass, two (rule, closure, phrasing) instantiations —
    # KA019 is KA015's twin over the inflight-gate regions instead of the
    # solve-lock ones. A sink already under the solve lock is USUALLY
    # also gate-held (the gate admits before the lock), so the rules
    # overlap on purpose — a suppression must name both, each with its
    # own reason (lock stall vs admission-slot starvation).
    def held_rule(rule: str, held, regions,
                  sink_tail: str, zk_tail: str) -> None:
        def finding(path: str, node: ast.Call, desc: str,
                    chain: Tuple[str, ...], label: str) -> Finding:
            return Finding(
                rule, path, node.lineno, node.col_offset + 1,
                f"{desc} {sink_tail.format(label=label)}",
                chain=chain,
            )

        for region in regions:
            path = disp(region.relpath)
            label = held.root_labels.get(region.funckey, region.funckey)
            for stmt in region.held_nodes:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call):
                        desc = _blocking_sink_desc(node)
                        if desc:
                            out.append(finding(
                                path, node, desc,
                                (f"{region.funckey}@{region.line}",),
                                label,
                            ))
        region_keys = {r.funckey for r in regions}
        for key in sorted(held.members):
            if key in region_keys:
                continue  # only a holder's held statements are in scope
            fn = project.functions.get(key)
            if fn is None:
                continue
            path = disp(fn.relpath)
            chain = held.chain_strs(key)
            label = entry_label(held, key)
            if fn.relpath == WIRE_MODULE and fn.name in ZK_WRITE_FUNC_NAMES:
                parent, line = held.parents.get(key, (None, fn.node.lineno))
                anchor_rel, _ = (
                    split_key(parent) if parent else (fn.relpath, "")
                )
                out.append(Finding(
                    rule, disp(anchor_rel), line, 1,
                    f"ZooKeeper write {fn.qualname}(...) "
                    + zk_tail.format(label=label),
                    chain=chain,
                ))
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    desc = _blocking_sink_desc(node)
                    if desc:
                        out.append(finding(path, node, desc, chain, label))

    held, regions = lock_held_set(project)
    held_rule(
        "KA015", held, regions,
        "reachable while the shared solve lock is held (from {label}): "
        "the lock serializes every solve-bearing request across all "
        "clusters, so a blocked holder stalls the whole daemon — move "
        "the blocking work outside the lock, or suppress with a reason "
        "citing the chain",
        "reachable while the shared solve lock is held (from {label}): "
        "a quorum round-trip under the lock stalls every cluster's "
        "solve-bearing requests — writes belong on the execute path, "
        "never under the solve lock",
    )
    # KA020 rides the same two closures (plus the controller-loop thread
    # entries): the qualitative rules above kill unbounded blocking; the
    # budget rule prices the BOUNDED kind.
    out.extend(check_blocking_budget(project, display))
    # KA028: the act-path twin — same pricing, name-bridged through
    # controller_execute, against the rolling move-window budget.
    out.extend(check_act_budget(project, display))
    # KA021/KA022/KA023: the thread-topology model (who runs where, under
    # which locks) over the same call graph.
    out.extend(check_thread_safety(project, display))
    # KA024-KA027: the determinism taint layer (source→sink over the same
    # call graph; KA027 reuses the thread model memo built above).
    from .determinism import check_determinism

    out.extend(check_determinism(project, display))

    gheld, gregions = gate_held_set(project)
    held_rule(
        "KA019", gheld, gregions,
        "reachable while an inflight-gate admission is held "
        "(from {label}): the admitted request occupies one of the "
        "cluster's bounded backpressure slots until _release(), so a "
        "blocked holder starves the gate and sheds healthy clients — "
        "move the blocking work outside the admission, or suppress with "
        "a reason citing the chain",
        "reachable while an inflight-gate admission is held "
        "(from {label}): a quorum round-trip inside an admitted slot "
        "starves the per-cluster backpressure gate — writes belong on "
        "the execute path, outside the solve-bearing admission",
    )

    # -- KA012 transitive: bulkhead reachability ------------------------------
    # Roots: every function in a daemon non-bulkhead module. Traversal never
    # passes THROUGH the bulkhead modules (supervisor methods ARE the
    # sanctioned interface). Sinks: a `.backend`/`.state` read on a value
    # statically typed as the supervisor class, in any non-bulkhead module
    # (direct reads inside daemon/ are the per-module rule's job).
    from .taint import _closure

    roots = {
        key: (fn.node.lineno, f"daemon handler {fn.qualname} ({fn.relpath})")
        for key, fn in project.functions.items()
        if fn.relpath.startswith(DAEMON_PKG_PREFIX)
        and fn.relpath not in DAEMON_BULKHEAD_MODULES
    }
    reach = _closure(
        project, roots,
        stop=lambda k: split_key(k)[0] in DAEMON_BULKHEAD_MODULES,
    )
    for key in sorted(reach.members):
        fn = project.functions.get(key)
        if fn is None or fn.relpath.startswith(DAEMON_PKG_PREFIX):
            continue  # daemon-module reads are the per-module rule's job
        mod = project.modules[fn.relpath]
        env = project.function_env(mod, fn)
        sup_names = {
            n for n, t in env.types.items() if t == SUPERVISOR_CLASS
        }
        if not sup_names:
            continue
        chain = reach.chain_strs(key)
        label = entry_label(reach, key)
        for node in ast.walk(fn.node):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and node.attr in BULKHEAD_ATTRS
                and isinstance(node.value, ast.Name)
                and node.value.id in sup_names
            ):
                out.append(Finding(
                    "KA012", disp(fn.relpath), node.lineno,
                    node.col_offset + 1,
                    f".{node.attr} read on a ClusterSupervisor outside the "
                    f"bulkhead boundary, reachable from {label} "
                    "(cross-bulkhead access through a helper chain): route "
                    "through the owning supervisor's methods",
                    chain=chain,
                ))

    # -- KA029 transitive: dispatch-plane seam reachability -------------------
    # Roots: every function in a daemon module other than the dispatcher
    # itself. Traversal never passes THROUGH the seam (the dispatcher and
    # the bucket-boundary modules ARE the sanctioned device path — their
    # internals submit rows to the installed broker). Sinks: a ``*_jit``
    # program call, or a store-backed ``_program``/``_sweep_program``
    # entry, anywhere the closure reaches OUTSIDE the seam — a device
    # dispatch the gather queue never sees.
    roots29 = {
        key: (fn.node.lineno, f"daemon handler {fn.qualname} ({fn.relpath})")
        for key, fn in project.functions.items()
        if fn.relpath.startswith(DAEMON_PKG_PREFIX)
        and fn.relpath not in DISPATCH_SEAM_MODULES
    }
    reach29 = _closure(
        project, roots29,
        stop=lambda k: split_key(k)[0] in DISPATCH_SEAM_MODULES,
    )
    for key in sorted(reach29.members):
        fn = project.functions.get(key)
        if fn is None or fn.relpath in DISPATCH_SEAM_MODULES:
            continue
        chain = reach29.chain_strs(key)
        label = entry_label(reach29, key)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = _call_terminal_name(node)
            if name is None:
                continue
            if (
                name.endswith("_jit")
                and name not in DISPATCH_BUILDER_NAMES
            ) or name in DISPATCH_STORE_ENTRY_NAMES:
                out.append(Finding(
                    "KA029", disp(fn.relpath), node.lineno,
                    node.col_offset + 1,
                    f"device dispatch {name}(...) reachable from {label} "
                    "outside the dispatcher seam: the gather queue never "
                    "sees this solve, so it monopolizes the device behind "
                    "the coalescing plane's back — route the rows through "
                    "daemon/dispatch.py or a bucket-boundary module "
                    f"({sorted(BUCKET_BOUNDARY_MODULES)})",
                    chain=chain,
                ))
    return out
