"""``python -m kafka_assigner_tpu.analysis.kalint`` dispatch."""
import sys

from .cli import main

sys.exit(main())
