"""``kalint`` — the project-native static analyzer, now interprocedural.

The system's value proposition is byte-compatibility with the reference
assigner under a large surface of tuning knobs; the correctness risks that
grow with the codebase are silent config drift, host-sync leaking into
jitted solver paths, and — since the daemon became a concurrent,
lock-mediated service — blocking work hiding behind the shared solve lock
and handlers reaching across cluster bulkheads. ``kalint`` machine-checks
all of it, and since ISSUE 12 it does so PROJECT-WIDE: an import graph,
per-module symbol tables and a call graph over the whole package
(:mod:`.resolve`) feed a taint engine (:mod:`.taint`) that computes the
transitive *traced set* (everything reachable from a ``jax.jit``/``pjit``/
``shard_map`` entry, across modules) and the *lock-held set* (everything
reachable from a ``with <solve-lock>`` region in ``daemon/``), so KA002/
KA007 fire anywhere in the traced set, KA012 is transitive, and the graph
powers three rules a single-file pass cannot see (KA015–KA017).

Since ISSUE 16 the graph also carries a THREAD-TOPOLOGY layer
(:mod:`.threads`): discovered thread entries (``Thread``/``Timer``/
executor targets, the HTTP handler surface, the daemon main thread),
per-entry reachable sets, an attribute-level shared-state model over the
``daemon/``/``exec/`` classes, and lock-set inference generalized from
the solve lock to every in-project ``threading.Lock/RLock/Condition`` —
feeding the race rules (KA021 unguarded multi-thread writes, KA022
inconsistent guarding) and the deadlock rule (KA023 lock-order cycles).
The smoke harnesses under ``scripts/`` are grafted into the same graph,
so their plumbing is swept too.

Since ISSUE 17 the graph carries a DETERMINISM taint layer
(:mod:`.determinism`): nondeterminism sources (set/queue/filesystem
iteration order, wall-clock/random/uuid reads, thread-racy collection
drains) propagated along the call graph into the byte-pinned sinks
(``json.dumps``, stdout emission, promtext rendering), with
``sorted()``/canonical-order sanitizer recognition — the rules KA024–
KA027 that statically prove the byte-identity contract, plus the KA028
act-path deadline cross-pricing twin of KA020.

The rule catalog (KA000–KA028) lives in :data:`RULES` with one-line
meanings and example chains in :data:`RULE_DOCS`; the README rule table is
generated from it (``python -m kafka_assigner_tpu.analysis.ruledoc
--write``).

Suppression: put ``# kalint: disable=KA002 -- <reason>`` on the offending
line, on its own line directly above, or on ANY physical line the wrapped
statement spans. The reason is mandatory — a reasonless suppression is
itself a finding (KA000) and does not suppress.

Run ``python -m kafka_assigner_tpu.analysis.kalint`` (no args: lint the
whole package interprocedurally through the content-hash cache, plus the
README check; exit non-zero on findings), pass explicit file paths for
single-file mode, ``--explain KA0NN`` for offending call chains, or
``--format json --out f.json`` for CI. ``scripts/lint.sh`` wires all of it
into the tier-1 gate.
"""
from __future__ import annotations

from .findings import (  # noqa: F401
    Finding,
    SuppressionIndex,
    dedupe_findings,
    finalize,
    sort_findings,
)
from .resolve import (  # noqa: F401
    FUNC_SEP,
    FunctionInfo,
    ModuleInfo,
    Project,
    build_project,
    func_key,
    split_key,
)
from .taint import (  # noqa: F401
    JIT_WRAPPER_NAMES,
    GateRegion,
    LockRegion,
    TaintResult,
    gate_held_set,
    gate_regions,
    jit_roots,
    lock_held_set,
    lock_regions,
    traced_set,
)
from .rules import (  # noqa: F401
    BUCKET_BOUNDARY_MODULES,
    BULKHEAD_ATTRS,
    DAEMON_BULKHEAD_MODULES,
    DAEMON_PKG_PREFIX,
    EITHER_NAME_CALLS,
    ENV_ACCESSOR_NAMES,
    JSON_BOUNDARY_MODULE,
    KERNEL_MODULES,
    METRIC_NAME_CALLS,
    METRIC_UNIT_TOKENS,
    OBS_WRITE_NAMES,
    REGISTRY_MODULE,
    RULE_DOCS,
    RULES,
    SERIAL_WRITE_FUNCS,
    SPAN_NAME_CALLS,
    SUPERVISOR_CLASS,
    WIRE_MODULE,
    WRITE_OPCODES,
    ZK_WRITE_FUNC_NAMES,
    ACT_BRIDGE_NAME,
    ACT_BUDGET_KNOB,
    ACT_ENTRY_NAME,
    BUDGET_KNOB,
    CONTROLLER_BUDGET_KNOB,
    CONTROLLER_MODULE,
    check_act_budget,
    check_blocking_budget,
    check_dead_knobs,
    check_metric_units,
    check_readme,
    check_thread_safety,
    project_findings,
)
from .determinism import (  # noqa: F401
    DECLARED_SINK_FUNCS,
    TS_FIELD_ALLOWLIST,
    TS_FIELD_TOKENS,
    SinkReach,
    check_determinism,
    sink_reach,
)
from .threads import (  # noqa: F401
    HTTP_SURFACE_SEEDS,
    LOCK_CTOR_NAMES,
    MAIN_THREAD_SEEDS,
    SHARED_STATE_PREFIXES,
    LockEdge,
    SharedAccess,
    ThreadEntry,
    ThreadModel,
    discover_locks,
    discover_thread_entries,
    thread_model,
)
from .driver import (  # noqa: F401
    lint_package,
    lint_source,
    lint_tree,
)
from .cli import main  # noqa: F401
