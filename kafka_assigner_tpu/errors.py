"""Phase-tagged failure types for the CLI's documented exit codes.

The reference collapses every failure into one generic nonzero JVM exit; an
operator (or the autoscaler driving this tool, arXiv:2206.11170) cannot tell
"the quorum was unreachable" from "the solve is infeasible" without parsing
stderr. The pipeline driver (``generator.py``) tags unrecoverable failures
with the phase they escaped from, and ``cli.run`` maps each type to its exit
code (README "Failure model"):

========================= ===========================================
type                      meaning / exit code
========================= ===========================================
:class:`IngestError`      metadata ingest failed past the resilience
                          layer's retry budget (exit 3)
:class:`SolveError`       a solver backend crashed — and, under
                          ``best-effort``, so did the greedy fallback
                          (exit 4)
``ValueError``/``KeyError`` input/validation failures keep their plain
                          stdlib types for library callers (exit 5)
:class:`ExecuteError`     the plan execution engine halted MID-plan
                          (convergence timeout past the retry budget,
                          write retry budget exhausted, a reassignment
                          stuck in flight) — the journal holds every
                          committed wave, so the run is resumable via
                          ``ka-execute --resume`` (exit 8). Pre-journal
                          refusals (read-only backend, plan topic not on
                          the cluster) are plain ``ValueError`` instead:
                          exit 8's resume promise would be a lie there
========================= ===========================================

Both types chain the original exception (``raise ... from e``), so library
callers that want the underlying ``ZkWireError``/XLA error still reach it
via ``__cause__``.
"""
from __future__ import annotations


class KafkaAssignerError(RuntimeError):
    """Base for phase-tagged unrecoverable failures of a CLI run."""


class IngestError(KafkaAssignerError):
    """Cluster-metadata ingest failed (connect/read/replay budget
    exhausted, snapshot unreadable, topic vanished under strict policy)."""


class SolveError(KafkaAssignerError):
    """The solver backend crashed (compile failure, device OOM) and no
    fallback produced a plan."""


class ExecuteError(KafkaAssignerError):
    """The plan execution engine halted MID-plan: a wave failed to converge
    within the poll budget under ``--failure-policy strict``, a
    reassignment write exhausted its read-back/resubmit budget, or another
    reassignment stayed in flight past the wait budget. The crash-safe
    journal retains every committed wave — the run resumes idempotently
    via ``ka-execute --resume``. Pre-journal refusals (read-only backend,
    plan/cluster mismatch) raise plain ``ValueError`` — validation, since
    there is nothing to resume."""
