"""Phase-tagged failure types for the CLI's documented exit codes.

The reference collapses every failure into one generic nonzero JVM exit; an
operator (or the autoscaler driving this tool, arXiv:2206.11170) cannot tell
"the quorum was unreachable" from "the solve is infeasible" without parsing
stderr. The pipeline driver (``generator.py``) tags unrecoverable failures
with the phase they escaped from, and ``cli.run`` maps each type to its exit
code (README "Failure model"):

========================= ===========================================
type                      meaning / exit code
========================= ===========================================
:class:`IngestError`      metadata ingest failed past the resilience
                          layer's retry budget (exit 3)
:class:`SolveError`       a solver backend crashed — and, under
                          ``best-effort``, so did the greedy fallback
                          (exit 4)
``ValueError``/``KeyError`` input/validation failures keep their plain
                          stdlib types for library callers (exit 5)
========================= ===========================================

Both types chain the original exception (``raise ... from e``), so library
callers that want the underlying ``ZkWireError``/XLA error still reach it
via ``__cause__``.
"""
from __future__ import annotations


class KafkaAssignerError(RuntimeError):
    """Base for phase-tagged unrecoverable failures of a CLI run."""


class IngestError(KafkaAssignerError):
    """Cluster-metadata ingest failed (connect/read/replay budget
    exhausted, snapshot unreadable, topic vanished under strict policy)."""


class SolveError(KafkaAssignerError):
    """The solver backend crashed (compile failure, device OOM) and no
    fallback produced a plan."""
