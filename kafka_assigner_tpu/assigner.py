"""Per-topic orchestration (L2), mirroring ``KafkaTopicAssigner.java:18-72``.

Responsibilities (SURVEY.md §1 L2):
  - infer the replication factor from the current assignment when the desired
    RF is negative, asserting it is uniform across partitions
    (``KafkaTopicAssigner.java:49-62``);
  - validate ``0 < RF <= |brokers|`` (``KafkaTopicAssigner.java:65-69``);
  - hold one cross-topic ``Context`` per assigner instance so leadership
    balancing spans all topics assigned through it
    (``KafkaTopicAssigner.java:19-23``).
"""
from __future__ import annotations

from typing import Dict, List, Mapping, Sequence, Set

from .solvers.base import Context, Solver, get_solver


class TopicAssigner:
    """Generates a minimal-movement assignment for one topic at a time.

    ``solver`` selects the backend: ``"greedy"`` (reference-faithful oracle) or
    ``"tpu"`` (JAX/XLA solver). Instances are not shared across threads, but
    unlike the reference the cross-topic state is confined to the ``Context``
    object and all solver math is functional.
    """

    def __init__(self, solver: str | Solver = "greedy") -> None:
        self.solver: Solver = get_solver(solver) if isinstance(solver, str) else solver
        self.context = Context()

    def generate_assignment(
        self,
        topic: str,
        current_assignment: Mapping[int, Sequence[int]],
        brokers: Set[int],
        rack_assignment: Mapping[int, str],
        desired_replication_factor: int = -1,
    ) -> Dict[int, List[int]]:
        """Compute a new assignment with minimal movement
        (``KafkaTopicAssigner.java:42-72``)."""
        replication_factor = desired_replication_factor
        partitions: Set[int] = set()
        for partition, replicas in sorted(current_assignment.items()):
            partitions.add(partition)
            if replication_factor < 0:
                replication_factor = len(replicas)
            elif desired_replication_factor < 0 and replication_factor != len(replicas):
                raise ValueError(
                    f"Topic {topic} has partition {partition} with unexpected "
                    f"replication factor {len(replicas)}"
                )
        if replication_factor <= 0:
            raise ValueError(
                f"Topic {topic} does not have a positive replication factor!"
            )
        if replication_factor > len(brokers):
            raise ValueError(
                f"Topic {topic} has a higher replication factor "
                f"({replication_factor}) than available brokers!"
            )
        return self.solver.assign(
            topic,
            current_assignment,
            rack_assignment,
            set(brokers),
            partitions,
            replication_factor,
            self.context,
        )
