"""Per-topic orchestration (L2), mirroring ``KafkaTopicAssigner.java:18-72``.

Responsibilities (SURVEY.md §1 L2):
  - infer the replication factor from the current assignment when the desired
    RF is negative, asserting it is uniform across partitions
    (``KafkaTopicAssigner.java:49-62``);
  - validate ``0 < RF <= |brokers|`` (``KafkaTopicAssigner.java:65-69``);
  - hold one cross-topic ``Context`` per assigner instance so leadership
    balancing spans all topics assigned through it
    (``KafkaTopicAssigner.java:19-23``).
"""
from __future__ import annotations

import sys
from typing import Dict, List, Mapping, Sequence, Set, Tuple

from .solvers.base import Context, Solver, get_solver


def infer_topic_rf(
    topic: str,
    current_assignment: Mapping[int, Sequence[int]],
    desired_replication_factor: int,
) -> int:
    """RF inference with the uniformity assertion
    (``KafkaTopicAssigner.java:49-62``): a negative desired RF means "keep the
    existing one", which is only well-defined when every partition agrees.

    Returns the desired RF unchanged (possibly negative) when the assignment
    is empty — callers that tolerate unknown RF (sweeps, validation) skip
    those topics; ``TopicAssigner`` turns it into the positivity error.

    Shared by the assigner, the what-if sweep, and feasibility validation so
    no path silently picks an arbitrary partition's RF.
    """
    replication_factor = desired_replication_factor
    for partition, replicas in sorted(current_assignment.items()):
        if replication_factor < 0:
            replication_factor = len(replicas)
        elif desired_replication_factor < 0 and replication_factor != len(replicas):
            raise ValueError(
                f"Topic {topic} has partition {partition} with unexpected "
                f"replication factor {len(replicas)}"
            )
    return replication_factor


class TopicAssigner:
    """Generates a minimal-movement assignment for one topic at a time.

    ``solver`` selects the backend: ``"greedy"`` (reference-faithful oracle) or
    ``"tpu"`` (JAX/XLA solver). Instances are not shared across threads, but
    unlike the reference the cross-topic state is confined to the ``Context``
    object and all solver math is functional.
    """

    def __init__(
        self, solver: str | Solver = "greedy", failure_policy: str = "strict"
    ) -> None:
        self.solver: Solver = get_solver(solver) if isinstance(solver, str) else solver
        self.context = Context()
        #: ``best-effort`` arms the solver fallback chain: a non-greedy
        #: backend that CRASHES (compile failure, device OOM — any
        #: non-ValueError exception) is retried through the greedy oracle
        #: for the affected group instead of killing the run. Safe because
        #: (a) every backend is byte-equal with the greedy oracle
        #: (test-pinned parity), and (b) backends only apply leadership-
        #: counter updates after a successful solve, so the shared Context
        #: is untouched by the crash and the replay is exact.
        self.failure_policy = failure_policy
        #: How many groups fell back to greedy in the most recent
        #: ``generate_assignments`` call (the run report's
        #: ``solve.fallbacks`` source).
        self.fallbacks = 0
        self._greedy_fallback: Solver | None = None

    def _should_fallback(self, exc: Exception) -> bool:
        """Crash classes only: ValueError is input validation/infeasibility
        (greedy would refuse identically — nothing to rescue), and a greedy
        backend has no one left to fall back to."""
        return (
            self.failure_policy == "best-effort"
            and not isinstance(exc, ValueError)
            and getattr(self.solver, "name", None) != "greedy"
        )

    def _fallback_group(
        self,
        items: Sequence[Tuple[str, Mapping[int, Sequence[int]]]],
        rfs: Sequence[int],
        rack_assignment: Mapping[int, str],
        brokers: Set[int],
        exc: Exception,
    ) -> List[Tuple[str, Dict[int, List[int]]]]:
        """Re-solve one crashed group through the greedy oracle, loudly."""
        from .obs.metrics import counter_add

        counter_add("solve.fallbacks")
        self.fallbacks += 1
        print(
            f"kafka-assigner: best-effort: "
            f"{getattr(self.solver, 'name', type(self.solver).__name__)} "
            f"solver crashed ({type(exc).__name__}: {exc}); falling back to "
            f"the greedy solver for {len(items)} topic(s)",
            file=sys.stderr,
        )
        if self._greedy_fallback is None:
            from .solvers.greedy import GreedySolver

            self._greedy_fallback = GreedySolver()
        return [
            (
                topic,
                self._greedy_fallback.assign(
                    topic, cur, rack_assignment, set(brokers), set(cur),
                    rf, self.context,
                ),
            )
            for (topic, cur), rf in zip(items, rfs)
        ]

    def _infer_replication_factor(
        self,
        topic: str,
        current_assignment: Mapping[int, Sequence[int]],
        brokers: Set[int],
        desired_replication_factor: int,
    ) -> int:
        """RF inference + validation (``KafkaTopicAssigner.java:49-69``)."""
        replication_factor = infer_topic_rf(
            topic, current_assignment, desired_replication_factor
        )
        if replication_factor <= 0:
            raise ValueError(
                f"Topic {topic} does not have a positive replication factor!"
            )
        if replication_factor > len(brokers):
            raise ValueError(
                f"Topic {topic} has a higher replication factor "
                f"({replication_factor}) than available brokers!"
            )
        return replication_factor

    def generate_assignment(
        self,
        topic: str,
        current_assignment: Mapping[int, Sequence[int]],
        brokers: Set[int],
        rack_assignment: Mapping[int, str],
        desired_replication_factor: int = -1,
    ) -> Dict[int, List[int]]:
        """Compute a new assignment with minimal movement
        (``KafkaTopicAssigner.java:42-72``)."""
        replication_factor = self._infer_replication_factor(
            topic, current_assignment, brokers, desired_replication_factor
        )
        return self.solver.assign(
            topic,
            current_assignment,
            rack_assignment,
            set(brokers),
            set(current_assignment),
            replication_factor,
            self.context,
        )

    def generate_assignments(
        self,
        topic_assignments: (
            Mapping[str, Mapping[int, Sequence[int]]]
            | Sequence[Tuple[str, Mapping[int, Sequence[int]]]]
        ),
        brokers: Set[int],
        rack_assignment: Mapping[int, str],
        desired_replication_factor: int = -1,
        preencoded: tuple | None = None,
    ) -> List[Tuple[str, Dict[int, List[int]]]]:
        """Solve many topics through one shared Context, returning
        ``[(topic, assignment), ...]`` in input order.

        Accepts an ordered mapping or a sequence of (topic, current) pairs;
        pairs may repeat a topic name, in which case every occurrence is
        solved and advances the leadership Context, exactly like the
        reference's topic loop (``KafkaAssignmentGenerator.java:173-176``).
        When the backend supports batching (``assign_many``), the topics are
        solved in a single device dispatch with identical output to the
        serial loop (the scan carries the leadership counters in topic
        order) — mixed replication factors included for backends that
        declare ``supports_mixed_rf`` (the TPU solver does); other batching
        backends get one dispatch per run of consecutive same-RF topics.

        ``preencoded``: an ``encode_topic_group`` result for exactly these
        topics in this order (the streaming-ingest overlap builds it while
        ZooKeeper responses arrive, ``generator.py``); forwarded to a
        mixed-RF batching backend so it can skip its own encode. Ignored —
        the work was merely speculative — for backends that cannot consume
        it.
        """
        # One device trace per batched solve when KA_OBS_PROFILE_DIR (or
        # the legacy KA_PROFILE) is set (SURVEY.md §5: the reference has no
        # profiling at all; solve latency is our headline metric). View
        # with TensorBoard/XProf. Unset: zero profiler overhead; busy
        # (a /debug/profile window in flight): this dispatch skips tracing
        # instead of failing the solve.
        from .obs.profile import dispatch_trace

        with dispatch_trace():
            return self._generate_assignments(
                topic_assignments, brokers, rack_assignment,
                desired_replication_factor, preencoded,
            )

    def _generate_assignments(
        self,
        topic_assignments,
        brokers: Set[int],
        rack_assignment: Mapping[int, str],
        desired_replication_factor: int = -1,
        preencoded: tuple | None = None,
    ) -> List[Tuple[str, Dict[int, List[int]]]]:
        items = (
            list(topic_assignments.items())
            if isinstance(topic_assignments, Mapping)
            else list(topic_assignments)
        )
        rfs = [
            self._infer_replication_factor(
                topic, cur, brokers, desired_replication_factor
            )
            for topic, cur in items
        ]
        self.fallbacks = 0
        assign_many = getattr(self.solver, "assign_many", None)
        out: List[Tuple[str, Dict[int, List[int]]]] = []
        if assign_many is None:
            for (topic, cur), rf in zip(items, rfs):
                try:
                    out.append(
                        (
                            topic,
                            self.solver.assign(
                                topic, cur, rack_assignment, set(brokers),
                                set(cur), rf, self.context,
                            ),
                        )
                    )
                except Exception as e:
                    if not self._should_fallback(e):
                        raise
                    out.extend(
                        self._fallback_group(
                            [(topic, cur)], [rf], rack_assignment, brokers, e
                        )
                    )
            return out

        # A mixed-RF-capable backend takes the whole list in ONE dispatch
        # (per-topic rfs ride the same lane the what-if sweeps use);
        # otherwise batch runs of consecutive topics sharing an RF. Order is
        # the CLI topic order either way, so the Context evolves exactly as
        # in the serial loop.
        if items and getattr(self.solver, "supports_mixed_rf", False):
            # Keyword only when there is something to forward: a third-party
            # mixed-RF backend predating the parameter must keep working
            # unchanged (the contract above).
            kwargs = {} if preencoded is None else {"preencoded": preencoded}
            try:
                return list(
                    assign_many(
                        items, rack_assignment, set(brokers), rfs,
                        self.context, **kwargs,
                    )
                )
            except Exception as e:
                if not self._should_fallback(e):
                    raise
                return self._fallback_group(
                    items, rfs, rack_assignment, brokers, e
                )
        i = 0
        while i < len(items):
            j = i
            while j < len(items) and rfs[j] == rfs[i]:
                j += 1
            try:
                solved = list(
                    assign_many(
                        items[i:j], rack_assignment, set(brokers), rfs[i],
                        self.context,
                    )
                )
            except Exception as e:
                if not self._should_fallback(e):
                    raise
                solved = self._fallback_group(
                    items[i:j], rfs[i:j], rack_assignment, brokers, e
                )
            out.extend(solved)
            i = j
        return out
