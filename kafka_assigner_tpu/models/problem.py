"""Dense problem encoding: the bridge between the reference's map-of-lists
world (``Map<partition, List<brokerId>>``, ``KafkaAssignmentStrategy.java:40-43``)
and the index-space tensors the TPU solver operates on.

Everything downstream of this module works on int32 arrays over *index* space
(broker row 0..N-1, rack 0..R-1, partition row 0..P-1); ids appear only here.
Shapes are bucketed so XLA compiles one kernel per bucket instead of one per
topic: multiples of 8 on the partition/node axes (``_pad8``), exact replica
width, powers of two on the batch axis (``batch_bucket``).
"""
from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from typing import Dict, List, Mapping, Sequence, Set

import numpy as np

from ..solvers.base import Context
from ..utils.env import env_bool
from ..utils.javahash import java_string_hash


def _checked_jhash(topic: str) -> int:
    """abs(Java String.hashCode), rejecting the one pathological input the
    reference crashes on (Math.abs of Integer.MIN_VALUE stays negative ->
    negative array index); surfaced as a clear error at encode time."""
    h = java_string_hash(topic)
    if h == -(2**31):
        raise ValueError(
            f"topic {topic!r} hashes to Integer.MIN_VALUE; the reference "
            "tool crashes on this input (negative array index)"
        )
    return abs(h)


def _hostcodec():
    """The C boundary codec (``native/hostcodec.c``), or None when disabled
    (``KA_HOSTCODEC=0``) or unbuildable — the numpy paths below are the
    always-available reference implementation (differential-tested equal)."""
    if not env_bool("KA_HOSTCODEC"):
        return None
    try:
        from ..native.build import load_hostcodec

        return load_hostcodec()
    except Exception:
        return None


def _next_bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _pad8(n: int, floor: int = 8) -> int:
    """Round up to a multiple of 8 (min ``floor``). Used for the partition
    and node axes, where power-of-2 bucketing wasted up to ~40% of every
    tensor op (e.g. 5100 brokers → 8192, 100 partitions → 128) — measured
    ~25% of the headline solve phase. Multiples of 8 keep the leadership
    chunk tiling (p_pad % 8 == 0) and the ≤8-way partition-axis sharding
    divisibility, while cutting the padding waste to <8 rows. Recompiles
    happen per distinct padded shape; within a run every topic group shares
    one shape, so only cross-run cluster-size changes pay them."""
    return max(floor, (n + 7) // 8 * 8)


def group_pads(currents: Sequence[Mapping[int, Sequence[int]]]) -> tuple:
    """(p_pad, width) bucket covering a whole topic group, using the same
    bucketing rules as :func:`encode_problem` so group overrides are correct
    by construction."""
    p_pad = max((_pad8(len(cur)) for cur in currents), default=8)
    # Exact width (min 2): the slot unroll in sticky_fill pays full op cost
    # per slot, and the power-of-2 bucket made RF=3 clusters pay a 4th,
    # always-empty slot (+33% sticky).
    width = max(
        (max((len(r) for r in cur.values()), default=1) for cur in currents),
        default=2,
    )
    return p_pad, max(width, 2)


def batch_bucket(b: int) -> int:
    """Bucket for the batch (topic-count) axis: scans are compiled per batch
    shape, so topic-count changes must not trigger recompiles. Padding topics
    are inert (p_real == 0)."""
    return _next_bucket(b, floor=1)


@dataclass
class ClusterEncoding:
    """Broker/rack canonicalization shared by every topic in a run (the
    reference re-derives none of this per topic either — one broker set per
    process, ``KafkaAssignmentGenerator.java:137-151``)."""

    broker_ids: np.ndarray      # (N,) int64 ascending
    rack_idx: np.ndarray        # (N_pad,) int32
    broker_to_idx: Dict[int, int]
    n: int
    n_pad: int
    n_racks: int                # distinct racks among the real brokers


def encode_cluster(
    rack_assignment: Mapping[int, str], nodes: Set[int]
) -> ClusterEncoding:
    """Factorize the broker set + rack map once for a whole multi-topic run."""
    broker_ids = np.array(sorted(nodes), dtype=np.int64)
    n = len(broker_ids)
    n_pad = _pad8(n)
    uniq: Dict[str, int] = {}
    rack_idx = np.empty(n_pad, dtype=np.int32)
    for i, b in enumerate(broker_ids):
        name = rack_assignment.get(int(b))
        if name is None:
            # A rackless node's rack id is its id string
            # (KafkaAssignmentStrategy.java:82-86), collisions included.
            name = str(int(b))
        rack_idx[i] = uniq.setdefault(name, len(uniq))
    for i in range(n, n_pad):
        rack_idx[i] = len(uniq) + (i - n)
    return ClusterEncoding(
        broker_ids=broker_ids,
        rack_idx=rack_idx,
        broker_to_idx={int(b): i for i, b in enumerate(broker_ids)},
        n=n,
        n_pad=n_pad,
        n_racks=len(uniq),
    )


def rack_cap(n_racks: int) -> int:
    """Static rack-id bound for the wave bodies' per-rack tensors.

    floor=16: per-rack ops are trivial at this width, and a coarse bucket
    keeps r_cap out of the compile-cache key for virtually every rack-aware
    cluster (a tight bucket would recompile per rack-count)."""
    return _next_bucket(n_racks + 1, floor=16)


@dataclass
class ProblemEncoding:
    """One topic's assignment problem, canonicalized to dense index space."""

    topic: str
    broker_ids: np.ndarray      # (N,) int64, ascending — index -> broker id
    partition_ids: np.ndarray   # (P,) int64, ascending — row -> partition id
    rack_idx: np.ndarray        # (N_pad,) int32; rack index per node, unique for padded rows
    current: np.ndarray         # (P_pad, L) int32; broker *index* or -1 (dead/absent).
                                # From encode_topic_group this is a VIEW into the
                                # shared (B_pad, P_pad, L) batch array (sibling
                                # encodings alias it) — treat as read-only; copy
                                # before mutating.
    rf: int                     # replication factor to assign
    jhash: int                  # abs(java hash); drives the topic rotation start
                                # abs(hash) % N (KafkaAssignmentStrategy.java:188-200)
                                # and the per-slot leadership tie-breaks — the
                                # solvers derive cap/start from it on device
    n: int                      # real node count (N)
    p: int                      # real partition count (P)
    n_pad: int
    p_pad: int
    r_cap: int | None = None    # static rack-id bound: bucket over the real
                                # rack count (+1 sentinel). The wave bodies
                                # size every per-rack tensor by it (~16 for a
                                # 10-rack cluster instead of the 2*n_pad
                                # worst case); padded node rows whose encoded
                                # rack ids exceed it are never read by the
                                # solve (only rows reachable from a real
                                # broker index are).


def encode_problem(
    topic: str,
    current_assignment: Mapping[int, Sequence[int]],
    rack_assignment: Mapping[int, str],
    nodes: Set[int],
    partitions: Set[int],
    replication_factor: int,
    p_pad_override: int | None = None,
    width_override: int | None = None,
    cluster: ClusterEncoding | None = None,
) -> ProblemEncoding:
    """Canonicalize one topic. ``p_pad_override``/``width_override`` let the
    batched solver pad a whole topic group to one common shape; ``cluster``
    reuses a shared broker/rack encoding across topics (empty-string rack
    names are real racks, not "no rack")."""
    if cluster is None:
        cluster = encode_cluster(rack_assignment, nodes)
    broker_ids = cluster.broker_ids
    rack_idx = cluster.rack_idx
    broker_to_idx = cluster.broker_to_idx
    n, n_pad = cluster.n, cluster.n_pad
    spids = sorted(partitions)  # python ints: cheap dict keys below
    partition_ids = np.array(spids, dtype=np.int64)
    p = len(partition_ids)
    p_pad = p_pad_override if p_pad_override is not None else _pad8(p)
    if p_pad < p:
        raise ValueError(f"p_pad_override {p_pad} < partition count {p}")
    lengths = {len(r) for r in current_assignment.values()}
    # Exact width, min 2 (see group_pads): sticky's slot unroll pays full op
    # cost per column, so padding columns are not free.
    width = (
        width_override
        if width_override is not None
        else max(max(lengths, default=0), 2)
    )
    if lengths and max(lengths) > width:
        raise ValueError(f"width_override {width} < max replica-list length")
    current = np.full((p_pad, width), -1, dtype=np.int32)
    uniform = (
        n > 0
        and len(lengths) == 1
        and next(iter(lengths)) > 0
        # The fast path indexes current_assignment by every partition id, so
        # partitions with no current assignment (fresh rows, left -1) must go
        # through the general path. When the caller passed the assignment's
        # own key set (the normal case), equality is a C-speed set compare;
        # only mismatched key sets pay the per-id membership scan.
        and (
            partitions == current_assignment.keys()
            or all(pid in current_assignment for pid in spids)
        )
    )
    if uniform and p > 0:
        # Uniform replica-list length (the overwhelmingly common case):
        # vectorized id -> index mapping via searchsorted over the sorted
        # broker ids instead of per-element dict lookups — at 200k partitions
        # this is milliseconds of host time instead of seconds. Ids not in
        # the live set (dead brokers) map to -1, same as the dict path.
        length = next(iter(lengths))
        ids = np.array(
            [current_assignment[pid] for pid in spids],
            dtype=np.int64,
        )
        idx = np.searchsorted(broker_ids, ids).clip(0, max(n - 1, 0))
        found = broker_ids[idx] == ids
        current[:p, :length] = np.where(found, idx, -1).astype(np.int32)
    else:
        part_to_row = {int(pid): i for i, pid in enumerate(partition_ids)}
        for pid, replicas in current_assignment.items():
            row = part_to_row.get(int(pid))
            if row is None:
                continue  # L2 guarantees key equality; tolerate extras defensively
            for s, b in enumerate(replicas):
                current[row, s] = broker_to_idx.get(int(b), -1)

    jhash = _checked_jhash(topic)
    return ProblemEncoding(
        topic=topic,
        broker_ids=broker_ids,
        partition_ids=partition_ids,
        rack_idx=rack_idx,
        current=current,
        rf=replication_factor,
        jhash=jhash,
        n=n,
        p=p,
        n_pad=n_pad,
        p_pad=p_pad,
        r_cap=rack_cap(cluster.n_racks),
    )


def encode_topic_group(
    named_currents: Sequence[tuple],  # [(topic, {pid: [broker_id, ...]}), ...]
    rack_assignment: Mapping[int, str],
    nodes: Set[int],
    rfs: int | Sequence[int],
    cluster: ClusterEncoding | None = None,
) -> tuple:
    """One-pass batched encode of a topic group: the fused equivalent of
    ``group_pads`` + per-topic :func:`encode_problem` + the caller's stacking
    loop. Returns ``(encs, currents (B_pad, P_pad, W) int32, jhashes (B_pad,),
    p_reals (B_pad,))`` with the batch axis bucketed (padding topics inert).

    Why it exists: at the 2000-topic headline, ``group_pads`` re-scans every
    replica list (200k ``len`` calls) only to compute two bucket sizes, and
    each ``encode_problem`` pays its own ``np.array`` + ``searchsorted`` —
    ~40% of the warm critical path was host encode overhead. Here every
    topic's replica lists convert to one ndarray each (the same single C call
    also detects raggedness), the id→index mapping is ONE ``searchsorted``
    over the concatenation, and the group buckets come from the per-topic
    shapes already in hand. Semantics are identical to the per-topic path
    (dead brokers → -1, Integer.MIN_VALUE hash rejection, ragged lists via
    the general fill).
    """
    if cluster is None:
        cluster = encode_cluster(rack_assignment, nodes)
    broker_ids = cluster.broker_ids
    n = cluster.n
    if isinstance(rfs, int):
        rfs = [rfs] * len(named_currents)
    elif len(rfs := list(rfs)) != len(named_currents):
        # zip truncation would silently drop the trailing topics from the
        # solve (their batch rows would stay inert) — fail loudly instead.
        raise ValueError(
            f"rfs has {len(rfs)} entries for {len(named_currents)} topics"
        )

    codec = _hostcodec()
    if codec is not None and all(
        isinstance(c, dict) for _, c in named_currents
    ):
        # The C codec walks real dicts (PyDict API); non-dict Mappings
        # (MappingProxyType, ChainMap, ...) take the numpy path below so the
        # accepted input types don't depend on toolchain availability.
        return _encode_topic_group_codec(codec, named_currents, rfs, cluster)

    per = []  # (topic, spids(np), ids(ndarray)|None, cur, jhash)
    max_p, max_w = 0, 1
    for topic, cur in named_currents:
        jh_abs = _checked_jhash(topic)
        spids = sorted(cur)
        ids = None
        width = 0
        if spids and n > 0:
            try:
                ids = np.asarray([cur[p] for p in spids], dtype=np.int64)
                if ids.ndim != 2:
                    ids = None
            except (ValueError, TypeError):
                ids = None  # ragged replica lists: general fill below
        if ids is not None:
            width = ids.shape[1]
        elif spids:
            width = max((len(cur[p]) for p in spids), default=0)
        max_p = max(max_p, len(spids))
        max_w = max(max_w, width)
        per.append((topic, spids, ids, cur, jh_abs))

    p_pad = _pad8(max_p)
    width = max(max_w, 2)
    b_pad = batch_bucket(len(per))
    currents = np.full((b_pad, p_pad, width), -1, dtype=np.int32)
    jhashes = np.zeros(b_pad, dtype=np.int32)
    p_reals = np.zeros(b_pad, dtype=np.int32)

    # One id→index mapping for every uniform topic at once.
    flats = [ids.ravel() for _, _, ids, _, _ in per if ids is not None]
    if flats:
        all_ids = np.concatenate(flats) if len(flats) > 1 else flats[0]
        idx = np.searchsorted(broker_ids, all_ids).clip(0, max(n - 1, 0))
        mapped = np.where(broker_ids[idx] == all_ids, idx, -1).astype(np.int32)
    off = 0
    encs = []
    for i, ((topic, spids, ids, cur, jh), rf) in enumerate(zip(per, rfs)):
        p = len(spids)
        if ids is not None:
            size = ids.size
            currents[i, :p, : ids.shape[1]] = mapped[off : off + size].reshape(
                ids.shape
            )
            off += size
        elif p:
            b2i = cluster.broker_to_idx
            for row, pid in enumerate(spids):
                for s, b in enumerate(cur[pid]):
                    currents[i, row, s] = b2i.get(int(b), -1)
        jhashes[i] = jh
        p_reals[i] = p
        encs.append(
            ProblemEncoding(
                topic=topic,
                broker_ids=broker_ids,
                partition_ids=np.asarray(spids, dtype=np.int64),
                rack_idx=cluster.rack_idx,
                current=currents[i],
                rf=rf,
                jhash=jh,
                n=n,
                p=p,
                n_pad=cluster.n_pad,
                p_pad=p_pad,
                r_cap=rack_cap(cluster.n_racks),
            )
        )
    return encs, currents, jhashes, p_reals


class GroupEncodeAccumulator:
    """Incremental :func:`encode_topic_group`: feed topic chunks as they
    arrive (the streaming ZooKeeper ingest, ``generator.py``), then
    :meth:`finish` into the exact arrays the one-shot group encode would
    have produced.

    Why chunking is safe: the group-wide buckets are maxima of per-topic
    shapes (``p_pad = _pad8(max p)``, ``width = max(w, 2)``,
    ``b_pad = batch_bucket(B)``), and the encoded *values* — the id→index
    mapping, jhashes, p_reals — never depend on which other topics share the
    batch. So each chunk encodes with its own (smaller) buckets while later
    responses are still in flight — that is the expensive dict-walking /
    ``searchsorted`` work — and ``finish`` only block-copies the chunk slabs
    into the final group-bucketed arrays: byte-identical to the one-shot
    encode by construction (test-pinned, any chunk size).

    Replication factors are usually not known until the whole topic list is
    in hand (RF inference is L2's job, after ingest); chunks encode with a
    placeholder ``rf`` and the consumer rewrites it on the finished
    encodings (``dataclasses.replace``) — ``rf`` is carried metadata, not an
    input to the array encode.
    """

    def __init__(
        self, rack_assignment: Mapping[int, str], nodes: Set[int]
    ) -> None:
        self.cluster = encode_cluster(rack_assignment, nodes)
        self._chunks: List[tuple] = []  # (encs, currents, jhashes, p_reals)
        self._total = 0
        self.encode_ms = 0.0  # host time spent in add() — the overlap numerator
        # Delta store (the daemon's incremental re-encode, ISSUE 8): one
        # entry per LIVE topic, each trimmed to the topic's OWN buckets so
        # a later merge() computes group buckets from real shapes — a big
        # topic that has since been deleted can never inflate them.
        self._delta: Dict[str, tuple] = {}  # topic -> (enc, cur2d, jh, p)

    def add(self, named_currents: Sequence[tuple], rfs: int = 0) -> None:
        """Encode one chunk of ``(topic, current_assignment)`` pairs (in
        stream order) against the shared cluster encoding."""
        if not named_currents:
            return
        t0 = time.perf_counter()
        out = encode_topic_group(
            named_currents, {}, set(), [rfs] * len(named_currents),
            cluster=self.cluster,
        )
        self._chunks.append(out)
        self._total += len(named_currents)
        self.encode_ms += (time.perf_counter() - t0) * 1000.0

    def peek_shape(self) -> tuple | None:
        """(p_pad, width) bucket maxima over the chunks encoded SO FAR, or
        None before any chunk arrived — the partial-metadata signal the
        ingest warm-up predicts the solve's program signature from
        (``solvers/warmup.py``). Later chunks can only grow these maxima."""
        if not self._chunks:
            return None
        return (
            max(c[1].shape[1] for c in self._chunks),
            max(c[1].shape[2] for c in self._chunks),
        )

    def finish(self) -> tuple:
        """Merge the chunk slabs into group-wide buckets; returns the same
        ``(encs, currents, jhashes, p_reals)`` tuple as one-shot
        :func:`encode_topic_group` over the concatenated chunks."""
        if not self._chunks:
            return (
                [],
                np.full((1, 8, 2), -1, dtype=np.int32),
                np.zeros(1, dtype=np.int32),
                np.zeros(1, dtype=np.int32),
            )
        p_pad = max(c[1].shape[1] for c in self._chunks)
        width = max(c[1].shape[2] for c in self._chunks)
        b_pad = batch_bucket(self._total)
        currents = np.full((b_pad, p_pad, width), -1, dtype=np.int32)
        jhashes = np.zeros(b_pad, dtype=np.int32)
        p_reals = np.zeros(b_pad, dtype=np.int32)
        encs: List[ProblemEncoding] = []
        i = 0
        for cencs, ccur, cjh, cpr in self._chunks:
            b = len(cencs)
            currents[i:i + b, : ccur.shape[1], : ccur.shape[2]] = ccur[:b]
            jhashes[i:i + b] = cjh[:b]
            p_reals[i:i + b] = cpr[:b]
            for k, e in enumerate(cencs):
                encs.append(
                    dataclasses.replace(
                        e, current=currents[i + k], p_pad=p_pad
                    )
                )
            i += b
        self._chunks = []
        return encs, currents, jhashes, p_reals

    # -- delta API (watch-driven incremental re-encode, ISSUE 8) -----------

    def update_topics(
        self, named_currents: Sequence[tuple], rfs: int = 0
    ) -> int:
        """(Re-)encode the given topics into the delta store — the touched
        set of one churn event (topic created, partitions reassigned/grown),
        batched through :func:`encode_topic_group` like a streamed chunk.
        Each topic's slab is then trimmed to its OWN buckets
        (``_pad8(p)`` x ``max(width, 2)``), so :meth:`merge` recovers
        exactly the group buckets a from-scratch encode of the final state
        would compute, no matter which topics shared a chunk or have since
        been deleted. Replaces any prior entry per topic (last write wins).
        Returns the number of topics (re-)encoded."""
        if not named_currents:
            return 0
        t0 = time.perf_counter()
        encs, currents, _jh, _pr = encode_topic_group(
            named_currents, {}, set(), [rfs] * len(named_currents),
            cluster=self.cluster,
        )
        for i, (topic, cur) in enumerate(named_currents):
            enc = encs[i]
            own_p_pad = _pad8(enc.p)
            own_width = max(
                max((len(r) for r in cur.values()), default=0), 2
            )
            trimmed = np.array(
                currents[i][:own_p_pad, :own_width], copy=True
            )
            self._delta[topic] = (
                dataclasses.replace(enc, current=trimmed, p_pad=own_p_pad),
                trimmed,
                enc.jhash,
                enc.p,
            )
        self.encode_ms += (time.perf_counter() - t0) * 1000.0
        return len(named_currents)

    def delete_topic(self, topic: str) -> bool:
        """Drop one topic from the delta store (topic deleted on the
        cluster). Returns whether it was present."""
        return self._delta.pop(topic, None) is not None

    def delta_topics(self) -> List[str]:
        """The topics currently in the delta store, insertion-ordered."""
        return list(self._delta)

    def delta_shape(self) -> tuple | None:
        """(p_pad, width) bucket maxima over the delta store's LIVE topics
        — what a ``merge`` over all of them would bucket to — or ``None``
        when the store is empty. The delta twin of :meth:`peek_shape` (the
        daemon's warm-signature input)."""
        if not self._delta:
            return None
        shapes = [cur.shape for _, cur, _, _ in self._delta.values()]
        return (max(s[0] for s in shapes), max(s[1] for s in shapes))

    def merge(self, topic_order: Sequence[str]) -> tuple:
        """Assemble the delta store into group-bucketed arrays for
        ``topic_order`` — the same ``(encs, currents, jhashes, p_reals)``
        tuple (and the same BYTES, test-pinned under randomized churn) as
        one-shot :func:`encode_topic_group` over the final state in that
        order. Non-destructive: the store keeps serving later merges.
        Unknown topics raise ``KeyError`` — the daemon resyncs rather than
        plan against a topic it never encoded."""
        entries = []
        for t in topic_order:
            try:
                entries.append(self._delta[t])
            except KeyError:
                raise KeyError(
                    f"topic {t!r} is not in the delta encode store"
                ) from None
        if not entries:
            return (
                [],
                np.full((1, 8, 2), -1, dtype=np.int32),
                np.zeros(1, dtype=np.int32),
                np.zeros(1, dtype=np.int32),
            )
        p_pad = max(cur.shape[0] for _, cur, _, _ in entries)
        width = max(cur.shape[1] for _, cur, _, _ in entries)
        b_pad = batch_bucket(len(entries))
        currents = np.full((b_pad, p_pad, width), -1, dtype=np.int32)
        jhashes = np.zeros(b_pad, dtype=np.int32)
        p_reals = np.zeros(b_pad, dtype=np.int32)
        encs: List[ProblemEncoding] = []
        for i, (enc, cur, jh, p) in enumerate(entries):
            currents[i, : cur.shape[0], : cur.shape[1]] = cur
            jhashes[i] = jh
            p_reals[i] = p
            encs.append(
                dataclasses.replace(enc, current=currents[i], p_pad=p_pad)
            )
        return encs, currents, jhashes, p_reals


def _encode_topic_group_codec(codec, named_currents, rfs, cluster):
    """C-codec encode: identical outputs to the numpy body of
    :func:`encode_topic_group` (differential-tested in
    ``tests/test_hostcodec.py``), with the dict walking, key sorting,
    id→index mapping and row fills done in one C pass instead of ~200k
    small Python/numpy operations at headline scale."""
    n = cluster.n
    jh_list = [_checked_jhash(topic) for topic, _ in named_currents]
    curs = [cur for _, cur in named_currents]
    max_p, max_w = codec.scan_dims(curs)
    p_pad = _pad8(max_p)
    width = max(max_w, 2)
    b_pad = batch_bucket(len(curs))
    currents = np.full((b_pad, p_pad, width), -1, dtype=np.int32)
    jhashes = np.zeros(b_pad, dtype=np.int32)
    p_reals = np.zeros(b_pad, dtype=np.int32)
    part_ids = np.full((b_pad, p_pad), -1, dtype=np.int64)
    codec.encode_rows(
        curs, np.ascontiguousarray(cluster.broker_ids, dtype=np.int64),
        currents, p_reals, part_ids,
    )
    jhashes[: len(jh_list)] = jh_list
    encs = []
    for i, ((topic, _), rf) in enumerate(zip(named_currents, rfs)):
        p = int(p_reals[i])
        encs.append(
            ProblemEncoding(
                topic=topic,
                broker_ids=cluster.broker_ids,
                partition_ids=part_ids[i, :p],
                rack_idx=cluster.rack_idx,
                current=currents[i],
                rf=rf,
                jhash=jh_list[i],
                n=n,
                p=p,
                n_pad=cluster.n_pad,
                p_pad=p_pad,
                r_cap=rack_cap(cluster.n_racks),
            )
        )
    return encs, currents, jhashes, p_reals


def decode_assignment(
    enc: ProblemEncoding, ordered: np.ndarray
) -> Dict[int, List[int]]:
    """(P_pad, RF) broker-index matrix -> {partition_id: [broker_id, ...]}."""
    rows = np.asarray(ordered[: enc.p])
    if rows.size and (rows >= 0).all():
        # Complete solve (the normal case): one vectorized gather, then bulk
        # int conversion via tolist().
        ids = enc.broker_ids[rows].tolist()
        return dict(zip(enc.partition_ids.tolist(), ids))
    out: Dict[int, List[int]] = {}
    for row in range(enc.p):
        ids = [int(enc.broker_ids[i]) for i in rows[row] if i >= 0]
        out[int(enc.partition_ids[row])] = ids
    return out


def decode_assignments_batched(
    encs: Sequence[ProblemEncoding], ordered: np.ndarray
) -> List[Dict[int, List[int]]]:
    """Batched :func:`decode_assignment`: one gather + one bulk int
    conversion over the whole (B, P_pad, RF) result instead of per-topic
    numpy round-trips — at 2000 headline topics this is ~3x less host time,
    which matters because host decode is on the critical path of every run
    (the device can't make it faster)."""
    if not encs:
        return []
    ordered = np.ascontiguousarray(ordered, dtype=np.int32)
    broker_ids = encs[0].broker_ids
    codec = _hostcodec()
    if codec is not None:
        part_ids = np.full(
            (len(encs), ordered.shape[1]), -1, dtype=np.int64
        )
        for i, e in enumerate(encs):
            part_ids[i, : e.p] = e.partition_ids
        p_reals32 = np.fromiter(
            (e.p for e in encs), dtype=np.int32, count=len(encs)
        )
        return codec.decode_rows(
            ordered[: len(encs)],
            np.ascontiguousarray(broker_ids, dtype=np.int64),
            part_ids, p_reals32, len(encs),
        )
    # Per-topic completeness over *real* rows and *this topic's* slots only
    # (padding rows are always -1, and in a mixed-RF batch a narrower
    # topic's trailing slots are legitimately -1): one vectorized pass
    # instead of 2000 per-topic reductions.
    p_reals = np.fromiter((e.p for e in encs), dtype=np.int64, count=len(encs))
    rfs = np.fromiter((e.rf for e in encs), dtype=np.int64, count=len(encs))
    valid = np.arange(ordered.shape[1])[None, :] < p_reals[:, None]
    slot_ok = np.arange(ordered.shape[2])[None, None, :] < rfs[:, None, None]
    incomplete = (
        (ordered < 0) & valid[:, :, None] & slot_ok
    ).any(axis=(1, 2))
    # Bulk tolist per distinct RF so narrow topics' lists carry exactly
    # their own rf entries (one group in the uniform-RF common case).
    lists_by_topic: Dict[int, list] = {}
    for r in np.unique(rfs):
        idx = np.where(rfs == r)[0]
        sub = broker_ids[np.maximum(ordered[idx][:, :, :r], 0)].tolist()
        for k, i in enumerate(idx):
            lists_by_topic[int(i)] = sub[k]
    out: List[Dict[int, List[int]]] = []
    for i, enc in enumerate(encs):
        if not incomplete[i] and enc.p:
            out.append(
                dict(
                    zip(enc.partition_ids.tolist(), lists_by_topic[i][: enc.p])
                )
            )
        else:
            out.append(decode_assignment(enc, ordered[i]))
    return out


def context_to_array(ctx: Context, enc: ProblemEncoding) -> np.ndarray:
    """Materialize the cross-topic leadership counters
    (``KafkaAssignmentStrategy.java:360-369``) as a dense (N_pad, RF) slab for
    the solve; slots beyond RF stay in the dict untouched."""
    # The on-device leadership key is ``count * m + rotated_pos`` (m <= RF)
    # sharing int32 space with the BIG taken/padded sentinel
    # (ops/assignment.py:leadership_order). Counters persisted across runs via
    # --leadership_context grow unboundedly; past the key space a taken
    # candidate could win the argmin, silently corrupting preference order —
    # so refuse at encode time. Counters also grow DURING the run (one
    # increment per placed replica), so reserve headroom of 2^24 (~16.7M
    # placements — two orders of magnitude beyond the 200k-partition headline)
    # on top of the hard bound.
    limit = (0x3FFFFFFF - enc.rf) // max(enc.rf, 1) - (1 << 24)
    counters = np.zeros((enc.n_pad, enc.rf), dtype=np.int32)
    for i, b in enumerate(enc.broker_ids):
        per_node = ctx.counter.get(int(b))
        if per_node:
            for slot in range(enc.rf):
                c = per_node.get(slot, 0)
                if c > limit:
                    raise ValueError(
                        f"leadership counter for broker {int(b)} slot {slot} "
                        f"({c}) exceeds the solver's key space ({limit}); the "
                        "persisted --leadership_context has grown too large — "
                        "start from a fresh context"
                    )
                counters[i, slot] = c
    return counters


def apply_counter_updates(
    ctx: Context, enc: ProblemEncoding, before: np.ndarray, after: np.ndarray
) -> None:
    """Fold the solve's counter increments back into the shared Context."""
    delta = np.asarray(after, dtype=np.int64) - np.asarray(before, dtype=np.int64)
    for i, b in enumerate(enc.broker_ids):
        for slot in range(enc.rf):
            d = int(delta[i, slot])
            if d:
                node = ctx.counter.setdefault(int(b), {})
                node[slot] = node.get(slot, 0) + d
