from .problem import ProblemEncoding, encode_problem, decode_assignment

__all__ = ["ProblemEncoding", "encode_problem", "decode_assignment"]
