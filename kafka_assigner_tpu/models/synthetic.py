"""Synthetic benchmark clusters (BASELINE.md configs).

Rack-striped steady-state clusters: every partition's RF replicas sit on
consecutive entries of a rack-interleaved broker list, so replicas are
rack-diverse and per-node load is balanced — the state a healthy cluster
converges to, and the honest starting point for replacement/decommission
benchmarks (movement then measures the *change*, not pre-existing skew).
"""
from __future__ import annotations

from typing import Dict, List, Set, Tuple


def rack_striped_cluster(
    n_brokers: int,
    n_topics: int,
    p_per_topic: int,
    rf: int,
    n_racks: int,
    name_fmt: str = "topic-{:03d}",
    extra_brokers: int = 0,
) -> Tuple[Dict[str, Dict[int, List[int]]], Set[int], Dict[int, str]]:
    """Return (topics, live_brokers, rack_map) in steady state.

    ``extra_brokers``: additional broker ids (``n_brokers..n_brokers+extra-1``)
    included in the rack map (same striping formula) but not in the live set
    or any replica list — replacement brokers for swap scenarios."""
    racks = {b: f"rack{b % n_racks}" for b in range(n_brokers + extra_brokers)}
    by_rack: Dict[int, List[int]] = {}
    for b in range(n_brokers):
        by_rack.setdefault(b % n_racks, []).append(b)
    inter = [
        by_rack[r][d]
        for d in range((n_brokers + n_racks - 1) // n_racks)
        for r in range(n_racks)
        if d < len(by_rack[r])
    ]
    topics: Dict[str, Dict[int, List[int]]] = {}
    for t in range(n_topics):
        base = t * 131
        topics[name_fmt.format(t)] = {
            p: [inter[(base + p * rf + i) % n_brokers] for i in range(rf)]
            for p in range(p_per_topic)
        }
    return topics, set(range(n_brokers)), racks


def build_config5():
    """BASELINE config 5: 1k brokers / 100 topics x 50 partitions / RF=3 /
    10 racks — the 256-scenario what-if fleet shape."""
    return rack_striped_cluster(1000, 100, 50, 3, 10)
