"""One atomic-write discipline for every crash-safety-critical file.

The journal, the snapshot backend's persisted cluster state, and the
program store all depend on the same property: a reader can NEVER observe
a torn file, only the state before or after a write. The recipe is
same-directory mkstemp (so the final rename never crosses a filesystem),
write + flush + fsync (the rename must not land before the bytes do), then
``os.replace``, with the temp file unlinked on any failure. Centralized
here so the fsync subtlety cannot silently diverge between copies.
"""
from __future__ import annotations

import os
import tempfile


def atomic_write_text(path: str, text: str, *, prefix: str = ".ka_") -> None:
    """Atomically replace ``path`` with ``text`` (UTF-8)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=prefix, suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:  # kalint: disable=KA008 -- cleanup of a temp file that may already be gone
            pass
        raise
