"""Logging/observability: structured diagnostics on stderr, machine-parseable
JSON alone on stdout.

The reference achieves the stdout/stderr separation by configuring log4j to
ERROR-only console output and silencing the ZK/Kafka client loggers
(``src/main/config/log4j.properties:21-31``). Here stdout is reserved for
payload JSON by construction; diagnostics go to a stderr logger whose level
is controlled by ``KA_LOG`` (default ERROR, same posture as the reference).
"""
from __future__ import annotations

import logging
import sys

from .env import env_choice

_LOGGER_NAME = "kafka_assigner_tpu"


def get_logger(child: str | None = None) -> logging.Logger:
    root = logging.getLogger(_LOGGER_NAME)
    if not root.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
        root.addHandler(handler)
        # env_choice folds case and falls back loudly on an unknown level
        # (the raw .upper()+setLevel it replaces crashed on garbage).
        root.setLevel(env_choice("KA_LOG"))
        root.propagate = False
    return root.getChild(child) if child else root
