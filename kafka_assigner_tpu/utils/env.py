"""Shared parsing for integer environment knobs (``KA_LEADER_CHUNK``,
``KA_DENSE_MASK_BUDGET``, ...): invalid values are ignored LOUDLY on stderr
— the house rule for every tuning knob (mis-set knobs must never silently
change the measured configuration)."""
from __future__ import annotations

import os
import sys


def env_int(name: str, default: int | None = None, floor: int = 1):
    """``int(os.environ[name])`` clamped to ``floor``; ``default`` when the
    variable is unset or non-integer (the latter with a stderr warning)."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return max(floor, int(raw))
    except ValueError:
        print(
            f"kafka-assigner: ignoring non-integer {name}={raw!r}",
            file=sys.stderr,
        )
        return default
