"""The declarative ``KA_*`` knob registry and its typed accessors.

Every tuning knob the package reads is declared here exactly once — name,
type, default, floor, choices, and a one-line effect doc — and read only
through the typed accessors (:func:`env_int`, :func:`env_float`,
:func:`env_bool`, :func:`env_choice`, :func:`env_str`). All accessors follow
the house rule for tuning knobs: **mis-set knobs must never silently change
the measured configuration** — an unparsable or unknown value is ignored
LOUDLY on stderr and the declared default is used instead.

Boolean truthiness convention (normalized by :func:`env_bool`): ``1``,
``true``, ``yes``, ``on`` are true; ``0``, ``false``, ``no``, ``off`` are
false (case-insensitive); unset or empty means the declared default; anything
else warns and falls back to the default. ``KA_FOO=1`` / ``KA_FOO=0`` remain
the canonical spellings used in docs.

The registry is machine-checked by the project linter
(``kafka_assigner_tpu/analysis/kalint/``): raw ``os.environ`` access to a
``KA_*`` name anywhere outside this module is rule KA001, an unregistered
``KA_*`` literal is KA003, and a registered knob missing from the README
knob table is KA004. The README table itself is generated from this registry
(``python -m kafka_assigner_tpu.analysis.knobdoc --write``).
"""
from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Any, Tuple


@dataclass(frozen=True)
class Knob:
    """One declared tuning knob.

    ``default_doc`` overrides how the default renders in the generated README
    table (for knobs whose effective default is computed at runtime);
    ``internal`` marks process-internal handshake variables operators should
    not set by hand (still registered so KA003/KA004 cover them).
    """

    name: str
    type: str                            # "int" | "float" | "bool" | "choice" | "str"
    default: Any
    floor: Any = None                    # numeric clamp (min), None = unclamped
    choices: Tuple[str, ...] | None = None
    doc: str = ""
    default_doc: str | None = None
    internal: bool = False


#: Declaration order is preserved and becomes the README table order.
KNOBS: "dict[str, Knob]" = {}


def _knob(
    name: str,
    type_: str,
    default: Any,
    *,
    floor: Any = None,
    choices: Tuple[str, ...] | None = None,
    doc: str = "",
    default_doc: str | None = None,
    internal: bool = False,
) -> None:
    KNOBS[name] = Knob(
        name, type_, default, floor, choices, doc, default_doc, internal
    )


# --- solver tuning ---------------------------------------------------------
_knob(
    "KA_WAVE_MODE", "choice", None,
    default_doc="auto (seq under RF-decrease compat)",
    doc="which orphan-spread fallback chain the batched solve compiles "
        "(`auto`, `fast_balance`, `fast_dense`, ..., validated against "
        "`ops/assignment.py:WAVE_MODES` at the call site). Chains starting "
        "with the fast leg emit identical output on every instance the fast "
        "leg solves; shorter chains compile fewer `while_loop` bodies — "
        "compile time is a first-class cost when the accelerator compiles "
        "remotely. Under `KA_RF_DECREASE_COMPAT=1` the default chain is the "
        "reference-verbatim `seq` leg (byte-parity on orphaned RF decreases)",
)
_knob(
    "KA_LEADER_CHUNK", "int", None, floor=1, default_doc="8 (kernel default)",
    doc="partitions per leadership scan step (static unroll). Chunk-invariant "
        "semantics (test-pinned); trades scan-step count against "
        "compiled-code size",
)
_knob(
    "KA_PLACE_MODE", "choice", "scan", choices=("scan", "vmap"),
    doc="batched placement stage: `scan` serializes topics through the full "
        "fallback chain (total work bounds wall clock — the host-CPU trade); "
        "`vmap` batches the single-leg fast wave across topics and rescues "
        "stranded topics through the scan chain (trip count bounds wall "
        "clock — the on-chip trade). Byte-identical output either way "
        "(`tests/test_place_vmap.py`)",
)
_knob(
    "KA_PLACE_CHUNK", "int", 256, floor=1,
    doc="topics per vmapped placement block under `KA_PLACE_MODE=vmap` "
        "(memory bound; the default keeps live wave state in the low "
        "hundreds of MB at the headline bucket)",
)
_knob(
    "KA_RF_DECREASE_COMPAT", "bool", False,
    doc="reference bug-compat RF decrease: sticky fill retains every current "
        "replica passing the node/rack/capacity gates with no per-partition "
        "RF bound (`KafkaAssignmentStrategy.java:320-324`), emitting the "
        "reference's non-uniform replica lists. ALL THREE backends are then "
        "byte-equal with the greedy oracle on every input class; default "
        "(off) clamps to the requested RF "
        "(`tests/test_rf_decrease_compat.py`)",
)
_knob(
    "KA_PALLAS_LEADERSHIP", "bool", False,
    doc="leadership ordering via the Pallas VMEM kernel instead of the "
        "chunked `lax.scan` (`ops/pallas_leadership.py`). Hardware-validated "
        "on a v5e: bit-identical and 3.3x faster than the XLA scan at a "
        "200k-partition topic, but 170x slower than the default host-C++ "
        "pass (`PALLAS_POSTHUMOUS_r05.json`) — useful only where leadership "
        "must stay on device; overrides `KA_LEADERSHIP=native` loudly",
)
_knob(
    "KA_LEADERSHIP", "choice", "auto", choices=("auto", "native", "device"),
    doc="where the sequential leadership-ordering pass runs (`auto` = host "
        "C++ `native/leadership.py` when buildable — the per-partition "
        "counter chain is ~ns/step scalar code vs ~us/step as an XLA scan; "
        "`device` restores the on-device scan, which jit-internal consumers "
        "like the what-if sweep always use)",
)
_knob(
    "KA_DENSE_MASK_BUDGET", "int", 1 << 27, floor=1,
    doc="the static giant-shape gate (P_pad x N_pad elements) that demotes "
        "the dense wave leg, slot-packs the fast waves, and inserts the "
        "`balance_quota` hybrid before every node-per-wave balance leg. Read "
        "at trace time (a mid-process change needs `jax.clear_caches()`); "
        "tests use it to pin the giant-chain machinery on small instances "
        "(`tests/test_wave_boundaries.py`)",
)
_knob(
    "KA_QUOTA_WAVE_TARGET", "int", 4, floor=1,
    doc="the `balance_quota` hybrid's per-node per-wave drain divisor "
        "`ceil(headroom/T)`. The default is the measured optimum of a "
        "seven-candidate matrix on the saturated showcase "
        "(`QUOTA_TUNING_r05.json` via `scripts/tune_quota_knobs.py`) — a "
        "measurement knob, not a tuning suggestion. Trace-time read like "
        "`KA_DENSE_MASK_BUDGET`",
)
_knob(
    "KA_QUOTA_ENDGAME", "int", 32, floor=1,
    doc="the `balance_quota` hybrid's endgame handoff: once every rack's "
        "headroom is at or below this, the proportional-quota drain hands "
        "over to the corner-free node-per-wave balance wave. Trace-time read "
        "like `KA_DENSE_MASK_BUDGET`",
)
_knob(
    "KA_WHATIF_INCREMENTAL", "bool", True,
    doc="the incremental what-if sweep (`parallel/whatif.py`: per scenario, "
        "only topics hosting removed brokers or failing the clean/capacity "
        "certificate are re-solved). Set to 0 to force the dense sweep, "
        "which remains the differential oracle",
)
_knob(
    "KA_WHATIF_MEMBUDGET", "int", 1 << 28, floor=1,
    doc="scenario-axis memory chunking for the dense what-if sweep: one "
        "dispatch's per-scenario solver state stays under this many int32 "
        "elements (default 2^28 = 1 GiB of int32)",
)
_knob(
    "KA_HOSTCODEC", "bool", True,
    doc="the C dict<->tensor boundary codec (`native/hostcodec.c`). Set to 0 "
        "to use the numpy reference encode/decode paths "
        "(differential-tested equal)",
)

# --- io / metadata backends ------------------------------------------------
_knob(
    "KA_ZK_CLIENT", "choice", "auto", choices=("auto", "kazoo", "wire"),
    doc="live-ZooKeeper client: `kazoo` when installed, else the in-tree "
        "minimal jute wire client (`io/zkwire.py`, read-only subset — no "
        "third-party dependency needed for live runs); "
        "`tests/test_zk_socket.py` smokes both against a real-TCP jute "
        "server",
)

_knob(
    "KA_ZK_PIPELINE", "int", 32, floor=1,
    doc="max in-flight pipelined requests per ZooKeeper session: the wire "
        "client's xid-matched `get_many`/`iter_get` window (`io/zkwire.py`) "
        "and the kazoo backend's async-handle window, so N metadata reads "
        "cost ~ceil(N/window) round-trips instead of N. `1` degrades to "
        "exactly the serial request/response behavior "
        "(`tests/test_zk_golden_frames.py` pins byte-identical decodes)",
)
_knob(
    "KA_ZK_CONNECT_RETRIES", "int", 3, floor=1,
    doc="connection passes over the shuffled ZooKeeper endpoint list before "
        "the wire client gives up (`zkwire.MiniZkClient.start`); exponential "
        "backoff between passes (0.1 s doubling, capped at 2 s), every "
        "failed pass warned on stderr",
)
_knob(
    "KA_ZK_SESSION_RETRIES", "int", 2, floor=0,
    doc="in-session re-establishment attempts when an open ZooKeeper "
        "session dies mid-read (socket drop, truncated/desynced frame, "
        "timeout): the wire client reconnects with jittered backoff and "
        "re-issues ONLY the unanswered reads (idempotent replay, "
        "byte-identical output — `io/zkwire.py`). 0 restores fail-fast; "
        "server-reported errors (NoNode) are never retried",
)
_knob(
    "KA_ZK_INGEST_CHUNK", "int", 64, floor=1,
    doc="topics per streamed host-encode chunk in the mode-3 ingest/encode "
        "overlap (`generator.py`): fetched topics fold into the batched "
        "encode in chunks of this size while later ZooKeeper responses are "
        "still in flight (chunk-size-invariant output by construction)",
)
_knob(
    "KA_ZK_OVERLAP", "bool", True,
    doc="overlap pipelined metadata ingest with host encode via the "
        "producer/consumer topic stream (`generator.py`); set to 0 to "
        "restore strictly sequential fetch-then-encode (byte-identical "
        "output either way, test-pinned)",
)

# --- robustness / fault injection -------------------------------------------
_knob(
    "KA_FAILURE_POLICY", "choice", "strict", choices=("strict", "best-effort"),
    doc="default `--failure-policy` for CLI runs (the flag overrides). "
        "`strict` aborts on the first unrecoverable ingest/solve failure "
        "(reference behavior); `best-effort` degrades gracefully: topics "
        "that vanish mid-scan are skipped (reported via "
        "`ingest.topics_skipped` + stderr), a crashed TPU solve falls back "
        "to the greedy solver (`solve.fallbacks`), and the run exits with "
        "the documented degraded-success code (see README \"Failure model\")",
)
_knob(
    "KA_FAULTS_SPEC", "str", None, default_doc="unset (no injection)",
    doc="fault-injection schedule for the harness in `faults/inject.py`: "
        "semicolon-separated `scope[@cluster]:index=kind[:arg]` events "
        "(scopes connect/handshake/reply/solve/warmup plus the write seams "
        "write/converge/wave, the daemon seams watch/session/resync/"
        "daemon/dispatch and the controller seams "
        "controller:{verdict-flap,exec-crash,regress}; kinds blackhole, "
        "expire, drop, trunc, slow, nonode, "
        "crash, lost, stall, solver-crash), or the word `random` for a "
        "seed-deterministic schedule (`KA_FAULTS_SEED`/`KA_FAULTS_RATE`). "
        "`@cluster` addresses one cluster of the multi-cluster daemon "
        "(e.g. `session@west:1=expire`), firing at that cluster's own "
        "per-scope index. Malformed specs are ignored loudly and "
        "injection stays off",
)
_knob(
    "KA_FAULTS_SEED", "int", 0,
    doc="seed for `KA_FAULTS_SPEC=random` schedules (same seed = identical "
        "schedule, byte-for-byte — the chaos soak's reproducibility handle)",
)
_knob(
    "KA_FAULTS_RATE", "float", 0.05, floor=0.0,
    doc="per-hook fault probability for `KA_FAULTS_SPEC=random` schedules "
        "(drawn over the first few dozen indexes of each scope; see "
        "`faults/inject.py:RANDOM_HORIZON`)",
)

# --- plan execution (ka-execute) ---------------------------------------------
_knob(
    "KA_EXEC_WAVE_SIZE", "int", 8, floor=1,
    doc="partition moves per execution wave (`exec/engine.py`): `ka-execute` "
        "submits the plan in waves of this many moves, awaiting ISR/URP "
        "convergence between waves — the reassignment throttle that keeps "
        "replication traffic bounded (the wave-sizing tradeoff of "
        "arXiv:1602.03770); the `--wave-size` flag overrides per run",
)
_knob(
    "KA_EXEC_THROTTLE", "float", 0.0, floor=0.0,
    doc="seconds to pause between converged waves (`--throttle` overrides): "
        "recovery headroom for the cluster between bursts of replica "
        "movement; 0 (default) submits the next wave immediately",
)
_knob(
    "KA_EXEC_POLL_INTERVAL", "float", 0.5, floor=0.001,
    doc="initial seconds between convergence polls of the in-flight wave; "
        "each retry backs off 1.5x with 0.5-1.5x jitter (no thundering herd "
        "against a recovering controller), capped at a quarter of "
        "`KA_EXEC_POLL_TIMEOUT`",
)
_knob(
    "KA_EXEC_POLL_TIMEOUT", "float", 600.0, floor=0.1,
    doc="seconds a wave may take to converge before the engine gives up on "
        "it: `strict` halts resumably (exit 8, journal keeps every "
        "committed wave), `best-effort` records the wave's moves as skipped "
        "and continues (degraded exit 6)",
)
_knob(
    "KA_EXEC_WRITE_RETRIES", "int", 2, floor=0,
    doc="resubmissions of a wave write after a transport failure, each "
        "preceded by a state read-back (the write-safety rule: a write is "
        "NEVER blindly replayed — re-establish, read back, and only "
        "re-issue when it provably did not land)",
)
_knob(
    "KA_EXEC_SIM_POLLS", "int", 1, floor=0,
    doc="snapshot-backend simulated convergence: a submitted move becomes "
        "visible to `read_assignment_state` after this many polls "
        "(deterministic, hermetic — the harness the write-path chaos soak "
        "and `scripts/exec_smoke.py` run against); live backends ignore it",
)
_knob(
    "KA_EXEC_JOURNAL", "str", None, default_doc="`<plan path>.journal`",
    doc="default crash-safe journal path for `ka-execute` (the `--journal` "
        "flag overrides): atomic tmp+rename commits after each converged "
        "wave, so a killed run resumes idempotently via `--resume`",
)

# --- resident daemon (ka-daemon) ---------------------------------------------
_knob(
    "KA_DAEMON_BIND", "str", "127.0.0.1",
    doc="address `ka-daemon` binds its HTTP surface to (the `--bind` flag "
        "overrides). Default loopback: the daemon is an operator tool, not "
        "an internet service — front it yourself before widening this",
)
_knob(
    "KA_DAEMON_PORT", "int", 0, floor=0,
    doc="`ka-daemon` listen port (`--port` overrides); 0 (default) picks an "
        "ephemeral port, announced as `ka-daemon: listening on ...` on "
        "stderr at startup",
)
_knob(
    "KA_DAEMON_MAX_INFLIGHT", "int", 8, floor=1,
    doc="backpressure gate: concurrent requests the daemon admits PER "
        "CLUSTER; beyond it requests are shed with 503 + `Retry-After` "
        "(counted as `daemon.requests_shed`) instead of queueing "
        "unboundedly. LIVE: re-read per request (like the program store's "
        "trace-time knobs), so an operator can loosen the gate on a "
        "running fleet without a restart",
)
_knob(
    "KA_DAEMON_REQUEST_TIMEOUT", "float", 30.0, floor=0.1,
    doc="watchdog budget per served request: a request exceeding it is "
        "flagged (`daemon.watchdog_exceeded` + stderr + a failed "
        "`daemon/request` span) so a wedged solve is visible; combined "
        "with the inflight gate this bounds queue growth",
)
_knob(
    "KA_DAEMON_RESYNC_INTERVAL", "float", 30.0, floor=0.05,
    doc="seconds between the daemon's periodic full resyncs — the escape "
        "hatch that reconverges the cache even when every watch "
        "notification was lost (`watch:drop` chaos class); also the "
        "retry cadence once prompt post-expiry resyncs are exhausted",
)
_knob(
    "KA_DAEMON_RESYNC_RETRIES", "int", 3, floor=1,
    doc="prompt bounded-resync attempts (jittered backoff) after a session "
        "re-establishment before falling back to the "
        "`KA_DAEMON_RESYNC_INTERVAL` cadence; the daemon serves "
        "stale-marked (`status: degraded`) responses until a resync lands, "
        "never an error",
)
_knob(
    "KA_DAEMON_DRAIN_TIMEOUT", "float", 10.0, floor=0.0,
    doc="seconds SIGTERM waits for in-flight requests to finish (new ones "
        "are refused on `/readyz` immediately) before the daemon exits 0 "
        "anyway",
)
_knob(
    "KA_DAEMON_BREAKER_THRESHOLD", "int", 3, floor=1,
    doc="per-cluster circuit breaker: consecutive session/resync failures "
        "that OPEN the breaker (`daemon.breaker_opened`); while open, the "
        "dead quorum is probed on the cooldown envelope instead of "
        "hammered, and that cluster's responses stale-serve or shed — "
        "other clusters' supervisors are untouched (bulkhead isolation)",
)
_knob(
    "KA_DAEMON_BREAKER_COOLDOWN", "float", 1.0, floor=0.05,
    doc="initial open-state cooldown before the breaker half-opens for one "
        "probe; doubles with 0.5-1.5x jitter per failed probe "
        "(`utils/backoff.py` envelope), capped at "
        "`KA_DAEMON_RESYNC_INTERVAL`. A successful probe closes the "
        "breaker and resets the progression",
)
_knob(
    "KA_DAEMON_JOURNAL_DIR", "str", None,
    default_doc="`.` (daemon working directory)",
    doc="where the daemon's /execute endpoint writes its crash-safe "
        "journals when the request names none: "
        "`ka-execute-<cluster>-<plan sha12>.journal` per (cluster, plan) — "
        "the journal identity that makes a daemon kill mid-execution "
        "resumable via /execute resume=1 or offline `ka-execute --resume`",
)
_knob(
    "KA_HEALTH_MOVE_COST", "float", 1.0, floor=0.0,
    doc="cost-of-change threshold for the daemon's observe-mode "
        "`/recommendations` endpoint (`obs/health.py`): a candidate plan is "
        "`recommend`ed only when its composite-score improvement exceeds "
        "`moves_required x this` — lower it and cheap rebalances flip from "
        "`hold` to `recommend`; the `?move_cost=` query param overrides per "
        "request. Read live per request, no restart needed",
)
_knob(
    "KA_DAEMON_WATCH", "bool", True,
    doc="watch-driven incremental re-encode (`daemon/`): ZooKeeper watches "
        "feed topic churn into the group-encode delta store so only "
        "touched topics re-encode (`daemon.reencode.topics`). Set to 0 "
        "(or run on a watchless backend) to fall back to interval-only "
        "full resync — identical responses, more metadata I/O",
)
_knob(
    "KA_DISPATCH", "bool", True,
    doc="request-coalescing batched solve dispatch (`daemon/dispatch.py`): "
        "concurrent solve-bearing requests queue into a gather window and "
        "compatible device work (what-if scenario rows, group autoscale "
        "rows, identical plan solves) packs into ONE batched dispatch "
        "padded to the existing power-of-two bucket shapes. Set to 0 to "
        "restore the PR 8-13 shared solve lock byte-for-byte (the "
        "kill-switch; per-request output is identical either way, "
        "test-pinned). Read once at daemon startup",
)
_knob(
    "KA_DISPATCH_WINDOW_MS", "float", 3.0, floor=0.0,
    doc="gather window of the batched solve dispatcher: after the first "
        "queued job the dispatcher waits up to this many milliseconds for "
        "more jobs to coalesce before dispatching. 0 disables gathering "
        "(every job dispatches immediately, still serialized through the "
        "dispatcher thread). Read live per gather cycle",
)
_knob(
    "KA_DISPATCH_MAX_BATCH", "int", 64, floor=1,
    doc="size trigger of the batched solve dispatcher: once this many jobs "
        "are queued the gather window closes immediately — bounds both the "
        "coalesced batch width and the latency a storm can add to the "
        "first queued request. Read live per gather cycle",
)
_knob(
    "KA_DISPATCH_WINDOW_MAX_MS", "float", 25.0, floor=0.0,
    doc="cap on the ADAPTIVE gather window: under sustained queue depth "
        "the effective window grows as `KA_DISPATCH_WINDOW_MS x depth` up "
        "to this many milliseconds (never below the configured base "
        "window), widening coalesced batches under load without letting "
        "latency run away. The live effective value is the "
        "`dispatch.window_ms` gauge. Read live per gather cycle",
)
_knob(
    "KA_DAEMON_HTTP_WORKERS", "int", 64, floor=1,
    doc="size of the daemon HTTP server's bounded worker-thread pool "
        "(`daemon/service.py`): accepted connections queue to this many "
        "handler threads instead of thread-per-request, so a 1024-client "
        "burst costs a bounded thread count and excess connections wait "
        "in the accept queue (backpressure) rather than forking a "
        "thousand threads. Read once at daemon startup",
)

# --- autonomous rebalance controller (daemon/controller.py) -----------------
_knob(
    "KA_CONTROLLER", "choice", "off", choices=("off", "observe", "auto"),
    doc="the closed-loop rebalance controller's policy ladder "
        "(`daemon/controller.py`, per cluster; the `--clusters` spec "
        "overrides per entry via `name=connect#controller=auto` or the "
        "JSON object form). `off` (default): no controller thread at all. "
        "`observe`: evaluate the recommendation pipeline on the interval "
        "and flight-record every decision — including `would-act` — but "
        "NEVER execute. `auto`: a `recommend` verdict that survives "
        "hysteresis is dispatched through the supervised /execute "
        "machinery under the blast-radius/cooldown/breaker safety rails. "
        "An explicit opt-in knob: nothing rebalances a cluster unless an "
        "operator set this",
)
_knob(
    "KA_CONTROLLER_INTERVAL", "float", 30.0, floor=0.05,
    doc="seconds between controller evaluations of the live "
        "recommendation pipeline (each evaluation is one solve under the "
        "shared dispatch regime, so the cadence trades advice freshness "
        "against device work). Read live per loop iteration",
)
_knob(
    "KA_CONTROLLER_CONFIRMATIONS", "int", 3, floor=1,
    doc="hysteresis gate: consecutive evaluations that must return a "
        "`recommend` verdict for the SAME plan bytes before the "
        "controller acts — a flapping objective (verdict or plan "
        "changing between evaluations) resets the streak and can never "
        "oscillate the cluster (the verdict-gated actuation posture of "
        "arXiv:2402.06085)",
)
_knob(
    "KA_CONTROLLER_MAX_MOVES", "int", 16, floor=1,
    doc="blast-radius cap, enforced twice: per ACTION (an oversize plan "
        "is truncated to a prefix-wave subset of at most this many "
        "replica moves — or held — never partially trusted) and per "
        "`KA_CONTROLLER_WINDOW` rolling window (actions stop once the "
        "window's executed-move budget is spent, resuming as old actions "
        "age out). Read live per evaluation",
)
_knob(
    "KA_CONTROLLER_WINDOW", "float", 3600.0, floor=1.0,
    doc="the rolling window (seconds) of the blast-radius move budget: "
        "moves executed by controller actions inside this window count "
        "against `KA_CONTROLLER_MAX_MOVES`. The window ledger persists "
        "in the journal dir (`ka-controller-<cluster>.window.json`), so "
        "a daemon restart cannot reset the budget",
)
_knob(
    "KA_CONTROLLER_COOLDOWN", "float", 300.0, floor=0.0,
    doc="minimum seconds between controller actions on one cluster, "
        "jittered 0.5-1.5x per action so a fleet of controllers never "
        "rebalances in lockstep; evaluations continue during the "
        "cooldown (keeping hysteresis warm) but actions hold",
)
_knob(
    "KA_CONTROLLER_REGRESSION_TOL", "float", 0.0, floor=0.0,
    doc="post-move regression tolerance: after a completed action the "
        "achieved composite health score (re-scored from the verify "
        "pass's observed state) may exceed the plan's projected score by "
        "at most this much; anything worse triggers the journaled "
        "abort-to-rollback path and opens the controller breaker",
)

# --- fleet scheduler (daemon/fleet.py) --------------------------------------
_knob(
    "KA_FLEET_MAX_MOVES", "int", 64, floor=1,
    doc="fleet-wide rolling move budget (`daemon/fleet.py`): replica "
        "moves charged by controller actions across EVERY cluster of one "
        "daemon inside the `KA_FLEET_WINDOW` window. A controller whose "
        "action would overspend the fleet budget is denied admission "
        "(`budget-hold`) and retries after its cooldown — the per-cluster "
        "`KA_CONTROLLER_MAX_MOVES` cap bounds one cluster, this bounds "
        "the daemon's total concurrent blast radius. Read live per "
        "admission request",
)
_knob(
    "KA_FLEET_WINDOW", "float", 3600.0, floor=1.0,
    doc="the fleet move budget's rolling window (seconds): moves charged "
        "by any cluster's admitted actions inside this window count "
        "against `KA_FLEET_MAX_MOVES`. The fleet ledger file persists in "
        "the journal dir (owned exclusively by `daemon/fleet.py` — kalint "
        "KA030), so a daemon restart cannot reset the fleet-wide "
        "accounting",
)
_knob(
    "KA_FLEET_MAX_CONCURRENT", "int", 1, floor=1,
    doc="fleet concurrency cap: how many clusters may hold an admission "
        "lease (i.e. run a controller action) at once. The default of 1 "
        "serializes the whole fleet — most-degraded cluster first, by "
        "composite health score — so two clusters sharing hardware can "
        "never rebalance simultaneously unless an operator raises this. "
        "Read live per admission request",
)
_knob(
    "KA_FLEET_LEASE_TTL", "float", 300.0, floor=0.1,
    doc="admission-lease expiry (seconds since the holder's last "
        "heartbeat; leases are heartbeat-stamped at every wave boundary). "
        "A crashed lease holder stops heartbeating and its lease ages "
        "out, so a `kill -9` mid-action can never wedge the fleet — the "
        "next admission request sweeps the expired lease and proceeds. "
        "Read live per admission request",
)

# --- consumer-group workload family (ka-groups / daemon /groups/*) ----------
_knob(
    "KA_GROUPS_DEFAULT_SCALES", "str", "100,150,200",
    doc="default lag-growth scenarios for the `ka-groups` autoscale sweep "
        "(comma-separated percentages of the observed weight column): each "
        "candidate consumer count is evaluated under every scale in one "
        "batched device fan-out; the `--scales` flag / `scales` request "
        "param override per run",
)
_knob(
    "KA_GROUPS_MAX_CANDIDATES", "int", 256, floor=1,
    doc="fan-out cap for the autoscale sweep: (consumer counts × lag "
        "scales) candidate rows per dispatch — the batch pads to its "
        "power-of-two bucket, so the cap bounds device memory and keeps "
        "the program-store bucket set small. Requests past the cap are "
        "refused loudly, never truncated silently",
)
_knob(
    "KA_GROUPS_CAPACITY_HEADROOM", "float", 1.25, floor=1.0,
    doc="capacity default for members (and synthetic consumers) without a "
        "declared estimate: the fair share of the group's total weight "
        "times this factor (`groups/encode.py`) — 1.0 means an exactly "
        "saturated default packing, larger values leave slack the sticky "
        "pass can keep partitions in place with",
)

# --- runtime / observability ------------------------------------------------
_knob(
    "KA_COMPILE_CACHE", "bool", True,
    doc="persistent XLA compile-cache kill-switch (`utils/compilecache.py`); "
        "set to 0 to disable",
)
_knob(
    "KA_PROGRAM_STORE", "bool", True,
    doc="persistent AOT program store (`utils/programstore.py`): solver "
        "executables are serialized per bucketed signature and reloaded by "
        "later processes, so a fresh process skips retrace+compile entirely "
        "(the XLA cache of `KA_COMPILE_CACHE` still pays tracing and "
        "per-process jit overhead). Set to 0 to fall back to plain jit "
        "dispatch — byte-identical output either way (test-pinned)",
)
_knob(
    "KA_PROGRAM_STORE_DIR", "str", None, default_doc="`<repo>/.ka_programs`",
    doc="program-store location; one directory per fingerprint (solver/jax/"
        "device versions — trace-time knob values key per entry) so stale "
        "executables are clean misses, never wrong answers",
)
_knob(
    "KA_PROGRAM_STORE_MAX_MB", "int", 512, floor=1,
    doc="program-store size cap in MB: after each write the store evicts "
        "least-recently-used entries (load hits refresh recency) until "
        "under the cap — a shape explosion ages out old programs instead "
        "of filling the disk",
)
_knob(
    "KA_WARMUP", "bool", True,
    doc="ingest-overlapped device warm-up (`solvers/warmup.py`): as soon as "
        "the first streamed topic chunk reveals the bucket signatures the "
        "solve will need, a background thread loads/compiles those programs "
        "concurrently with the remaining metadata ingest. Kill-switch; a "
        "failed warm-up always degrades to the normal cold path "
        "(`warmup.failures` counter), never fails the solve",
)
_knob(
    "KA_COMPILE_CACHE_DIR", "str", None, default_doc="`<repo>/.jax_cache`",
    doc="persistent XLA compile cache location; `bench.py` and the "
        "`scripts/` probes share one cache so a slow remote compile is paid "
        "once per machine",
)
_knob(
    "KA_LOG", "choice", "ERROR",
    choices=("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"),
    doc="stderr diagnostics level (`utils/logging.py`; stdout stays reserved "
        "for payload JSON — the reference gets the same split from its "
        "log4j config)",
)
_knob(
    "KA_PROFILE", "str", None, default_doc="unset (no trace)",
    doc="capture a `jax.profiler` device trace (TensorBoard/XProf xplane) "
        "into this directory around each batched solve — phase wall-clocks "
        "are always in `TpuSolver.last_timers`; this adds the op-level "
        "device view",
)
_knob(
    "KA_OBS_ENABLE", "bool", False,
    doc="collect obs/ tracing spans + metrics for CLI runs and print the "
        "run summary on stderr; `--report-json PATH` (or `KA_OBS_REPORT`) "
        "implies collection for that run regardless. Default off: the "
        "disabled mode is zero-overhead and byte-identical to a build "
        "without the subsystem",
)
_knob(
    "KA_OBS_REPORT", "str", None, default_doc="unset (no report file)",
    doc="default run-report path: when set, every CLI run emits the "
        "schema-versioned JSON run report there (obs/report.py; the "
        "`--report-json` flag overrides per run)",
)
_knob(
    "KA_OBS_HIST_EDGES", "str", None,
    default_doc="`1,5,25,100,500,2500,10000`",
    doc="obs histogram bucket upper edges (comma-separated ascending "
        "numbers, ms for timing histograms) shared by all histograms of a "
        "run; malformed values are ignored loudly and the default edges "
        "used",
)
_knob(
    "KA_OBS_ACCESS_LOG", "str", None, default_doc="unset (stderr)",
    doc="path of the daemon's structured NDJSON access log (one JSON line "
        "per served request: request id, method, path, cluster, HTTP code, "
        "report status, duration ms, inflight depth, stale/degraded "
        "markers; appended across restarts). Unset: the lines go to "
        "stderr. `ka-daemon --access-log PATH` overrides",
)
_knob(
    "KA_OBS_ACCESS_LOG_MAX_MB", "int", 0, floor=0,
    doc="size-capped rollover for the daemon's NDJSON access log: once the "
        "file reaches this many MB it is renamed to `<path>.1` (replacing "
        "any previous `.1`) and a fresh file reopened atomically under the "
        "log lock — at most ~2x this bound on disk. 0 (default) keeps the "
        "historical unbounded append behavior. Read live per write, so an "
        "operator can cap a runaway log without a restart",
)
_knob(
    "KA_OBS_TRAFFIC_SERIES_MAX", "int", 512, floor=0,
    doc="per-cluster cap on the `/metrics` per-partition traffic/lag gauge "
        "series (`traffic.in_bytes`/`traffic.out_bytes`/`traffic.lag`, "
        "labeled topic x partition): the top partitions by produce rate "
        "are exported, the suppressed remainder is COUNTED in "
        "`traffic.series_dropped` (never silently truncated). 0 disables "
        "the cap — a million-partition cluster will mint a million label "
        "sets, so leave it bounded on giants",
)
_knob(
    "KA_OBS_FLIGHT_EVENTS", "int", 512, floor=0,
    doc="flight-recorder ring capacity: the daemon retains this many "
        "recent lifecycle/breaker/session/resync/watch/watchdog/request/"
        "fault events in memory (`obs/flight.py`), dumpable via "
        "`/debug/flight` and flushed to `KA_OBS_FLIGHT_DUMP` on SIGTERM "
        "or crash; overflow evicts oldest and is counted (`dropped`). "
        "0 disables the recorder",
)
_knob(
    "KA_OBS_FLIGHT_DUMP", "str", None,
    default_doc="unset (live /debug/flight only)",
    doc="when set, the daemon flushes its flight-recorder ring to this "
        "path as NDJSON on SIGTERM drain AND on a crashing exit — the "
        "post-mortem artifact that replaces scraping stderr after a "
        "chaos-soak failure",
)
_knob(
    "KA_OBS_PROFILE_DIR", "str", None, default_doc="unset (no profiling)",
    doc="device-profiler output directory: gates the `jax.profiler` trace "
        "around each batched solve dispatch (`obs/profile.py`; supersedes "
        "the legacy `KA_PROFILE`, which still works) and enables the "
        "daemon's `/debug/profile?seconds=N` window capture. Unset "
        "(default): zero profiler overhead, /debug/profile refuses",
)
_knob(
    "KA_LINT_CACHE", "bool", True,
    doc="serve `python -m kafka_assigner_tpu.analysis.kalint` package runs "
        "from the content-hash analysis cache (keyed on every source file, "
        "the linter itself, the registries and the README — any edit "
        "misses and re-analyzes, so a hit is always current). 0 forces a "
        "full interprocedural re-analysis every run",
)
_knob(
    "KA_LINT_CACHE_DIR", "str", None,
    default_doc="`<repo>/.kalint-cache`",
    doc="where the kalint analysis cache lives; entries are whole-tree "
        "finding sets keyed by content hash, atomic-written, pruned to "
        "the newest few",
)
_knob(
    "KA_DEVICE_WATCHDOG_S", "float", 0.0, floor=0.0,
    doc="console entry point probes accelerator init in a subprocess for "
        "this many seconds and falls back to the CPU backend (with a stderr "
        "warning) instead of hanging on a wedged TPU tunnel; 0 (default) "
        "disables the probe",
)
_knob(
    "KA_CLI_CPU_FALLBACK", "bool", False, internal=True,
    doc="internal handshake set by the watchdog re-exec so the CPU-fallback "
        "process does not probe again; not meant to be set by operators",
)


_UNSET = object()


def _lookup(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a registered knob; declare it in "
            "kafka_assigner_tpu/utils/env.py (see kalint rule KA003)"
        ) from None


def _warn(msg: str) -> None:
    print(f"kafka-assigner: {msg}", file=sys.stderr)


def knob_default(name: str):
    """The declared default of a registered knob (KeyError on a typo — the
    programmatic twin of kalint's KA003)."""
    return _lookup(name).default


def registered_knobs() -> Tuple[Knob, ...]:
    """All declared knobs, in declaration (= README table) order."""
    return tuple(KNOBS.values())


def env_int(name: str, default=_UNSET, floor=_UNSET):
    """``int(os.environ[name])`` clamped to the knob's floor; the declared
    default when unset/empty or non-integer (the latter with a stderr
    warning). ``default``/``floor`` override the declaration when given."""
    k = _lookup(name)
    if default is _UNSET:
        default = k.default
    if floor is _UNSET:
        floor = k.floor
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        _warn(f"ignoring non-integer {name}={raw!r}")
        return default
    return val if floor is None else max(floor, val)


def env_float(name: str, default=_UNSET, floor=_UNSET):
    """``float(os.environ[name])`` clamped to the knob's floor; the declared
    default when unset/empty or non-numeric (the latter with a stderr
    warning)."""
    k = _lookup(name)
    if default is _UNSET:
        default = k.default
    if floor is _UNSET:
        floor = k.floor
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        _warn(f"ignoring non-numeric {name}={raw!r}")
        return default
    return val if floor is None else max(floor, val)


#: The normalized truthiness convention (module docstring).
_TRUE = frozenset({"1", "true", "yes", "on"})
_FALSE = frozenset({"0", "false", "no", "off"})


def env_bool(name: str, default=_UNSET) -> bool:
    """Boolean knob under the package truthiness convention; unset/empty means
    the declared default, anything unrecognized warns and defaults."""
    k = _lookup(name)
    if default is _UNSET:
        default = k.default
    raw = os.environ.get(name)
    if not raw:
        return bool(default)
    low = raw.strip().lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    _warn(
        f"ignoring non-boolean {name}={raw!r} "
        "(truthy: 1/true/yes/on, falsy: 0/false/no/off)"
    )
    return bool(default)


def env_choice(name: str, choices=None, default=_UNSET):
    """Enumerated knob: the raw value must be one of ``choices`` (declared on
    the knob, or passed for knobs whose choice set lives elsewhere, e.g.
    ``KA_WAVE_MODE`` against ``ops/assignment.py:WAVE_MODES``). Case and
    surrounding whitespace are forgiven when the folded form matches;
    unknown values warn and default."""
    k = _lookup(name)
    if choices is None:
        choices = k.choices
    if not choices:
        # Passing raw through unvalidated would be exactly the silent config
        # drift the house rule forbids — a programming error, not a knob error.
        raise KeyError(
            f"{name} is a choice knob with no declared choice set; pass "
            "choices= at the call site (e.g. KA_WAVE_MODE against "
            "ops/assignment.py:WAVE_MODES)"
        )
    if default is _UNSET:
        default = k.default
    raw = os.environ.get(name)
    if not raw or not raw.strip():
        return default
    raw = raw.strip()
    for cand in (raw, raw.upper(), raw.lower()):
        if cand in choices:
            return cand
    _warn(
        f"ignoring unknown {name}={raw!r} "
        f"(expected one of {sorted(choices)})"
    )
    return default


def env_str(name: str, default=_UNSET):
    """Free-form string knob (paths, directories); unset/empty means the
    declared default. No parsing, so nothing to ignore loudly."""
    k = _lookup(name)
    if default is _UNSET:
        default = k.default
    raw = os.environ.get(name)
    return raw if raw else default
