"""The one jittered-exponential-backoff implementation (ISSUE 8 satellite).

Three hand-rolled copies of the same loop had grown across the I/O and
execution layers — the wire client's connect passes and in-session
re-establishment (``io/zkwire.py``) and the execution engine's convergence
poll (``exec/engine.py``) — each re-deriving ``min(base * factor**k, cap)``
with 0.5–1.5x jitter inline. Divergence between them is exactly the kind of
silent timing drift the knob registry exists to prevent, so the progression
lives here once, with the observable timing contract pinned by
``tests/test_backoff.py``:

- attempt ``k`` (1-based) draws ``min(base * factor**(k-1), cap) * j`` with
  ``j`` uniform in ``[0.5, 1.5)`` — the anti-thundering-herd jitter every
  call site already used (a fleet of retriers must not re-arrive in
  lockstep);
- the nominal (pre-jitter) progression is deterministic and knob-driven;
  jitter is the ONLY randomness, so a seeded ``rng`` reproduces a schedule
  exactly.

Callers own their retry COUNTING and their sleeps (the engine clamps each
delay to its poll deadline; the wire client warns per attempt): this class
only answers "how long is the next pause?".
"""
from __future__ import annotations

import random
import time
from typing import Optional


class JitteredBackoff:
    """Successive jittered delays: ``min(base * factor**k, cap) * jitter``.

    ``factor`` defaults to the doubling every prior call site used;
    ``cap`` bounds the nominal delay (None = uncapped); ``rng`` defaults to
    the module-global ``random`` (pass a seeded ``random.Random`` for
    reproducible schedules in tests).
    """

    def __init__(
        self,
        base: float,
        *,
        factor: float = 2.0,
        cap: Optional[float] = None,
        rng=None,
    ) -> None:
        if base < 0:
            raise ValueError(f"backoff base must be >= 0, got {base}")
        if factor < 1.0:
            raise ValueError(f"backoff factor must be >= 1, got {factor}")
        self.base = float(base)
        self.factor = float(factor)
        self.cap = None if cap is None else float(cap)
        self._rng = rng if rng is not None else random
        self._nominal = self.base

    def peek_nominal(self) -> float:
        """The next delay BEFORE jitter (capped) — what a log line or a
        deadline clamp should quote, since the jittered value is drawn only
        when the delay is actually taken."""
        if self.cap is None:
            return self._nominal
        return min(self._nominal, self.cap)

    def next_delay(self) -> float:
        """Draw the next jittered delay and advance the progression."""
        nominal = self.peek_nominal()
        self._nominal *= self.factor
        if self.cap is not None:
            self._nominal = min(self._nominal, self.cap)
        return nominal * (0.5 + self._rng.random())

    def delay_for(self, attempt: int) -> float:
        """Stateless variant: the jittered delay for 1-based ``attempt``
        (``min(base * factor**(attempt-1), cap) * jitter``), independent of
        the instance's own progression. For call sites whose retry counter
        lives elsewhere (the wire client's session-reestablishment loops
        pass their attempt number down into one shared ``_reconnect``)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        nominal = self.base * (self.factor ** (attempt - 1))
        if self.cap is not None:
            nominal = min(nominal, self.cap)
        return nominal * (0.5 + self._rng.random())

    def sleep(self) -> float:
        """``time.sleep(next_delay())``; returns the slept delay."""
        delay = self.next_delay()
        time.sleep(delay)
        return delay
