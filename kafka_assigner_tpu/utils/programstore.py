"""Persistent AOT program store: compiled solver executables, across processes.

The XLA compile cache (``utils/compilecache.py``) already persists *backend
compilation*, but a fresh process still pays jaxpr tracing, lowering, and the
cache's own fingerprinting on every jit entry — ~16 s of the deployment
target's cold start against a 542.7 ms solve (``BENCH_onchip_r05.json:
tpu_cold_ms``). This module removes the remaining term: the solver's jitted
entry points are compiled once per *bucketed signature* via
``jax.jit(...).lower().compile()``, serialized with JAX's executable
serialization, and reloaded byte-for-byte by later processes — load is
deserialization, not retrace.

Layering (this module sits ON TOP of the XLA cache, never replaces it):

- ``enable_persistent_cache`` stays on for every OTHER compile in the
  process (mesh paths, scripts, plain-jit fallbacks). The store's own
  miss-compiles bypass it (:func:`_aot_compile`): an executable rehydrated
  from the XLA cache re-serializes without its object code — "Symbols not
  found" on every later load — so store entries must come from genuine
  backend compiles (regression-pinned in ``tests/test_programstore.py``,
  which runs with the suite's XLA cache warm);
- the store keys on the *call* signature (entry name + static args + input
  avals), the granularity the solver already buckets on
  (``models/problem.py``: P/N axes multiples of 8, batch axis powers of two,
  exact replica width) — one entry per ``(P-bucket, N-bucket, L, RF,
  wave-mode)`` class, reused across topics and runs.

Safety contract (every path is belt-and-braces, the store is an optimization):

- **fingerprinted**: entries live under a directory named by a hash of
  (store schema version, package version, jax/jaxlib versions, backend
  platform + compiler version, device kind + count); trace-time ``KA_*``
  knob values (which can change mid-process) are read fresh on every
  dispatch and participate in the entry key instead. Any mismatch is a
  clean miss — a stale executable can never be *loaded*, let alone
  produce a wrong answer;
- **corruption-tolerant**: an unreadable/undeserializable entry warns on
  stderr, is unlinked best-effort, and falls back to a fresh compile;
- **atomic**: writes go to a same-directory temp file and ``os.replace`` in,
  so concurrent writers (or a crash mid-write) can never torch the store;
- **bounded**: after each write the store evicts least-recently-used entries
  (mtime, refreshed on load hits) until under ``KA_PROGRAM_STORE_MAX_MB``;
- **bucket-guarded**: entries carry a shape contract (``BucketContract``)
  mirroring the encode-side bucketing rules; an ad-hoc shape is dispatched
  through plain jit (and warned about) instead of persisting — the runtime
  half of kalint rule KA009, so unbucketed call sites cannot silently
  explode the store with one-shot programs.

Observability: ``compile.store.hits`` / ``compile.store.misses`` counters and
the ``compile.store.loads_ms`` / ``compile.store.compiles_ms`` histograms give
every run report cold-vs-warm compile attribution.

``KA_PROGRAM_STORE=0`` disables the whole layer: every wrapped entry degrades
to its plain jit call, byte-identical output (test-pinned).
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import pickle
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

from .env import env_bool, env_int, env_str

#: Bump when the stored payload format or the keying scheme changes — old
#: stores become clean misses instead of deserialization errors.
STORE_SCHEMA_VERSION = 1

#: Trace-time knobs whose values are baked into the compiled program without
#: appearing in any static argument (their reads happen inside the traced
#: code, see utils/env.py docs) — they MUST participate in the entry KEY,
#: and they are read fresh on every dispatch: a mid-process knob change
#: (tests flip KA_DENSE_MASK_BUDGET around ``jax.clear_caches()``; the
#: boundary tests depend on it) must re-key immediately, exactly like jax's
#: own trace cache re-traces. Process-stable facts (versions, devices) live
#: in the cached fingerprint instead.
TRACE_TIME_KNOBS = (
    "KA_DENSE_MASK_BUDGET", "KA_QUOTA_WAVE_TARGET", "KA_QUOTA_ENDGAME",
)


def _trace_knob_key() -> str:
    from .env import env_int

    return ",".join(f"{k}={env_int(k)}" for k in TRACE_TIME_KNOBS)

#: Default store location: sibling of the package, like `.jax_cache`
#: (gitignored). Override with KA_PROGRAM_STORE_DIR.
_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".ka_programs",
)

_warned: set = set()

#: Unique temp-file suffix per write: pid alone is not enough — concurrent
#: THREADS of one process (warm-up + solve) write the same entry.
_tmp_seq = itertools.count()


def _tmp_name(path: str) -> str:
    return f"{path}.tmp.{os.getpid()}.{next(_tmp_seq)}"


def _warn_once(msg: str) -> None:
    """Loud-but-not-spammy stderr warning (the compile path can run inside
    per-topic loops; each distinct condition prints once per process)."""
    if msg not in _warned:
        print(f"kafka-assigner: {msg}", file=sys.stderr)
        _warned.add(msg)


# --- bucket contracts (runtime half of kalint KA009) -------------------------

@dataclass(frozen=True)
class BucketContract:
    """Axis-bucketing contract for one ops/ entry point's positional args.

    ``axes[i]`` describes positional arg i as a tuple of per-dimension codes:
    ``"b"`` (batch axis: power of two, ``models/problem.py:batch_bucket``),
    ``"p"``/``"n"`` (partition/node axis: multiple of 8, ``_pad8``), or
    ``None`` (unconstrained, e.g. the exact replica width). Args beyond
    ``axes`` and keyword args are unconstrained.
    """

    axes: Tuple[Optional[Tuple[Optional[str], ...]], ...] = ()

    def violations(self, args: Sequence[Any]) -> Tuple[str, ...]:
        out = []
        for i, spec in enumerate(self.axes):
            if spec is None or i >= len(args):
                continue
            shape = getattr(args[i], "shape", None)
            if shape is None or len(shape) != len(spec):
                continue  # scalar / unexpected rank: not this contract's job
            for dim, code in zip(shape, spec):
                if code == "b" and (dim < 1 or (dim & (dim - 1)) != 0):
                    out.append(f"arg{i} batch axis {dim} is not a power of 2")
                elif code in ("p", "n") and dim % 8 != 0:
                    out.append(
                        f"arg{i} {'partition' if code == 'p' else 'node'} "
                        f"axis {dim} is not a multiple of 8"
                    )
        return tuple(out)


# --- fingerprint -------------------------------------------------------------

_FP_LOCK = threading.Lock()
_FP_CACHE: Optional[Tuple[str, Dict[str, Any]]] = None


def _fingerprint_facts() -> Dict[str, Any]:
    """The raw fingerprint inputs (also written to the store's meta.json so a
    human can see WHY an old entry stopped matching)."""
    import jax
    import jaxlib

    from .. import __version__ as pkg_version

    try:
        from jax.extend import backend as jex_backend

        b = jex_backend.get_backend()
        platform = b.platform
        platform_version = getattr(b, "platform_version", "")
    except Exception as e:  # very old/new jax: degrade to the device view
        _warn_once(f"program store: backend probe failed ({e})")
        platform, platform_version = jax.default_backend(), ""
    devices = jax.devices()
    return {
        "store_schema": STORE_SCHEMA_VERSION,
        "package": pkg_version,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
        "platform_version": platform_version,
        "device_kind": devices[0].device_kind if devices else "none",
        "device_count": len(devices),
    }


def fingerprint() -> str:
    """Hex digest naming this process's compatibility class (cached — the
    backend cannot change mid-process)."""
    global _FP_CACHE
    with _FP_LOCK:
        if _FP_CACHE is None:
            facts = _fingerprint_facts()
            digest = hashlib.sha256(
                # kalint: disable=KA005 -- fingerprint hash input, not a Kafka plan payload
                json.dumps(facts, sort_keys=True).encode()
            ).hexdigest()[:24]
            _FP_CACHE = (digest, facts)
        return _FP_CACHE[0]


def _reset_fingerprint_cache() -> None:
    """Test hook: forget the cached fingerprint (e.g. after monkeypatching
    the fingerprint inputs)."""
    global _FP_CACHE
    with _FP_LOCK:
        _FP_CACHE = None


# --- the on-disk store -------------------------------------------------------

class ProgramStore:
    """One on-disk executable store rooted at ``root`` (layout:
    ``<root>/<fingerprint>/<keyhash>.exe`` + a human-readable meta.json per
    fingerprint directory)."""

    def __init__(self, root: str) -> None:
        self.root = root

    def _dir(self) -> str:
        return os.path.join(self.root, fingerprint())

    def _path(self, key: str) -> str:
        keyhash = hashlib.sha256(key.encode()).hexdigest()[:32]
        return os.path.join(self._dir(), f"{keyhash}.exe")

    def load(self, key: str):
        """The deserialized executable for ``key``, or None (clean miss /
        corrupted entry). Never raises."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
            if blob.get("schema") != STORE_SCHEMA_VERSION or \
                    blob.get("key") != key:
                raise ValueError("key/schema mismatch inside entry")
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            exe = deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
        except FileNotFoundError:
            return None
        except Exception as e:
            _warn_once(
                f"program store: dropping corrupted entry {path} "
                f"({type(e).__name__}: {e}); falling back to fresh compile"
            )
            try:
                os.unlink(path)
            except OSError as ue:
                _warn_once(f"program store: could not unlink {path}: {ue}")
            return None
        try:
            # Recency for the LRU cap: a loaded program is a live program.
            os.utime(path, None)
        except OSError:  # kalint: disable=KA008 -- recency refresh is advisory; a read-only store must still serve loads
            pass
        return exe

    def save(self, key: str, compiled) -> bool:
        """Serialize ``compiled`` under ``key`` (atomic rename; concurrent
        writers of the same key both write valid files and one wins).
        The payload is VERIFIED (deserialized back) before it is written: an
        executable that was rehydrated from jax's persistent compilation
        cache anywhere up the stack serializes without its object code and
        would fail every later load ("Symbols not found") — such a payload
        must never enter the store (the caller retries with a forced-fresh
        compile, see ``StoredJit._resolve``). Returns success; never
        raises."""
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
                serialize,
            )

            payload, in_tree, out_tree = serialize(compiled)
            deserialize_and_load(payload, in_tree, out_tree)  # verify
            blob = pickle.dumps({
                "schema": STORE_SCHEMA_VERSION,
                "key": key,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
            d = self._dir()
            os.makedirs(d, exist_ok=True)
            meta = os.path.join(d, "meta.json")
            if not os.path.exists(meta):
                tmp_meta = _tmp_name(meta)
                with open(tmp_meta, "w", encoding="utf-8") as f:
                    # kalint: disable=KA005 -- store metadata, not a Kafka plan payload
                    json.dump(_fingerprint_facts(), f, indent=2, default=str)
                os.replace(tmp_meta, meta)
            path = self._path(key)
            tmp = _tmp_name(path)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except Exception as e:
            _warn_once(
                f"program store: could not persist executable ({type(e).__name__}: "
                f"{e}); this process keeps its in-memory copy"
            )
            return False
        self._evict()
        return True

    def _evict(self) -> None:
        """LRU size cap over the whole store (all fingerprints): drop
        oldest-mtime entries until under ``KA_PROGRAM_STORE_MAX_MB``."""
        cap_bytes = env_int("KA_PROGRAM_STORE_MAX_MB") * (1 << 20)
        entries = []
        total = 0
        try:
            for dirpath, _dirnames, filenames in os.walk(self.root):
                for name in filenames:
                    if not name.endswith(".exe"):
                        continue
                    p = os.path.join(dirpath, name)
                    try:
                        st = os.stat(p)
                    except OSError:  # kalint: disable=KA008 -- entry raced away (concurrent eviction); nothing to size
                        continue
                    entries.append((st.st_mtime, st.st_size, p))
                    total += st.st_size
            if total <= cap_bytes:
                return
            evicted = 0
            for _mtime, size, p in sorted(entries):
                try:
                    os.unlink(p)
                    total -= size
                    evicted += 1
                except OSError:  # kalint: disable=KA008 -- a concurrent evictor won the unlink; the size goal still converges
                    continue
                if total <= cap_bytes:
                    break
            if evicted:
                _warn_once(
                    f"program store: size cap reached "
                    f"(KA_PROGRAM_STORE_MAX_MB); evicted {evicted} LRU "
                    "entr(y/ies)"
                )
        except Exception as e:
            _warn_once(f"program store: eviction sweep failed ({e})")


_STORE_LOCK = threading.Lock()
_STORE: Optional[Tuple[str, ProgramStore]] = None


def store_enabled() -> bool:
    return env_bool("KA_PROGRAM_STORE")


def get_store() -> ProgramStore:
    """The process store (rebuilt when ``KA_PROGRAM_STORE_DIR`` changes —
    tests repoint it per tmp_path)."""
    global _STORE
    root = env_str("KA_PROGRAM_STORE_DIR") or _DEFAULT_DIR
    with _STORE_LOCK:
        if _STORE is None or _STORE[0] != root:
            _STORE = (root, ProgramStore(root))
        return _STORE[1]


#: Guards the global compilation-cache toggle in :func:`_aot_compile` (the
#: warm-up thread and the solve can compile concurrently).
_COMPILE_LOCK = threading.Lock()


def _aot_compile(jit_fn, args, kwargs, force_fresh: bool = False):
    """``lower().compile()`` with the XLA persistent compilation cache
    BYPASSED for this one compile.

    Why: an executable rehydrated from that cache re-serializes without its
    jitted object code (XLA CPU drops it on the cache path — every later
    ``deserialize_and_load`` fails with "Symbols not found"), so a store
    entry must always come from a genuine backend compile. The toggle is
    global, hence the lock; a concurrent unrelated compile merely loses one
    cache lookup, never correctness. Paid once per signature per store —
    after that every process loads the serialized program directly.

    ``force_fresh``: escape hatch when the default compile STILL came back
    unserializable (a rehydrated executable served from jax's in-memory
    executable cache, which ignores the toggle): an explicit no-op compiler
    option changes the cache key, forcing a genuine backend compile.
    Returns None (with a warning) when even that fails — the caller keeps
    its working in-memory executable and simply doesn't persist."""
    import jax

    def _compile():
        lowered = jit_fn.lower(*args, **kwargs)
        if not force_fresh:
            return lowered.compile()
        return lowered.compile(
            compiler_options={"xla_embed_ir_in_executable": False}
        )

    try:
        with _COMPILE_LOCK:
            try:
                from jax.experimental.compilation_cache.compilation_cache import (
                    reset_cache,
                )

                enabled = bool(jax.config.jax_enable_compilation_cache)
            except (AttributeError, ImportError):
                # ancient jax: no toggle, no persistent cache either
                return _compile()
            if not enabled:
                return _compile()
            # The disable toggle alone is NOT enough: jax memoizes
            # "is the cache used" per process at first compile, so a flag
            # flip after that is ignored. reset_cache() clears the memo (the
            # on-disk cache is untouched); the trailing reset lets the next
            # plain-path compile re-initialize and use the cache normally.
            jax.config.update("jax_enable_compilation_cache", False)
            reset_cache()
            try:
                return _compile()
            finally:
                jax.config.update("jax_enable_compilation_cache", True)
                reset_cache()
    except Exception as e:
        if force_fresh:
            _warn_once(
                f"program store: forced-fresh compile failed "
                f"({type(e).__name__}: {e}); entry stays unpersisted"
            )
            return None
        raise


# --- the dispatch wrapper ----------------------------------------------------

class StoredJit:
    """A ``jax.jit``-ed entry point routed through the program store.

    Call it exactly like the wrapped jit function. Per bucketed signature
    (static args + input avals) the first call resolves an executable —
    in-memory cache, then store load, then ``lower().compile()`` + persist —
    and every later call dispatches the resolved executable directly. Any
    failure anywhere degrades to the plain jit call (byte-identical output;
    the store is an optimization, never a correctness dependency).
    """

    def __init__(
        self,
        name: str,
        jit_fn,
        static_argnames: Tuple[str, ...],
        contract: Optional[BucketContract] = None,
    ) -> None:
        self.name = name
        self._jit = jit_fn
        self._static = frozenset(static_argnames)
        self._contract = contract
        self._mem: Dict[str, Any] = {}
        self._unbucketed: set = set()  # keys rejected by the contract
        self._mem_lock = threading.Lock()
        self._key_locks: Dict[str, threading.Lock] = {}

    # -- keying ---------------------------------------------------------------

    def _split(self, kwargs):
        statics = {k: v for k, v in kwargs.items() if k in self._static}
        dyn = {k: v for k, v in kwargs.items() if k not in self._static}
        return statics, dyn

    def _key(self, args, dyn, statics) -> str:
        import jax
        from jax.api_util import shaped_abstractify

        leaves, treedef = jax.tree_util.tree_flatten((args, dyn))
        avals = ",".join(str(shaped_abstractify(x)) for x in leaves)
        stat = ",".join(f"{k}={statics[k]!r}" for k in sorted(statics))
        return f"{self.name}|{stat}|{_trace_knob_key()}|{treedef}|{avals}"

    def _multi_device(self, args, dyn) -> bool:
        """Mesh-sharded inputs bypass the store: a serialized executable is
        sharding-specific and the mesh path already amortizes its compiles
        per process. (Single-device arrays — the CLI path — qualify.)"""
        import jax

        for leaf in jax.tree_util.tree_leaves((args, dyn)):
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and len(sharding.device_set) > 1:
                return True
        return False

    def _lock_for(self, key: str) -> threading.Lock:
        with self._mem_lock:
            lock = self._key_locks.get(key)
            if lock is None:
                lock = self._key_locks[key] = threading.Lock()
            return lock

    # -- resolution -----------------------------------------------------------

    def _resolve(self, key, args, kwargs):
        """The executable for this signature, resolving store-load before
        compile; None when this signature must go through plain jit (bucket
        contract violation). Thread-safe per key: the ingest warm-up thread
        and the solve can race on the same signature and the loser reuses
        the winner's executable instead of compiling twice."""
        from ..obs.metrics import counter_add, hist_observe

        with self._mem_lock:
            if key in self._unbucketed:
                return None
            exe = self._mem.get(key)
        if exe is not None:
            return exe
        with self._lock_for(key):
            with self._mem_lock:
                if key in self._unbucketed:
                    return None
                exe = self._mem.get(key)
            if exe is not None:
                return exe
            # Contract gate BEFORE any store traffic: an unbucketed shape is
            # not a miss (it was never eligible), and the verdict is
            # memoized so repeated ad-hoc dispatches don't re-probe disk.
            if self._contract is not None:
                bad = self._contract.violations(args)
                if bad:
                    counter_add("compile.store.unbucketed")
                    _warn_once(
                        f"program store: {self.name} called with "
                        f"unbucketed shapes ({'; '.join(bad)}); "
                        "dispatching through plain jit and NOT "
                        "persisting (see kalint rule KA009 / "
                        "models/problem.py bucketing)"
                    )
                    with self._mem_lock:
                        self._unbucketed.add(key)
                    return None
            store = get_store()
            t0 = time.perf_counter()
            exe = store.load(key)
            if exe is not None:
                counter_add("compile.store.hits")
                hist_observe(
                    "compile.store.loads_ms",
                    (time.perf_counter() - t0) * 1000.0,
                )
            else:
                counter_add("compile.store.misses")
                t0 = time.perf_counter()
                exe = _aot_compile(self._jit, args, kwargs)
                hist_observe(
                    "compile.store.compiles_ms",
                    (time.perf_counter() - t0) * 1000.0,
                )
                if not store.save(key, exe):
                    # Unserializable (a cache-rehydrated executable leaked in
                    # through jax's in-memory executable cache): retry once
                    # with a forced-fresh backend compile so the store gets a
                    # loadable entry; the solve works either way.
                    fresh = _aot_compile(
                        self._jit, args, kwargs, force_fresh=True
                    )
                    if fresh is not None and store.save(key, fresh):
                        exe = fresh
            with self._mem_lock:
                self._mem[key] = exe
            return exe

    # -- public surface -------------------------------------------------------

    def __call__(self, *args, **kwargs):
        if not store_enabled():
            return self._jit(*args, **kwargs)
        statics, dyn = self._split(kwargs)
        try:
            if self._multi_device(args, dyn):
                return self._jit(*args, **kwargs)
            key = self._key(args, dyn, statics)
            exe = self._resolve(key, args, kwargs)
        except Exception as e:
            _warn_once(
                f"program store: {self.name} resolution failed "
                f"({type(e).__name__}: {e}); using plain jit dispatch"
            )
            exe = None
        if exe is None:
            return self._jit(*args, **kwargs)
        try:
            return exe(*args, **dyn)
        except Exception as e:
            # Aval/layout mismatch or a stale executable that loaded but
            # cannot run here: drop it and recover through plain jit.
            _warn_once(
                f"program store: stored executable for {self.name} failed to "
                f"run ({type(e).__name__}: {e}); recompiling via jit"
            )
            from ..obs.metrics import counter_add

            counter_add("compile.store.exec_fallbacks")
            with self._mem_lock:
                self._mem.pop(key, None)
            return self._jit(*args, **kwargs)

    def warm(self, *args, **kwargs) -> str:
        """Ensure this signature's executable is resident (load or compile)
        WITHOUT executing it. Returns one of ``"hit"`` (already in memory),
        ``"warmed"`` (loaded/compiled now), ``"jit"`` (store disabled or
        unbucketed: the plain jit function was traced+compiled instead), or
        ``"error"`` — warm-up must never raise."""
        try:
            if not store_enabled():
                # Populate jax's own jit cache so the real call is still warm.
                self._jit(*args, **kwargs)
                return "jit"
            statics, dyn = self._split(kwargs)
            if self._multi_device(args, dyn):
                self._jit(*args, **kwargs)
                return "jit"
            key = self._key(args, dyn, statics)
            with self._mem_lock:
                hit = key in self._mem
            if hit:
                return "hit"
            exe = self._resolve(key, args, kwargs)
            if exe is None:
                self._jit(*args, **kwargs)
                return "jit"
            return "warmed"
        except Exception as e:
            _warn_once(
                f"program store: warm({self.name}) failed "
                f"({type(e).__name__}: {e}); cold path unaffected"
            )
            return "error"


_WRAPPERS: Dict[str, StoredJit] = {}
_WRAPPERS_LOCK = threading.Lock()


def wrap_jit(
    name: str,
    jit_fn,
    static_argnames: Sequence[str],
    contract: Optional[BucketContract] = None,
) -> StoredJit:
    """The process-wide :class:`StoredJit` for ``name`` (created on first
    use; later calls return the same wrapper so its executable cache is
    shared by every call site, warm-up thread included)."""
    with _WRAPPERS_LOCK:
        w = _WRAPPERS.get(name)
        if w is None:
            w = _WRAPPERS[name] = StoredJit(
                name, jit_fn, tuple(static_argnames), contract
            )
        return w


def clear_memory() -> None:
    """Drop every wrapper's in-memory executables (NOT the on-disk store).
    Used by tests to force the store-load path, and by long test sessions to
    bound live-executable memory next to ``jax.clear_caches()``."""
    with _WRAPPERS_LOCK:
        wrappers = list(_WRAPPERS.values())
    for w in wrappers:
        with w._mem_lock:
            w._mem.clear()
            w._unbucketed.clear()
