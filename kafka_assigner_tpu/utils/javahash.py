"""Java ``String.hashCode`` semantics, needed for output parity with the reference.

The reference rotates its node-processing order by ``Math.abs(topic.hashCode()) %
nodes`` (``KafkaAssignmentStrategy.java:188-200``) both when spreading orphaned
replicas and when breaking ties in leadership ordering. To reproduce the
reference's placement decisions bit-for-bit, we reproduce the JVM hash exactly,
including 32-bit overflow over UTF-16 code units.
"""
from __future__ import annotations

import struct

_INT32_MIN = -(2**31)


def java_string_hash(s: str) -> int:
    """Java ``String.hashCode()``: ``sum(u[i] * 31^(n-1-i))`` wrapped to int32.

    Operates on UTF-16 code units (Java ``char``), so supplementary-plane
    characters contribute two units, exactly as on the JVM.
    """
    data = s.encode("utf-16-be")
    units = struct.unpack(f">{len(data) // 2}H", data)
    h = 0
    for u in units:
        h = (31 * h + u) & 0xFFFFFFFF
    return h - 0x100000000 if h >= 0x80000000 else h


def topic_start_index(topic: str, n: int) -> int:
    """``Math.abs(topic.hashCode()) % n`` (``KafkaAssignmentStrategy.java:190``).

    Java's ``Math.abs(Integer.MIN_VALUE)`` is still negative; the reference
    would then index an array with a negative value and crash with
    ``ArrayIndexOutOfBoundsException``. We surface that pathological case as a
    clear error instead of reproducing the crash.
    """
    if n <= 0:
        raise ValueError("node count must be positive")
    h = java_string_hash(topic)
    if h == _INT32_MIN:
        raise ValueError(
            f"topic {topic!r} hashes to Integer.MIN_VALUE; the reference tool "
            "crashes on this input (negative array index)"
        )
    return abs(h) % n
