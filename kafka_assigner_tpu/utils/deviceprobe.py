"""Wedged-accelerator self-defense, shared by ``bench.py`` and
``__graft_entry__.py``.

A tunneled TPU chip can wedge so that PJRT backend init hangs forever — and
because jax eagerly initializes every *registered* plugin, even
``JAX_PLATFORMS=cpu`` runs hang at ``jax.devices()`` while the plugin's site
dir (``axon``) is importable. The recipe that works (learned the hard way in
round 1):

1. probe device init in a *subprocess* under a watchdog (the hang must not
   reach the calling process);
2. on failure, re-run on the CPU backend with the plugin's site dir stripped
   from ``PYTHONPATH`` — and, when a virtual mesh is needed, with
   ``--xla_force_host_platform_device_count=<n>``.
"""
from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, Optional, Sequence


def probe_device_count(timeout_s: float, allow_cpu: bool = False) -> int:
    """Device count a fresh interpreter sees with the current env, -1 on
    wedge/failure. With ``allow_cpu=False`` (the bench's setting) a
    successfully-initialized ``cpu`` backend reports 0 — a CPU platform
    (e.g. an ambient ``JAX_PLATFORMS=cpu``) must never make the bench
    artifact drop its ``_cpu_fallback`` tag. ``allow_cpu=True`` counts any
    platform's devices (the multichip dryrun runs on a forced CPU mesh by
    design). Init can legitimately take ~20-40s on first TPU contact; pick
    ``timeout_s`` above that."""
    expr = (
        "len(ds)"
        if allow_cpu
        else "0 if jax.default_backend() == 'cpu' else len(ds)"
    )
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                f"import jax; ds = jax.devices(); print({expr})",
            ],
            timeout=timeout_s, capture_output=True, text=True,
        )
        if proc.returncode == 0:
            return int(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):  # kalint: disable=KA008 -- probe failure IS the signal; -1 below tells the caller
        pass
    return -1


def virtual_cpu_env(
    n_devices: Optional[int] = None,
    prepend_path: Sequence[str] = (),
) -> Dict[str, str]:
    """Env for a CPU-backend re-run with the TPU plugin unregistered.

    ``n_devices``: when set, force an n-device virtual CPU platform (for mesh
    code); when None, leave the device count alone (single CPU device).
    ``prepend_path``: entries to put at the front of ``PYTHONPATH`` (e.g. the
    repo root so the re-exec'd script still finds its package).
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        xla = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        ]
        xla.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(xla)
    env["PYTHONPATH"] = ":".join(
        p
        for p in [*prepend_path, *env.get("PYTHONPATH", "").split(":")]
        if p and "axon" not in p
    )
    return env
