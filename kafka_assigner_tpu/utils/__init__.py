from .javahash import java_string_hash, topic_start_index

__all__ = ["java_string_hash", "topic_start_index"]
