"""Persistent XLA compilation-cache switch, shared by every on-chip tool.

The deployment target compiles jit programs remotely (the PJRT plugin ships
HLO over the device tunnel); the headline solve's cold compile is therefore
the dominant — and least predictable — cost of any fresh process. Pointing
every tool (bench.py, scripts/tpu_compile_probe.py,
scripts/validate_pallas_tpu.py) at one on-disk cache means the first
successful compile of each (program, shape) signature is paid exactly once
per machine, not once per process: a probe run seeds the cache the
end-of-round bench then hits.

The reference has no analogue (a JVM CLI has no compile step); this is
TPU-runtime infrastructure in the sense of SURVEY.md §5's build notes.
"""
from __future__ import annotations

import os
import sys

from .env import env_bool, env_str

#: Default cache location: sibling of this package, i.e. <repo>/.jax_cache
#: (gitignored). Override with KA_COMPILE_CACHE_DIR; disable with
#: KA_COMPILE_CACHE=0.
_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_persistent_cache(cache_dir: str | None = None) -> bool:
    """Turn on jax's persistent compilation cache; returns success.

    Never fatal: the cache is an optimization, and a tool must not lose its
    measurement because the cache directory is unwritable.
    """
    if not env_bool("KA_COMPILE_CACHE"):
        return False
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            cache_dir or env_str("KA_COMPILE_CACHE_DIR") or _DEFAULT_DIR,
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return True
    except Exception as e:
        print(f"compile cache unavailable: {e}", file=sys.stderr)
        return False
