"""Per-phase wall-clock timers and an optional device profiler hook.

The reference has no tracing or profiling of any kind (SURVEY.md §5) — solve
latency is our headline metric, so phases are first-class observable here.

Usage::

    timers = Timers()
    with timers.phase("encode"):
        ...
    timers.report()            # -> {"encode": 12.3, ...} and stderr log

``device_trace`` wraps ``jax.profiler.trace`` so a TPU trace of a solve can
be captured with one context manager (view with TensorBoard/XProf).
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator

from .logging import get_logger

_log = get_logger("timers")


class Timers:
    def __init__(self) -> None:
        self.ms: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed = (time.perf_counter() - t0) * 1000.0
            self.ms[name] = self.ms.get(name, 0.0) + elapsed
            _log.info("phase %s: %.2f ms", name, elapsed)

    def report(self) -> Dict[str, float]:
        return dict(self.ms)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a device profile (TPU trace) for everything in the block."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
