"""DEPRECATED compat shim over the ``obs/`` observability subsystem.

``Timers`` and ``device_trace`` predate ``obs/`` (they were the repo's only
instrumentation — SURVEY.md §5). Both now live there: phases are
:func:`kafka_assigner_tpu.obs.span` spans, the device profiler hook is
:mod:`kafka_assigner_tpu.obs.profile`. This module stays importable so
external scripts keep working, and ``Timers`` keeps its exact contract (a
live ``.ms`` dict accumulating per-phase wall milliseconds, obs enabled or
not) — but new code should use ``obs`` directly::

    from kafka_assigner_tpu.obs import span
    with span("encode"):
        ...
"""
from __future__ import annotations

import contextlib
from typing import Dict, Iterator

from ..obs.profile import device_trace  # noqa: F401  (compat re-export)
from ..obs.trace import span
from .logging import get_logger

_log = get_logger("timers")


class Timers:
    """Deprecated: a bag of named phase timers backed by obs spans.

    ``.ms`` accumulates per-phase wall milliseconds exactly as before (the
    ``TpuSolver.last_timers`` live-reference contract); when an obs run
    capture is active each phase additionally records a span.
    """

    def __init__(self) -> None:
        self.ms: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        with span(name, sink=self.ms, key=name, log=_log):
            yield

    def report(self) -> Dict[str, float]:
        return dict(self.ms)
