"""L4 driver: mode dispatch, broker-set resolution, rack-map construction and
the reassignment pipeline — the tpu-framework counterpart of
``KafkaAssignmentGenerator.java`` with the ZooKeeper layer behind the
``MetadataBackend`` protocol.

All human-readable banners and JSON payloads match the reference byte-for-byte
("CURRENT ASSIGNMENT:", "CURRENT BROKERS:", "NEW ASSIGNMENT:\\n<json>"); JSON
goes to stdout, diagnostics to stderr (the reference achieves the same
separation via log4j ERROR-only console config, ``src/main/config/
log4j.properties:21-31``).
"""
from __future__ import annotations

import dataclasses
import json
import os
import queue
import sys
import threading
from typing import Dict, List, Optional, Sequence, Set, TextIO, Tuple

from .assigner import TopicAssigner
from .errors import IngestError, SolveError
from .obs import gauge_set, obs_active, span
from .solvers.base import Context
from .io.base import BrokerInfo, MetadataBackend
from .io.zkwire import ZkWireError
from .validate import validate_cluster_feasibility
from .io.json_io import (
    format_brokers_json,
    format_reassignment_json,
    format_reassignment_pairs,
)


def broker_hostnames_to_ids(
    brokers: Sequence[BrokerInfo], hostnames: Set[str], check_presence: bool
) -> Set[int]:
    """Hostname → broker-id resolution (``KafkaAssignmentGenerator.java:189-204``):
    strict all-must-resolve for inclusion sets, lenient for exclusion sets."""
    ids = {b.id for b in brokers if b.host in hostnames}
    if check_presence and len(hostnames) != len(ids):
        raise ValueError(f"Some hostnames could not be found! We found: {sorted(ids)}")
    return ids


def resolve_broker_ids(
    brokers: Sequence[BrokerInfo],
    integer_broker_ids: Optional[str],
    broker_hostnames: Optional[str],
) -> Set[int]:
    """``--integer_broker_ids`` parse or ``--broker_hosts`` lookup
    (``KafkaAssignmentGenerator.java:206-225``). ``brokers`` is the live-broker
    list, fetched once by the caller."""
    if integer_broker_ids:
        out = set()
        for tok in integer_broker_ids.split(","):
            try:
                out.add(int(tok))
            except ValueError:
                raise ValueError(f"Invalid broker ID: {tok}") from None
        return out
    if broker_hostnames:
        hostnames = set(broker_hostnames.split(","))
        return broker_hostnames_to_ids(brokers, hostnames, True)
    return set()


def resolve_excluded_broker_ids(
    brokers: Sequence[BrokerInfo], broker_hosts_to_remove: Optional[str]
) -> Set[int]:
    """``--broker_hosts_to_remove`` lookup, lenient on unknown hosts
    (``KafkaAssignmentGenerator.java:227-236``)."""
    if broker_hosts_to_remove:
        hostnames = set(broker_hosts_to_remove.split(","))
        return broker_hostnames_to_ids(brokers, hostnames, False)
    return set()


def build_rack_assignment(
    brokers: Sequence[BrokerInfo], disable_rack_awareness: bool
) -> Dict[int, str]:
    """Broker-id → rack map; empty when rack-awareness is disabled
    (``KafkaAssignmentGenerator.java:238-250``)."""
    if disable_rack_awareness:
        return {}
    return {b.id: b.rack for b in brokers if b.rack is not None}


def print_current_assignment(
    backend: MetadataBackend,
    topics: Optional[Sequence[str]],
    out: Optional[TextIO] = None,
) -> None:
    """Mode 1 (``KafkaAssignmentGenerator.java:103-111``): snapshot of the
    existing assignment in Kafka-parseable JSON — also the rollback artifact
    printed before every reassignment."""
    out = out if out is not None else sys.stdout
    topic_list = list(topics) if topics is not None else backend.all_topics()
    assignment = backend.partition_assignment(topic_list)
    print("CURRENT ASSIGNMENT:", file=out)
    print(format_reassignment_json(assignment, topic_order=topic_list), file=out)


def print_current_brokers(
    backend: MetadataBackend,
    out: Optional[TextIO] = None,
    live_brokers: Optional[Sequence[BrokerInfo]] = None,
) -> None:
    """Mode 2 (``KafkaAssignmentGenerator.java:113-129``)."""
    out = out if out is not None else sys.stdout
    if live_brokers is None:
        live_brokers = backend.brokers()
    print("CURRENT BROKERS:", file=out)
    print(format_brokers_json(live_brokers), file=out)


def load_scenario_file(
    path: str, live_brokers: Sequence[BrokerInfo]
) -> List[List[int]]:
    """Parse a ``--scenario_file``: a JSON array of removal scenarios, each
    an array of broker ids (integers) and/or hostnames (strings), e.g.
    ``[[1,2],[3],["kafka7.example.com","kafka8.example.com"]]``.

    Hostnames resolve strictly against the live broker list (same contract
    as ``--broker_hosts``, ``KafkaAssignmentGenerator.java:189-204``);
    unknown ids or hosts are errors — a silently dropped broker would rank
    a different scenario than the operator asked about.
    """
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list) or not all(
        isinstance(s, list) for s in data
    ):
        raise ValueError(
            f"scenario file {path!r} must be a JSON array of arrays of "
            "broker ids or hostnames"
        )
    by_host = {b.host: b.id for b in live_brokers}
    known = {b.id for b in live_brokers}
    scenarios: List[List[int]] = []
    for s in data:
        ids: List[int] = []
        for entry in s:
            if isinstance(entry, bool) or not isinstance(entry, (int, str)):
                raise ValueError(
                    f"scenario file {path!r}: invalid broker entry {entry!r}"
                )
            if isinstance(entry, str):
                if entry not in by_host:
                    raise ValueError(
                        f"scenario file {path!r}: unknown broker host "
                        f"{entry!r}"
                    )
                ids.append(by_host[entry])
            else:
                if entry not in known:
                    raise ValueError(
                        f"scenario file {path!r}: unknown broker id {entry}"
                    )
                ids.append(int(entry))
        deduped = sorted(set(ids))
        scenarios.append(deduped)
    return scenarios


def print_decommission_ranking(
    backend: MetadataBackend,
    topics: Optional[Sequence[str]],
    candidate_brokers: Optional[Set[int]],
    rack_assignment: Dict[int, str],
    desired_replication_factor: int,
    out: Optional[TextIO] = None,
    live_brokers: Optional[Sequence[BrokerInfo]] = None,
    scenario_file: Optional[str] = None,
) -> None:
    """RANK_DECOMMISSION: one batched what-if sweep over candidate
    broker removals, printed least-disruptive-first as a JSON array on
    stdout. Default: every live broker as a singleton scenario;
    ``--scenario_file`` ranks arbitrary removal SETS (pairs, whole racks,
    ...) in the same single sweep — ``evaluate_removal_scenarios`` always
    took arbitrary sets; this exposes it (VERDICT r3 item 10).

    The reference can only answer this one process run at a time
    (``--broker_hosts_to_remove`` + eyeballing the JSON); the sweep solves
    all candidates at once (BASELINE config 5).
    """
    from .parallel.whatif import (
        evaluate_removal_scenarios,
        rank_decommission_candidates,
    )

    out = out if out is not None else sys.stdout
    if live_brokers is None:
        live_brokers = backend.brokers()
    brokers = {b.id for b in live_brokers}
    topic_list = list(topics) if topics is not None else backend.all_topics()
    initial = backend.partition_assignment(topic_list)

    # Spread the sweep across every visible device (the scenario axis is
    # embarrassingly parallel; sharded == unsharded is test-pinned). The
    # library call stays explicit — only the CLI auto-meshes.
    import jax

    mesh = None
    if len(jax.devices()) > 1:
        from .parallel.mesh import build_mesh

        mesh = build_mesh()

    topic_map = {t: initial[t] for t in topic_list}
    racks = {k: v for k, v in rack_assignment.items() if k in brokers}
    if scenario_file is not None:
        scenarios = load_scenario_file(scenario_file, live_brokers)
        with span("whatif/rank"):
            results = evaluate_removal_scenarios(
                topic_map, brokers, racks, scenarios,
                desired_replication_factor, mesh=mesh,
            )
        ranked = sorted(
            results,
            key=lambda r: (not r.feasible, r.moved_replicas, r.removed),
        )
        rows = [
            {
                "brokers": list(r.removed),
                "moved_replicas": r.moved_replicas,
                "feasible": r.feasible,
                "max_node_load": r.max_node_load,
            }
            for r in ranked
        ]
    else:
        with span("whatif/rank"):
            ranked = rank_decommission_candidates(
                topic_map, brokers, racks,
                sorted(candidate_brokers) if candidate_brokers else None,
                desired_replication_factor, mesh=mesh,
            )
        rows = [
            {
                "broker": r.removed[0],
                "moved_replicas": r.moved_replicas,
                "feasible": r.feasible,
                "max_node_load": r.max_node_load,
            }
            for r in ranked
        ]
    print("DECOMMISSION RANKING:", file=out)
    # kalint: disable=KA005 -- ranking rows are this mode's own format, not a Kafka plan payload
    print(json.dumps(rows, separators=(",", ":")), file=out)


def print_fresh_assignment(
    topics: Sequence[str],
    partition_count: int,
    replication_factor: int,
    live_brokers: Sequence[BrokerInfo],
    rack_assignment: Dict[int, str],
    out: Optional[TextIO] = None,
) -> None:
    """PRINT_FRESH_ASSIGNMENT: place new topics from scratch (no current
    assignment) and emit Kafka-parseable reassignment JSON — a capability the
    reference lacks (its greedy first-fit dead-ends on fresh placements at
    moderate saturation; the balance-wave chain does not, see
    solvers/tpu.py:fresh_assignment)."""
    from .solvers.base import get_solver

    out = out if out is not None else sys.stdout
    brokers = {b.id for b in live_brokers}
    solver = get_solver("tpu")  # clean NotImplementedError when jax is absent
    context = Context()
    with span("plan/fresh"):
        pairs = [
            (
                topic,
                solver.fresh_assignment(
                    topic, partition_count, brokers, rack_assignment,
                    replication_factor, context,
                ),
            )
            for topic in topics
        ]
    if obs_active():
        record_plan_stats({}, pairs)
    print("FRESH ASSIGNMENT:\n" + format_reassignment_pairs(pairs), file=out)


def record_plan_stats(
    initial: Dict[str, Dict[int, List[int]]],
    final_pairs: Sequence[tuple],
) -> None:
    """Plan-disruption gauges (``plan.*`` → the run report's ``plan``
    section): moved replicas (new broker acquisitions, the what-if sweep's
    disruption metric), leader churn (partitions whose preferred leader —
    replica slot 0 — changed), and plan size. Call sites gate on
    ``obs_active`` so the disabled mode never pays the diff."""
    moves = churn = partitions = 0
    for topic, new in final_pairs:
        old = initial.get(topic, {})
        for p, replicas in new.items():
            partitions += 1
            before = list(old.get(p, []))
            moves += len(set(replicas) - set(before))
            lead_new = replicas[0] if replicas else None
            lead_old = before[0] if before else None
            if lead_new != lead_old:
                churn += 1
    gauge_set("plan.moves", moves)
    gauge_set("plan.leader_churn", churn)
    gauge_set("plan.topics", len(final_pairs))
    gauge_set("plan.partitions", partitions)


def _is_ingest_failure(e: BaseException) -> bool:
    """Failure classes the metadata phase tags as :class:`IngestError`:
    the wire client's errors, socket/file errors, snapshot KeyErrors — and
    kazoo's exception tree, matched by ancestor NAME so the tagging works
    whether or not the optional kazoo package is importable here."""
    if isinstance(e, (ZkWireError, OSError, KeyError)):
        return True
    return any(c.__name__ == "KazooException" for c in type(e).__mro__)


#: Sentinel closing the ingest stream (the producer finished cleanly).
_INGEST_DONE = object()


#: Live warm-up threads (in-process test/soak hygiene: a daemon thread that
#: outlives its run would write metrics into the NEXT run's capture;
#: ``join_warmup_threads`` lets multi-run processes drain them).
_LIVE_WARMUPS: List[threading.Thread] = []
_WARMUP_LOCK = threading.Lock()


def join_warmup_threads(timeout: float = 60.0) -> None:
    """Wait for any still-running warm-up threads (no-op in the common
    case: a correctly-predicted warm-up finishes before its own solve
    does). Multi-run processes — the test suite, the chaos soak — call
    this between runs so one run's background compile can never bleed
    metrics or store writes into the next."""
    with _WARMUP_LOCK:
        threads, _LIVE_WARMUPS[:] = list(_LIVE_WARMUPS), []
    for t in threads:
        t.join(timeout)


def _start_warmup_thread(acc, n_topics: int, desired_rf: int):
    """Spawn the ingest-overlapped device warm-up (ISSUE 6) once the first
    encoded chunk reveals the partition/width buckets: a daemon thread asks
    the program store to make the predicted solve programs resident (load or
    compile) while the remaining metadata is still in flight.

    Failure contract: a warm-up crash of any kind (including the injected
    ``warmup:i=crash`` fault class, consumed HERE on the orchestration
    thread so per-scope fault indexes stay coherent across a process's
    runs) degrades to the normal cold path with a stderr warning and a
    ``warmup.failures`` count, never to a failed solve. Returns the thread,
    or None when warm-up is disabled (``KA_WARMUP=0``), nothing was encoded
    yet, or the injected crash fired.
    """
    import time

    from .obs.metrics import counter_add
    from .obs.trace import record_span
    from .utils.env import env_bool

    if not env_bool("KA_WARMUP"):
        return None
    shape = acc.peek_shape()
    if shape is None:
        return None
    p_pad, width = shape
    rf = desired_rf if desired_rf > 0 else width

    try:
        from .faults.inject import fault_point

        # Injected warm-up crash (KA_FAULTS_SPEC warmup:i=crash): the chaos
        # matrix's proof that a dead warm-up is invisible in the plan bytes.
        fault_point("warmup")
    except BaseException as e:
        counter_add("warmup.failures")
        print(
            f"kafka-assigner: warm-up failed ({type(e).__name__}: {e}); "
            "continuing on the cold compile path",
            file=sys.stderr,
        )
        return None

    def _warm() -> None:
        t0 = time.perf_counter()
        ok = True
        try:
            from .solvers.warmup import warm_solver_programs

            outcomes = warm_solver_programs(
                acc.cluster, n_topics, p_pad, width, rf
            )
            for name, outcome in outcomes.items():
                counter_add(f"warmup.{outcome}")
                if outcome == "error":
                    ok = False
        except BaseException as e:
            ok = False
            counter_add("warmup.failures")
            print(
                f"kafka-assigner: warm-up failed ({type(e).__name__}: {e}); "
                "continuing on the cold compile path",
                file=sys.stderr,
            )
        finally:
            record_span("warmup", (time.perf_counter() - t0) * 1000.0, ok)

    t = threading.Thread(target=_warm, name="ka-warmup", daemon=True)
    with _WARMUP_LOCK:
        _LIVE_WARMUPS.append(t)
    t.start()
    return t


@dataclasses.dataclass
class Degradation:
    """What a ``--failure-policy best-effort`` run survived: the record the
    CLI turns into the degraded-success exit code and the run report's
    ``ingest.topics_skipped``/``solve.fallbacks`` accounting."""

    topics_skipped: List[str] = dataclasses.field(default_factory=list)
    solve_fallbacks: int = 0

    def any(self) -> bool:
        return bool(self.topics_skipped or self.solve_fallbacks)


def _note_skipped(topic: str, skipped: List[str]) -> None:
    """Record one vanished topic — loud on stderr per occurrence (the
    operator must see exactly what the plan will NOT cover)."""
    skipped.append(topic)
    print(
        f"kafka-assigner: best-effort: topic {topic!r} vanished during the "
        "metadata scan; skipping it",
        file=sys.stderr,
    )


def stream_initial_assignment(
    backend: MetadataBackend,
    topic_list: Sequence[str],
    brokers: Optional[Set[int]] = None,
    rack_assignment: Optional[Dict[int, str]] = None,
    want_encode: bool = False,
    failure_policy: str = "strict",
    skipped: Optional[List[str]] = None,
    desired_rf: int = -1,
) -> Tuple[Dict[str, Dict[int, List[int]]], Optional[tuple]]:
    """Metadata ingest overlapped with host encode.

    A producer thread drains ``backend.fetch_topics`` (pipelined reads on
    live backends, ``KA_ZK_PIPELINE``) into a queue while this — the
    orchestration — thread folds arrived topics into the batched host encode
    in ``KA_ZK_INGEST_CHUNK``-sized chunks, so the encode work that used to
    start only after the last ZooKeeper round-trip now hides inside the
    fetch. Returns ``(initial, preencoded)`` where ``initial`` is exactly
    ``backend.partition_assignment(topic_list)`` and ``preencoded`` is the
    ``encode_topic_group`` result for the same topic order (or None when
    encoding was not requested or streaming is unavailable/disabled —
    callers fall back to encoding inside the solver, identical output either
    way).

    ``desired_rf``: the CLI's ``--desired_replication_factor`` (or -1 for
    "infer") — only a HINT here, consumed by the ingest-overlapped warm-up
    (ISSUE 6) to predict the solve's replica-width bucket before RF
    inference runs; it never changes the returned data.

    ``failure_policy="best-effort"`` (ISSUE 5): a topic that vanishes
    mid-scan — deleted between the topic listing and its metadata read — is
    skipped instead of aborting the ingest: it is appended to the caller's
    ``skipped`` list (and warned per occurrence on stderr), left out of
    ``initial`` AND of the preencode, and the stream keeps flowing. The
    returned pair then covers exactly ``topic_list`` minus the skipped
    occurrences, in order. Backends predating the ``missing=`` parameter
    degrade to strict with a stderr notice.

    Failure contract (strict, and every non-missing failure): a
    producer-side exception (missing znode, wire error, missing snapshot
    topic) re-raises here, on the orchestration thread, so tracing spans and
    the run report see it exactly like a serial fetch failure. A
    CONSUMER-side abort (encode error, KeyboardInterrupt) leaves the daemon
    producer blocked on its socket; it is not joined — the CLI's
    ``backend.close()`` on the unwind path closes that socket, which errors
    the producer out promptly (possible stderr noise, never a hang past the
    socket timeout).
    """
    from .utils.env import env_bool, env_int

    best_effort = failure_policy == "best-effort"
    if skipped is None:
        skipped = []
    fetch = getattr(backend, "fetch_topics", None)

    def _open_stream():
        if best_effort:
            try:
                return fetch(topic_list, missing="skip")
            except TypeError:
                # Third-party backend predating the degradation contract:
                # strict semantics, said out loud rather than silently.
                print(
                    "kafka-assigner: this metadata backend predates the "
                    "missing-topic degradation contract; --failure-policy "
                    "best-effort degrades to strict for ingest",
                    file=sys.stderr,
                )
        return fetch(topic_list)

    if fetch is None or not env_bool("KA_ZK_OVERLAP"):
        if fetch is not None and best_effort:
            # Overlap disabled but degradation requested: drain the stream
            # inline (identical output to partition_assignment) so vanished
            # topics can still be skipped per entry.
            initial = {}
            with span("ingest/stream"):
                for topic, parts in _open_stream():
                    if parts is None:
                        _note_skipped(topic, skipped)
                        continue
                    initial[topic] = parts
            if obs_active():
                gauge_set("ingest.topics", len(initial))
                gauge_set("ingest.topics_skipped", len(skipped))
            return initial, None
        return backend.partition_assignment(topic_list), None

    acc = None
    if want_encode and brokers is not None:
        from .models.problem import GroupEncodeAccumulator

        acc = GroupEncodeAccumulator(rack_assignment or {}, brokers)

    if acc is None:
        # Nothing to overlap: the pipelined fetch is the whole win, so drain
        # the stream inline — no producer thread, no queue hops.
        initial = {}
        streamed = 0
        with span("ingest/stream"):
            for topic, parts in _open_stream():
                if parts is None:
                    _note_skipped(topic, skipped)
                    continue
                initial[topic] = parts
                streamed += 1
        if obs_active():
            gauge_set("ingest.topics", streamed)
            if best_effort:
                gauge_set("ingest.topics_skipped", len(skipped))
        return initial, None

    q: "queue.Queue" = queue.Queue()
    producer_done = threading.Event()

    def _produce() -> None:
        try:
            for item in _open_stream():
                q.put(item)
            q.put(_INGEST_DONE)
        except BaseException as e:  # re-raised on the consumer side
            q.put(e)
        finally:
            producer_done.set()

    t = threading.Thread(target=_produce, name="zk-ingest", daemon=True)
    chunk_size = env_int("KA_ZK_INGEST_CHUNK")
    initial: Dict[str, Dict[int, List[int]]] = {}
    chunk: List[tuple] = []
    streamed = 0
    overlap_ms = 0.0
    # At most ONE start attempt per run: a crashed attempt (the injected
    # warmup:i=crash class) must degrade to the cold path, not be silently
    # retried by the tail-chunk site below.
    warmup_attempted = False
    with span("ingest/stream"):
        t.start()
        while True:
            item = q.get()
            if item is _INGEST_DONE:
                break
            if isinstance(item, BaseException):
                t.join()
                raise item
            topic, parts = item
            if parts is None:  # vanished mid-scan (best-effort stream)
                _note_skipped(topic, skipped)
                continue
            initial[topic] = parts
            streamed += 1
            if acc is not None:
                chunk.append((topic, parts))
                if len(chunk) >= chunk_size:
                    overlapping = not producer_done.is_set()
                    before = acc.encode_ms
                    acc.add(chunk)
                    if overlapping:
                        overlap_ms += acc.encode_ms - before
                    chunk = []
                    if not warmup_attempted:
                        # First chunk encoded: the bucket signature is now
                        # predictable — start making the solve's programs
                        # resident while the rest of the metadata streams.
                        warmup_attempted = True
                        _start_warmup_thread(acc, len(topic_list), desired_rf)
        t.join()
        if acc is not None and chunk:
            acc.add(chunk)
        if acc is not None and not warmup_attempted:
            # Short run (everything fit in one tail chunk): still warm —
            # load/compile overlaps the feasibility pass and rollback
            # emission, and the solve's per-program lock joins in on the
            # same executable instead of compiling twice.
            warmup_attempted = True
            _start_warmup_thread(acc, len(topic_list), desired_rf)
    preencoded = acc.finish() if acc is not None else None
    if obs_active():
        gauge_set("ingest.topics", streamed)
        if best_effort:
            gauge_set("ingest.topics_skipped", len(skipped))
        if acc is not None:
            gauge_set("ingest.encode_ms", round(acc.encode_ms, 3))
            gauge_set("ingest.overlap_ms", round(overlap_ms, 3))
    return initial, preencoded


def print_least_disruptive_reassignment(
    backend: MetadataBackend,
    topics: Optional[Sequence[str]],
    specified_brokers: Set[int],
    excluded_brokers: Set[int],
    rack_assignment: Dict[int, str],
    desired_replication_factor: int,
    solver: str = "greedy",
    out: Optional[TextIO] = None,
    live_brokers: Optional[Sequence[BrokerInfo]] = None,
    context_file: Optional[str] = None,
    failure_policy: str = "strict",
    degradation: Optional[Degradation] = None,
    ingest=None,
) -> Dict[str, Dict[int, List[int]]]:
    """Mode 3 — the reassignment driver (``KafkaAssignmentGenerator.java:131-187``):
    resolve the broker set (all-live default, minus exclusions), choose topics,
    print the current assignment for rollback, then solve topic-by-topic
    through the selected backend and emit the combined reassignment JSON.

    Metadata is read exactly once: the rollback snapshot and the solver both
    see the same ``initial`` assignment (the reference reads ZK twice,
    ``KafkaAssignmentGenerator.java:160,163`` — a race we close).

    ``failure_policy="best-effort"`` (ISSUE 5): topics that vanish mid-scan
    are skipped (reported per occurrence on stderr and in the run report's
    ``ingest.topics_skipped``), and a crashed non-greedy solve falls back to
    the greedy solver per group (``solve.fallbacks``); what the run survived
    is written into the caller-supplied ``degradation`` record, which the
    CLI turns into the degraded-success exit code. Unrecoverable failures
    are re-raised phase-tagged (:class:`~.errors.IngestError` /
    :class:`~.errors.SolveError`) so the CLI exit code names the phase.

    ``ingest``: optional replacement for the metadata read — a callable
    ``(topic_list) -> (initial, preencoded)`` with exactly
    :func:`stream_initial_assignment`'s return contract. The resident
    daemon (ISSUE 8) injects its watch-maintained cache + incremental
    group encode here, so a served ``/plan`` runs the identical pipeline
    (same rollback snapshot, feasibility pass, solve and emission —
    byte-identical stdout) without re-reading or re-encoding the world."""
    out = out if out is not None else sys.stdout
    broker_set = set(specified_brokers)
    if not broker_set:
        if live_brokers is None:
            live_brokers = backend.brokers()
        broker_set = {b.id for b in live_brokers}
    brokers = broker_set - excluded_brokers
    rack_assignment = {k: v for k, v in rack_assignment.items() if k in brokers}

    topic_list = list(topics) if topics is not None else backend.all_topics()

    skipped: List[str] = []
    with span("metadata/assignment"):
        # Pipelined ingest overlapped with host encode: the TPU path gets the
        # batched group encode built WHILE ZooKeeper responses stream in (the
        # solver then skips its own encode — identical arrays by
        # construction); other solvers still get the pipelined fetch.
        try:
            if ingest is not None:
                initial, preencoded = ingest(topic_list)
            else:
                initial, preencoded = stream_initial_assignment(
                    backend, topic_list, brokers, rack_assignment,
                    want_encode=(solver == "tpu"),
                    failure_policy=failure_policy, skipped=skipped,
                    desired_rf=desired_replication_factor,
                )
        except Exception as e:
            if not _is_ingest_failure(e):
                raise
            raise IngestError(f"metadata ingest failed: {e}") from e
    if skipped:
        # The plan can only cover what survived the scan. Filter by presence
        # in the ingested map (duplicate-occurrence-safe).
        topic_list = [t for t in topic_list if t in initial]
        if any(t in initial for t in skipped):
            # Duplicate-occurrence edge (a name both vanished AND resolved
            # within one scan): the preencode's occurrence list no longer
            # matches the filtered one — drop it and let the solver
            # re-encode. The common case (a name wholly vanished) keeps the
            # overlap's preencode: the accumulator only ever saw the
            # surviving occurrences, which IS the filtered list.
            preencoded = None
        # A name still present in the plan did not degrade it: count only
        # the occurrences the plan actually lost (and re-stamp the gauge).
        skipped = [t for t in skipped if t not in initial]
        if obs_active():
            gauge_set("ingest.topics_skipped", len(skipped))
            # The degraded-run DIFF, not just the count (ISSUE 7 satellite):
            # the run report's plan section names exactly which topics the
            # plan does NOT cover, so the execute-side verify pass (and any
            # supervisor) can separate "unplanned by policy" from "drifted".
            gauge_set("plan.unplanned_topics", sorted(set(skipped)))
    if skipped:
        print(
            f"kafka-assigner: best-effort: {len(skipped)} topic read(s) "
            f"vanished mid-scan; planning the remaining "
            f"{len(topic_list)} topic(s)",
            file=sys.stderr,
        )

    # Rollback snapshot first (KafkaAssignmentGenerator.java:159-160), from
    # the same read the solver uses.
    print("CURRENT ASSIGNMENT:", file=out)
    print(format_reassignment_json(initial, topic_order=topic_list), file=out)

    # Up-front feasibility report on stderr — the reference only discovers
    # infeasibility mid-solve (KafkaAssignmentStrategy.java:183-184); the
    # solver's hard error remains the backstop.
    with span("feasibility"):
        issues = validate_cluster_feasibility(
            [(t, initial[t]) for t in topic_list], brokers, rack_assignment,
            desired_replication_factor,
        )
    for issue in issues:
        # Straight to stderr, not through the (default-ERROR) logger: the
        # operator about to apply a reassignment must see these unprompted,
        # while stdout stays machine-parseable.
        print(
            f"feasibility {issue.severity}: topic {issue.topic}: {issue.message}",
            file=sys.stderr,
        )

    # Topics flow through one shared-context assigner in CLI order
    # (KafkaAssignmentGenerator.java:166-176), duplicates solved per
    # occurrence like the reference loop. The TPU backend folds the whole
    # loop into a single device dispatch with identical output.
    assigner = TopicAssigner(solver=solver, failure_policy=failure_policy)
    if context_file is not None and os.path.exists(context_file):
        try:
            assigner.context = Context.load(context_file)
        except (ValueError, KeyError, TypeError, AttributeError, OSError) as e:
            raise ValueError(
                f"invalid leadership context file {context_file!r}: {e}"
            ) from e
    with span("plan/solve"):
        try:
            final_pairs = assigner.generate_assignments(
                [(topic, initial[topic]) for topic in topic_list],
                brokers,
                rack_assignment,
                desired_replication_factor,
                preencoded=preencoded,
            )
        except (ValueError, SolveError):
            # ValueError = input validation (RF bounds, infeasibility):
            # keeps its plain type for library callers and the validation
            # exit code. SolveError = an already-tagged backend crash.
            raise
        except Exception as e:
            raise SolveError(
                f"solver backend crashed ({type(e).__name__}): {e}"
            ) from e
    if degradation is not None:
        degradation.topics_skipped = list(skipped)
        degradation.solve_fallbacks = assigner.fallbacks
    if obs_active():
        record_plan_stats(initial, final_pairs)
    with span("plan/emit"):
        payload = format_reassignment_pairs(final_pairs)
    print("NEW ASSIGNMENT:\n" + payload, file=out)
    # Save after the payload is out: a failing save (unwritable path, disk
    # full) must never discard a completed solve.
    if context_file is not None:
        assigner.context.save(context_file)
    return dict(final_pairs)
