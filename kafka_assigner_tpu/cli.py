"""CLI entry point — flag-for-flag compatible with the reference
(``KafkaAssignmentGenerator.java:53-101, 256-299``), plus ``--solver``.

Usage (mirrors ``KafkaAssignmentGenerator.java:39-47``)::

    kafka-assignment-generator \
        --zk_string zkhost:2181 \
        --mode PRINT_REASSIGNMENT \
        --broker_hosts host1,host2,host3 \
        --broker_hosts_to_remove misbehaving_host1

``--zk_string`` additionally accepts ``file://cluster.json`` (or any ``*.json``
path) for hermetic snapshot runs — the offline mode the reference lacks.

Divergences from the reference, on purpose:
  - the mutual-exclusion error names the real flags (the reference's message
    cites nonexistent ``--kafka_assigner_*`` names — latent bug,
    ``KafkaAssignmentGenerator.java:263-265``);
  - bad usage exits with status 1 after printing usage to stderr (the
    reference returns 0, ``KafkaAssignmentGenerator.java:266-270``);
  - failure classes exit with DISTINCT documented codes (the ``EXIT_*``
    constants below; README "Failure model"): ingest vs. solve vs.
    validation vs. best-effort degraded success, so supervisors can react
    without scraping stderr.
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .errors import IngestError, SolveError
from .generator import (
    Degradation,
    build_rack_assignment,
    print_current_assignment,
    print_current_brokers,
    print_decommission_ranking,
    print_fresh_assignment,
    print_least_disruptive_reassignment,
    resolve_broker_ids,
    resolve_excluded_broker_ids,
)
from .io.base import open_backend
from .io.zkwire import ZkWireError
from .solvers.base import get_solver

# Documented exit codes (README "Failure model"): the reference collapses
# every failure into one generic nonzero JVM exit, so a supervising process
# cannot distinguish "the quorum was down" from "the plan is infeasible"
# without scraping stderr. 2 is left to argparse (its own usage-error code).
EXIT_OK = 0            # plan emitted / executed+verified, nothing degraded
EXIT_USAGE = 1         # bad flag combination / unavailable backend refusal
EXIT_INGEST = 3        # metadata ingest failed past the retry budget
EXIT_SOLVE = 4         # solver crashed (and best-effort fallback too)
EXIT_VALIDATION = 5    # input/validation failure (RF bounds, unknown hosts)
EXIT_DEGRADED = 6      # best-effort success: plan emitted/executed, degraded
EXIT_VERIFY = 7        # ka-execute: verify-after-move found the cluster
                       # diverged from the plan (beyond recorded skips)
EXIT_EXECUTE = 8       # ka-execute: halted mid-plan under strict policy;
                       # the journal is resumable via --resume

# The reference's three modes (KafkaAssignmentGenerator.java:86-101) plus
# RANK_DECOMMISSION, which exposes the what-if fleet: it solves one candidate
# broker-removal scenario per live broker (or per --broker_hosts candidate)
# in a single batched sweep and prints the ranking least-disruptive-first.
MODES = (
    "PRINT_CURRENT_ASSIGNMENT",
    "PRINT_CURRENT_BROKERS",
    "PRINT_REASSIGNMENT",
    "RANK_DECOMMISSION",
    "PRINT_FRESH_ASSIGNMENT",
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kafka-assignment-generator",
        description="Prints assignments of topic partition replicas to brokers "
        "in Kafka-parseable JSON.",
        add_help=True,
    )
    p.add_argument("--zk_string", default=None,
                   help="ZK quorum as comma-separated host:port pairs, or a "
                        "file://cluster.json snapshot")
    p.add_argument("--mode", default=None, choices=MODES,
                   help="the mode to run")
    p.add_argument("--integer_broker_ids", default=None,
                   help="comma-separated list of Kafka broker IDs (integers)")
    p.add_argument("--broker_hosts", default=None,
                   help="comma-separated list of broker hostnames (instead of broker IDs)")
    p.add_argument("--broker_hosts_to_remove", default=None,
                   help="comma-separated list of broker hostnames to exclude")
    p.add_argument("--topics", default=None,
                   help="comma-separated list of topics")
    p.add_argument("--desired_replication_factor", type=int, default=-1,
                   help="used for changing replication factor for topics; "
                        "if not present it will use the existing number")
    p.add_argument("--disable_rack_awareness", action="store_true",
                   help="set to true to ignore rack configurations")
    p.add_argument("--solver", default="greedy",
                   choices=("greedy", "native", "tpu"),
                   help="assignment backend: reference-faithful greedy "
                        "(python), the same algorithm as native C++, or the "
                        "TPU (JAX/XLA) solver")
    p.add_argument("--partition_count", type=int, default=None,
                   help="PRINT_FRESH_ASSIGNMENT: number of partitions to "
                        "place for each --topics entry")
    p.add_argument("--scenario_file", default=None, metavar="PATH",
                   help="RANK_DECOMMISSION: JSON array of removal scenarios "
                        "(arrays of broker ids and/or hostnames, e.g. "
                        '[[1,2],["host7"]]) ranked in one batched sweep '
                        "instead of the default per-broker singleton sweep")
    p.add_argument("--leadership_context", default=None, metavar="PATH",
                   help="persist cross-run leadership counters to PATH "
                        "(loaded if present, saved after PRINT_REASSIGNMENT) "
                        "so repeated partial reassignments keep balancing "
                        "leaders cluster-wide")
    p.add_argument("--failure-policy", dest="failure_policy", default=None,
                   choices=("strict", "best-effort"),
                   help="strict (default): abort on the first unrecoverable "
                        "ingest/solve failure, like the reference. "
                        "best-effort: skip topics that vanish mid-scan and "
                        "fall back to the greedy solver when the TPU solve "
                        "crashes — degradations are reported on stderr and "
                        "in the run report, and the process exits with the "
                        "documented degraded-success code (default: the "
                        "KA_FAILURE_POLICY knob)")
    p.add_argument("--report-json", dest="report_json", default=None,
                   metavar="PATH",
                   help="emit a schema-versioned machine-readable run report "
                        "(tracing spans, metrics, plan stats) to PATH, plus "
                        "a human summary on stderr; implies observability "
                        "collection for this run (see KA_OBS_* knobs)")
    return p


def _prebuild_native() -> None:
    """Best-effort startup build of the native fast paths (ISSUE 14): the
    solve/request paths are load-only (``native/build.py``), so any
    process that wants the C greedy oracle or the boundary codec must
    compile them at startup, before work begins — never under the
    daemon's solve queue or an admitted inflight slot (the deleted
    KA015/KA019 lazy-build chains). Failure degrades exactly like the old
    lazy builds did (device scan / numpy codec), byte-identically."""
    from .native.build import prebuild_native_libraries

    prebuild_native_libraries(err=sys.stderr)


def run_tool(argv: Optional[List[str]] = None) -> int:
    """Parse, validate, connect, dispatch (``KafkaAssignmentGenerator.java:256-299``)."""
    # Persistent XLA compile cache, honoring KA_COMPILE_CACHE (never fatal).
    # Until ISSUE 6 only bench/scripts/conftest enabled it — the production
    # entry point was the one place the cache was off, so every CLI run paid
    # the full backend compile the cache exists to amortize.
    from .utils.compilecache import enable_persistent_cache

    enable_persistent_cache()
    _prebuild_native()

    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        if args.zk_string is None:
            raise ValueError("--zk_string is required")
        if args.mode is None:
            raise ValueError("--mode is required")
        if args.integer_broker_ids is not None and args.broker_hosts is not None:
            raise ValueError(
                "--integer_broker_ids and --broker_hosts cannot be used together!"
            )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        parser.print_usage(sys.stderr)
        return 1

    topics = args.topics.split(",") if args.topics is not None else None

    from .utils.env import env_bool, env_str

    # Observability capture (obs/): explicit opt-in via --report-json, the
    # KA_OBS_REPORT default path, or KA_OBS_ENABLE=1. Off (the default) the
    # dispatch below runs with the obs no-op singletons — byte-identical
    # behavior and output (test-pinned).
    report_path = args.report_json or env_str("KA_OBS_REPORT")
    if report_path is None and not env_bool("KA_OBS_ENABLE"):
        return _dispatch_mode(args, topics)

    from . import obs

    with obs.run_capture() as run:
        status, error, rc = "error", None, 1
        try:
            with obs.span(f"mode/{args.mode}") as sp:
                rc = _dispatch_mode(args, topics)
                if rc not in (EXIT_OK, EXIT_DEGRADED):
                    # Failure signaled by return code, not exception (e.g.
                    # the rack-blind backend refusal): the span must agree
                    # with the report's top-level status. Degraded success
                    # is NOT a span failure — the plan was emitted.
                    sp.fail()
            status = (
                "ok" if rc == EXIT_OK
                else "degraded" if rc == EXIT_DEGRADED
                else "error"
            )
            return rc
        except BaseException as e:
            # The bugfix contract: a solve that raises mid-phase must still
            # flush its spans (their __exit__ ran during unwinding, marked
            # error) and emit the report — losing all timing data on the
            # failing runs is losing it exactly when it matters most.
            error = e
            raise
        finally:
            # Emission must never mask the run's own outcome: a report that
            # cannot even be built (e.g. a non-serializable metric value from
            # a future instrumentation site) is reported on stderr, and the
            # solve's exception/exit status always wins.
            try:
                # The ingest-overlapped warm-up records its outcome
                # counter/span from a daemon thread; under CPU contention
                # that thread can lose the scheduling race with report
                # emission. Drain it first so the report deterministically
                # carries the warm-up outcome.
                from .generator import join_warmup_threads

                join_warmup_threads()
                report = obs.build_report(
                    run, status=status, mode=args.mode,
                    argv=list(argv) if argv is not None else sys.argv[1:],
                    error=error,
                )
                obs.emit_report(report, report_path)
            except Exception as e:
                print(f"obs: could not emit run report: {e}", file=sys.stderr)


def _dispatch_mode(args, topics) -> int:
    """Backend open → mode dispatch → close (the pre-obs ``run_tool`` body)."""
    # Fail fast on an unavailable solver backend, before any metadata is read
    # or partial output emitted.
    get_solver(args.solver)

    backend = open_backend(args.zk_string)
    try:
        live_brokers = backend.brokers()  # single metadata read, reused below
        broker_ids = resolve_broker_ids(
            live_brokers, args.integer_broker_ids, args.broker_hosts
        )
        excluded = resolve_excluded_broker_ids(
            live_brokers, args.broker_hosts_to_remove
        )
        rack_assignment = build_rack_assignment(
            live_brokers, args.disable_rack_awareness
        )
        # A rack-BLIND backend (one that structurally cannot report racks,
        # e.g. confluent-kafka's AdminClient) must not silently produce a
        # rack-unsafe plan from a tool whose headline feature is rack
        # awareness: plan-producing modes refuse unless the operator opts
        # out explicitly. Inspection-only modes keep the stderr warning.
        plan_modes = (
            "PRINT_REASSIGNMENT", "RANK_DECOMMISSION", "PRINT_FRESH_ASSIGNMENT"
        )
        if (
            args.mode in plan_modes
            and getattr(backend, "rack_blind", False)
            and not args.disable_rack_awareness
        ):
            print(
                "error: this metadata backend cannot supply broker rack info "
                "(confluent-kafka's AdminClient is rack-blind), so a "
                "rack-aware assignment cannot be guaranteed. Re-run with "
                "--disable_rack_awareness to explicitly opt out of rack "
                "diversity, or use the zk:// or file:// backend (or install "
                "kafka-python, whose AdminClient carries racks).",
                file=sys.stderr,
            )
            return 1
        if args.mode == "PRINT_CURRENT_ASSIGNMENT":
            print_current_assignment(backend, topics)
        elif args.mode == "PRINT_CURRENT_BROKERS":
            print_current_brokers(backend, live_brokers=live_brokers)
        elif args.mode == "PRINT_FRESH_ASSIGNMENT":
            # From-scratch placement (no current assignment) — a capability
            # the reference lacks entirely; requires explicit positive shape
            # flags. Always the JAX backend (like RANK_DECOMMISSION).
            if not topics or args.partition_count is None \
                    or args.partition_count <= 0 \
                    or args.desired_replication_factor <= 0:
                print(
                    "error: PRINT_FRESH_ASSIGNMENT requires --topics, a "
                    "positive --partition_count and a positive "
                    "--desired_replication_factor",
                    file=sys.stderr,
                )
                return 1
            if args.solver != "greedy":
                print(
                    f"note: --solver {args.solver} is ignored by "
                    "PRINT_FRESH_ASSIGNMENT (always the JAX solver)",
                    file=sys.stderr,
                )
            # Honor broker selection/exclusion like PRINT_REASSIGNMENT:
            # target set = (--integer_broker_ids/--broker_hosts or all live)
            # minus --broker_hosts_to_remove.
            target = (broker_ids or {b.id for b in live_brokers}) - excluded
            print_fresh_assignment(
                topics, args.partition_count, args.desired_replication_factor,
                [b for b in live_brokers if b.id in target],
                {k: v for k, v in rack_assignment.items() if k in target},
            )
        elif args.mode == "RANK_DECOMMISSION":
            # Sweep-based mode: always the JAX backend; --solver is not
            # meaningful here.
            if args.solver != "greedy":
                print(
                    f"note: --solver {args.solver} is ignored by "
                    "RANK_DECOMMISSION (always the batched JAX sweep)",
                    file=sys.stderr,
                )
            # --broker_hosts_to_remove narrows the cluster first (rank the
            # remaining removals GIVEN those already gone).
            live = [b for b in live_brokers if b.id not in excluded]
            print_decommission_ranking(
                backend, topics, (broker_ids - excluded) or None,
                {k: v for k, v in rack_assignment.items() if k not in excluded},
                args.desired_replication_factor, live_brokers=live,
                scenario_file=args.scenario_file,
            )
        else:
            from .utils.env import env_choice

            policy = args.failure_policy or env_choice("KA_FAILURE_POLICY")
            degradation = Degradation()
            print_least_disruptive_reassignment(
                backend,
                topics,
                broker_ids,
                excluded,
                rack_assignment,
                args.desired_replication_factor,
                solver=args.solver,
                live_brokers=live_brokers,
                context_file=args.leadership_context,
                failure_policy=policy,
                degradation=degradation,
            )
            if degradation.any():
                # The plan on stdout is complete for what it covers, but the
                # operator (and any supervising autoscaler) must be able to
                # tell this run from a clean one without parsing stderr.
                print(
                    f"kafka-assigner: degraded success: "
                    f"{len(degradation.topics_skipped)} topic(s) skipped, "
                    f"{degradation.solve_fallbacks} solver fallback(s); "
                    f"exiting {EXIT_DEGRADED}",
                    file=sys.stderr,
                )
                return EXIT_DEGRADED
    finally:
        backend.close()
    return 0


def build_warm_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ka-warm",
        description="Seed the persistent AOT program store "
        "(utils/programstore.py) so later processes start load-bound "
        "instead of compile-bound: warm the batched-solve programs for a "
        "cluster snapshot's exact bucket signature, or for an explicit "
        "synthetic bucket set.",
    )
    p.add_argument("--zk_string", default=None,
                   help="cluster to warm for: ZK quorum host:port pairs or a "
                        "file://cluster.json snapshot (the store is seeded "
                        "for this cluster's exact bucket signature)")
    p.add_argument("--topics", default=None,
                   help="comma-separated topic subset (default: all topics)")
    p.add_argument("--desired_replication_factor", type=int, default=-1,
                   help="RF override, like the generator flag; default "
                        "infers from the current assignment")
    p.add_argument("--buckets", default=None,
                   metavar="TOPICS,PARTITIONS,RF,BROKERS[,RACKS]",
                   help="warm a synthetic bucket set instead of a cluster, "
                        "e.g. the headline 2048,128,3,5120,8 — no metadata "
                        "backend needed")
    return p


def run_warm(argv: Optional[List[str]] = None) -> int:
    """``ka-warm``: precompile/load the solve programs for a cluster (or an
    explicit bucket set) into the program store, so the NEXT process — CLI or
    daemon — finds them resident. Exit 0 on success, 1 on usage error,
    ingest errors map like the generator's."""
    from .models.problem import encode_cluster, group_pads, _pad8
    from .obs.trace import span
    from .solvers.warmup import warm_solver_programs
    from .utils.compilecache import enable_persistent_cache

    parser = build_warm_parser()
    args = parser.parse_args(argv)
    enable_persistent_cache()
    _prebuild_native()

    if (args.buckets is None) == (args.zk_string is None):
        print("error: pass exactly one of --zk_string or --buckets",
              file=sys.stderr)
        parser.print_usage(sys.stderr)
        return 1

    if args.buckets is not None:
        try:
            parts = [int(tok) for tok in args.buckets.split(",")]
            if len(parts) == 4:
                parts.append(8)
            n_topics, partitions, rf, brokers, racks = parts
            if min(n_topics, partitions, rf, brokers, racks) < 1:
                raise ValueError("all bucket fields must be positive")
        except ValueError as e:
            print(f"error: bad --buckets value {args.buckets!r}: {e}",
                  file=sys.stderr)
            return 1
        rack_assignment = {i: f"r{i % racks}" for i in range(brokers)}
        cluster = encode_cluster(rack_assignment, set(range(brokers)))
        p_pad, width = _pad8(partitions), max(rf, 2)
    else:
        from .assigner import infer_topic_rf
        from .io.base import open_backend

        backend = open_backend(args.zk_string)
        try:
            live = backend.brokers()
            topic_list = (
                args.topics.split(",") if args.topics is not None
                else backend.all_topics()
            )
            initial = backend.partition_assignment(topic_list)
        finally:
            backend.close()
        brokers_set = {b.id for b in live}
        rack_assignment = {
            b.id: b.rack for b in live if b.rack is not None
        }
        rfs = [
            infer_topic_rf(t, initial[t], args.desired_replication_factor)
            for t in topic_list
        ]
        rf = max((r for r in rfs if r > 0), default=2)
        n_topics = len(topic_list)
        cluster = encode_cluster(rack_assignment, brokers_set)
        p_pad, width = group_pads([initial[t] for t in topic_list])

    with span("warmup"):
        outcomes = warm_solver_programs(
            cluster, n_topics, p_pad, width, rf
        )
    for name, outcome in sorted(outcomes.items()):
        print(f"ka-warm: {name}: {outcome}", file=sys.stderr)
    if not outcomes or "error" in outcomes.values():
        print("ka-warm: warm-up incomplete (see warnings above)",
              file=sys.stderr)
        return 1
    if all(o == "jit" for o in outcomes.values()):
        # Compiled in-process only (store disabled or shapes rejected by the
        # bucket contract): the next process would still start cold, which
        # defeats this tool's whole purpose — say so and fail.
        print(
            "ka-warm: programs compiled but NOTHING persisted — the store "
            "is disabled (KA_PROGRAM_STORE=0?) or the signature was "
            "rejected; the next process will still pay the cold compile",
            file=sys.stderr,
        )
        return 1
    print(
        f"ka-warm: store seeded for {n_topics} topic(s), "
        f"p_pad={p_pad}, width={width}, rf={rf}, "
        f"n={cluster.n}", file=sys.stderr,
    )
    return 0


def warm_main() -> None:
    """Console entry point for ``ka-warm`` (pyproject.toml)."""
    try:
        sys.exit(run_warm())
    except (ZkWireError, OSError) as e:
        print(f"error: metadata ingest failed: {e}", file=sys.stderr)
        sys.exit(EXIT_INGEST)
    except (ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(EXIT_VALIDATION)


def build_daemon_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ka-daemon",
        description="Resident assigner daemon (daemon/service.py): holds "
        "ZooKeeper sessions, the warm program store and the encoded "
        "cluster state in memory, keeps them fresh via ZK watches with "
        "incremental re-encode, and serves /plan, /whatif, /execute, "
        "/healthz, /readyz and /state over HTTP — for ONE cluster "
        "(--zk_string) or a whole fleet (--clusters, one supervised "
        "bulkhead per cluster, requests routed by /clusters/<name>/... "
        "prefix). SIGTERM drains and exits 0.",
    )
    p.add_argument("--zk_string", default=None,
                   help="single cluster to serve: ZK quorum host:port "
                        "pairs, or a file://cluster.json snapshot "
                        "(watchless; interval resync only)")
    p.add_argument("--clusters", default=None, metavar="SPEC",
                   help="serve SEVERAL clusters from one daemon: "
                        "semicolon-separated name=connect pairs (connect "
                        "strings may contain commas), e.g. "
                        "'west=zk1:2181,zk2:2181;east=file://east.json', "
                        "or a path to a JSON file mapping names to connect "
                        "strings. Append '#controller=off|observe|auto' "
                        "to an entry (or use the JSON object form "
                        "{\"connect\": ..., \"controller\": ...}) to "
                        "override the KA_CONTROLLER policy per cluster. "
                        "One ClusterSupervisor per entry: own "
                        "session, watch loop, cache, inflight gate, "
                        "watchdog and circuit breaker — one sick quorum "
                        "never takes down planning for the others. "
                        "Mutually exclusive with --zk_string")
    p.add_argument("--solver", default="tpu",
                   choices=("greedy", "native", "tpu"),
                   help="default solver for served /plan requests "
                        "(per-request 'solver' overrides)")
    p.add_argument("--failure-policy", dest="failure_policy", default=None,
                   choices=("strict", "best-effort"),
                   help="default failure policy for served requests "
                        "(default: the KA_FAILURE_POLICY knob; a resident "
                        "service usually wants best-effort — a degraded "
                        "answer beats a dead request)")
    p.add_argument("--bind", default=None,
                   help="bind address (default: the KA_DAEMON_BIND knob, "
                        "loopback)")
    p.add_argument("--port", type=int, default=None,
                   help="listen port (default: the KA_DAEMON_PORT knob; "
                        "0 = ephemeral, announced on stderr)")
    p.add_argument("--access-log", dest="access_log", default=None,
                   metavar="PATH",
                   help="structured NDJSON access log path — one JSON line "
                        "per served request with its request id, endpoint, "
                        "cluster, HTTP code, status, latency and "
                        "stale/degraded markers (default: the "
                        "KA_OBS_ACCESS_LOG knob, else stderr)")
    return p


def parse_clusters_spec(spec: str) -> dict:
    """Parse the ``--clusters`` value: a ``*.json``/``file://`` path to a
    ``{name: connect}`` mapping — each value a connect string or an
    object ``{"connect": ..., "controller": "off|observe|auto"}`` (the
    per-cluster controller-policy override, ISSUE 15) — or inline
    semicolon-separated ``name=connect`` pairs (connect strings keep
    their commas; append ``#controller=<policy>`` per entry for the same
    override)."""
    import json as json_mod

    # Inline entries always carry '='; a bare path never does (a connect
    # string with '=' in a PATH would be ambiguous — name it in a file).
    if "=" not in spec and (
        spec.startswith("file://") or spec.endswith(".json")
    ):
        path = spec[len("file://"):] if spec.startswith("file://") else spec
        with open(path, "r", encoding="utf-8") as f:
            raw = json_mod.load(f)
        if not isinstance(raw, dict) or not raw or not all(
            isinstance(k, str) and isinstance(v, (str, dict))
            for k, v in raw.items()
        ):
            raise ValueError(
                f"--clusters file {path!r} must be a non-empty JSON "
                "object mapping cluster names to connect strings (or "
                "{\"connect\": ..., \"controller\": ...} objects)"
            )
        return dict(raw)
    clusters = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, eq, connect = entry.partition("=")
        name, connect = name.strip(), connect.strip()
        if not eq or not name or not connect:
            raise ValueError(
                f"--clusters entry {entry!r} is not of the form "
                "name=connect"
            )
        if name in clusters:
            raise ValueError(f"--clusters names {name!r} twice")
        clusters[name] = connect
    if not clusters:
        raise ValueError("--clusters names no clusters")
    return clusters


def run_daemon(argv: Optional[List[str]] = None) -> int:
    """``ka-daemon``: start the resident daemon and serve until signaled.
    Exit 0 after a clean SIGTERM/SIGINT drain; ingest failures of the
    initial sync (single-cluster mode only — a multi-cluster daemon keeps
    serving the healthy clusters) map to the documented ingest code via
    :func:`daemon_main`."""
    from .daemon.service import run_daemon_process
    from .utils.compilecache import enable_persistent_cache

    parser = build_daemon_parser()
    args = parser.parse_args(argv)
    if (args.zk_string is None) == (args.clusters is None):
        print("error: pass exactly one of --zk_string or --clusters",
              file=sys.stderr)
        parser.print_usage(sys.stderr)
        return EXIT_USAGE
    clusters = None
    if args.clusters is not None:
        try:
            clusters = parse_clusters_spec(args.clusters)
        except (ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            parser.print_usage(sys.stderr)
            return EXIT_USAGE
    # Build the native artifacts BEFORE the solver fail-fast: the load
    # paths no longer compile (ISSUE 14), so `--solver native` on a box
    # with a toolchain but no prebuilt .so must build here, not refuse.
    _prebuild_native()
    # Fail fast on an unavailable solver backend, like the one-shot CLI.
    get_solver(args.solver)
    enable_persistent_cache()
    return run_daemon_process(
        args.zk_string,
        clusters=clusters,
        solver=args.solver,
        failure_policy=args.failure_policy,
        bind=args.bind,
        port=args.port,
        access_log=args.access_log,
    )


def daemon_main() -> None:
    """Console entry point for ``ka-daemon`` (pyproject.toml)."""
    from .errors import IngestError

    try:
        sys.exit(run_daemon())
    except IngestError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(EXIT_INGEST)
    except (ZkWireError, OSError) as e:
        print(f"error: metadata ingest failed: {e}", file=sys.stderr)
        sys.exit(EXIT_INGEST)
    except (ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(EXIT_VALIDATION)


def build_groups_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ka-groups",
        description="Consumer-group workload family (groups/): "
        "capacity-constrained partition→consumer packing. Plan mode emits "
        "a sticky, movement-minimizing rebalance plan for each group; "
        "sweep mode answers \"how many consumers do I need\" by "
        "evaluating every (consumer count × lag scale) candidate as ONE "
        "batched on-device fan-out and printing the cost curve. Output "
        "is a schema-versioned JSON envelope on stdout, byte-stable "
        "across identical runs.",
    )
    p.add_argument("--zk_string", default=None,
                   help="cluster metadata source: ZK quorum host:port "
                        "pairs, kafka://bootstrap, or a "
                        "file://cluster.json snapshot (group state needs "
                        "a backend with group support — a snapshot "
                        "\"groups\" section or an AdminClient with the "
                        "consumer-group offset chain — or --synthetic)")
    p.add_argument("--mode", default="plan", choices=("plan", "sweep"),
                   help="plan: per-group packing plan; sweep: the batched "
                        "autoscale cost curve")
    p.add_argument("--group", default=None,
                   help="comma-separated group names (default: every "
                        "group the backend reports)")
    p.add_argument("--synthetic", action="store_true",
                   help="EXPLICIT opt-in to the deterministic synthetic "
                        "group family (derived from cluster partitions; "
                        "envelopes carry groups_real=false). Without it, "
                        "a backend without group support is refused "
                        "loudly — synthetic inputs never masquerade as "
                        "cluster truth")
    p.add_argument("--weight", default="lag", choices=("lag", "throughput"),
                   help="packing weight column: per-partition consumer "
                        "lag (from the group state) or produced-byte "
                        "rate (from the traffic hook, synthetic where "
                        "the backend has no meters)")
    p.add_argument("--counts", default=None,
                   help="sweep candidate consumer counts, comma-separated "
                        "(default: 1..2x the current membership, capped "
                        "by KA_GROUPS_MAX_CANDIDATES)")
    p.add_argument("--scales", default=None,
                   help="sweep weight scales in percent, comma-separated "
                        "(default: the KA_GROUPS_DEFAULT_SCALES knob)")
    p.add_argument("--solver", default="device",
                   choices=("device", "greedy"),
                   help="device: the batched packing kernel (program-"
                        "store warm); greedy: the host packing oracle "
                        "(same plans, by the parity pin)")
    p.add_argument("--failure-policy", dest="failure_policy", default=None,
                   choices=("strict", "best-effort"),
                   help="strict (default): a crashed device solve exits "
                        "with the solve code. best-effort: it falls back "
                        "to the greedy packing oracle (same plan bytes) "
                        "and the run exits with the degraded-success "
                        "code")
    p.add_argument("--report-json", dest="report_json", default=None,
                   metavar="PATH",
                   help="emit the schema-versioned run report (groups "
                        "span family + groups.* counters) to PATH")
    return p


def run_groups(argv: Optional[List[str]] = None) -> int:
    """``ka-groups``: the consumer-group plan/sweep pipeline. Library
    callers get raw typed exceptions; :func:`groups_main` maps them to the
    documented exit codes."""
    from .utils.compilecache import enable_persistent_cache
    from .utils.env import env_bool, env_str

    parser = build_groups_parser()
    args = parser.parse_args(argv)
    if args.zk_string is None:
        print("error: --zk_string is required", file=sys.stderr)
        parser.print_usage(sys.stderr)
        return EXIT_USAGE
    enable_persistent_cache()
    _prebuild_native()

    report_path = args.report_json or env_str("KA_OBS_REPORT")
    if report_path is None and not env_bool("KA_OBS_ENABLE"):
        return _dispatch_groups(args)

    from . import obs

    mode = "GROUPS_PLAN" if args.mode == "plan" else "GROUPS_SWEEP"
    with obs.run_capture() as run:
        status, error, rc = "error", None, 1
        try:
            with obs.span(f"mode/{mode}") as sp:
                rc = _dispatch_groups(args)
                if rc not in (EXIT_OK, EXIT_DEGRADED):
                    sp.fail()
            status = (
                "ok" if rc == EXIT_OK
                else "degraded" if rc == EXIT_DEGRADED
                else "error"
            )
            return rc
        except BaseException as e:
            error = e
            raise
        finally:
            try:
                report = obs.build_report(
                    run, status=status, mode=mode,
                    argv=list(argv) if argv is not None else sys.argv[1:],
                    error=error,
                )
                obs.emit_report(report, report_path)
            except Exception as e:
                print(f"obs: could not emit run report: {e}",
                      file=sys.stderr)


def _dispatch_groups(args) -> int:
    """Backend open → group ingest (or loud refusal) → encode → solve →
    envelope emission."""
    import json as json_mod

    from .groups.model import GROUPS_SCHEMA_VERSION
    from .groups.solve import (
        build_group_bodies,
        load_group_states,
        parse_int_list,
        subscribed_partitions,
        throughput_weights,
    )
    from .obs.metrics import counter_add
    from .utils.env import env_choice, env_float, env_int, env_str

    policy = args.failure_policy or env_choice("KA_FAILURE_POLICY")
    fallback = "greedy" if policy == "best-effort" else "raise"
    group_names = args.group.split(",") if args.group else None
    scales = parse_int_list(
        args.scales, env_str("KA_GROUPS_DEFAULT_SCALES")
    )
    counts = parse_int_list(args.counts)
    headroom = env_float("KA_GROUPS_CAPACITY_HEADROOM")
    max_cand = env_int("KA_GROUPS_MAX_CANDIDATES")

    backend = open_backend(args.zk_string)
    try:
        supports = bool(
            getattr(backend, "supports_groups", lambda: False)()
        )
        if not args.synthetic and not supports:
            # The loud refusal (never synthetic-as-real): mirror the
            # rack-blind refusal's shape — a clear error naming the
            # explicit opt-out, usage exit code.
            counter_add("groups.refusals")
            print(
                "error: this metadata backend cannot read consumer "
                "groups (no group membership/offset surface), so a "
                "packing plan would be built on invented inputs. Re-run "
                "with --synthetic to explicitly opt into the "
                "deterministic synthetic family (marked "
                "groups_real=false), or use a snapshot with a \"groups\" "
                "section / an AdminClient with consumer-group offset "
                "support.",
                file=sys.stderr,
            )
            return EXIT_USAGE
        partitions = backend.partition_assignment(backend.all_topics())
        part_map = {t: sorted(per) for t, per in partitions.items()}
        states, groups_real = load_group_states(
            backend, part_map, groups=group_names,
            synthetic=args.synthetic,
        )
        if not states:
            raise ValueError("the backend reports no consumer groups")
        weight_values = (
            # Traffic I/O proportional to the packing problem (the
            # groups' subscribed topics), not the whole cluster.
            throughput_weights(
                backend, subscribed_partitions(states, part_map)
            )
            if args.weight == "throughput" else None
        )
    finally:
        backend.close()

    bodies, degraded_by_group = build_group_bodies(
        states, groups_real, part_map, args.mode, args.weight,
        weight_values, scales, headroom, max_cand, counts=counts,
        solver=args.solver, fallback=fallback,
    )
    degraded_any = False
    for g, body in bodies.items():
        if args.mode == "sweep":
            counter_add("groups.sweeps")
        else:
            counter_add("groups.plans")
            counter_add("groups.moves", body["moves"])
        if degraded_by_group[g]:
            counter_add("groups.solve_fallbacks")
            degraded_any = True

    if len(bodies) == 1:
        payload = next(iter(bodies.values()))
    else:
        payload = {
            "schema_version": GROUPS_SCHEMA_VERSION,
            "kind": (
                "groups-plan-set" if args.mode == "plan"
                else "groups-sweep-set"
            ),
            "groups_real": groups_real,
            "groups": bodies,
        }
    # kalint: disable=KA005 -- groups envelope emission (new schema-versioned surface), not a Kafka-parseable reassignment payload
    print(json_mod.dumps(payload, indent=1, sort_keys=True))
    if degraded_any:
        print(
            "ka-groups: degraded success: device solve fell back to the "
            f"greedy packing oracle; exiting {EXIT_DEGRADED}",
            file=sys.stderr,
        )
        return EXIT_DEGRADED
    return EXIT_OK


def groups_main() -> None:
    """Console entry point for ``ka-groups`` (pyproject.toml)."""
    try:
        sys.exit(run_groups())
    except IngestError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(EXIT_INGEST)
    except SolveError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(EXIT_SOLVE)
    except (ZkWireError, OSError) as e:
        print(f"error: metadata ingest failed: {e}", file=sys.stderr)
        sys.exit(EXIT_INGEST)
    except (ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(EXIT_VALIDATION)


def build_execute_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ka-execute",
        description="Execute an emitted reassignment plan against the "
        "cluster: throttled waves, ISR-convergence polling between waves, "
        "a crash-safe journal (resume with --resume after a kill), and a "
        "byte-identical verify-after-move pass (exec/engine.py).",
    )
    p.add_argument("--zk_string", default=None,
                   help="cluster to execute against: ZK quorum host:port "
                        "pairs, or a file://cluster.json snapshot (hermetic "
                        "simulated-convergence mode)")
    p.add_argument("--plan", default=None, metavar="PATH",
                   help="plan JSON to execute — the NEW ASSIGNMENT payload "
                        "(a saved mode-3 stdout is accepted; the rollback "
                        "snapshot section is ignored)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="crash-safe journal path (default: the "
                        "KA_EXEC_JOURNAL knob, else <plan>.journal)")
    p.add_argument("--resume", action="store_true",
                   help="continue an interrupted run from its journal's "
                        "last committed wave (refused when the journal "
                        "belongs to a different plan)")
    p.add_argument("--rollback", action="store_true",
                   help="execute the plan file's saved CURRENT ASSIGNMENT "
                        "snapshot instead of the NEW ASSIGNMENT payload — "
                        "drives the cluster BACK to its pre-reassignment "
                        "state through the same wave engine (throttled, "
                        "journaled at <plan>.rollback.journal by default, "
                        "verified after the moves)")
    p.add_argument("--wave-size", dest="wave_size", type=int, default=None,
                   help="partition moves per wave (default: the "
                        "KA_EXEC_WAVE_SIZE knob)")
    p.add_argument("--throttle", type=float, default=None,
                   help="seconds to pause between converged waves "
                        "(default: the KA_EXEC_THROTTLE knob)")
    p.add_argument("--failure-policy", dest="failure_policy", default=None,
                   choices=("strict", "best-effort"),
                   help="strict (default): halt resumably on the first "
                        "wave that fails to converge (exit 8). "
                        "best-effort: record unconverged moves as skipped "
                        "and keep going — the run exits with the "
                        "degraded-success code and the skips are listed in "
                        "the run report's plan section")
    p.add_argument("--report-json", dest="report_json", default=None,
                   metavar="PATH",
                   help="emit the schema-versioned run report (exec span "
                        "family, exec.* counters, wave-latency histogram) "
                        "to PATH")
    return p


def run_execute(argv: Optional[List[str]] = None) -> int:
    """``ka-execute``: drive a plan to convergence. Library callers get the
    raw typed exceptions; :func:`execute` maps them to the documented exit
    codes. Returns EXIT_OK, EXIT_DEGRADED (best-effort skips) or
    EXIT_VERIFY (post-move cluster state diverges from the plan)."""
    parser = build_execute_parser()
    args = parser.parse_args(argv)
    if args.zk_string is None or args.plan is None:
        print("error: --zk_string and --plan are required", file=sys.stderr)
        parser.print_usage(sys.stderr)
        return EXIT_USAGE

    from .utils.env import env_bool, env_str

    report_path = args.report_json or env_str("KA_OBS_REPORT")
    if report_path is None and not env_bool("KA_OBS_ENABLE"):
        return _dispatch_execute(args)

    from . import obs

    mode = (
        "ROLLBACK_REASSIGNMENT" if args.rollback else "EXECUTE_REASSIGNMENT"
    )
    with obs.run_capture() as run:
        status, error, rc = "error", None, 1
        try:
            with obs.span(f"mode/{mode}") as sp:
                rc = _dispatch_execute(args)
                if rc not in (EXIT_OK, EXIT_DEGRADED):
                    sp.fail()
            status = (
                "ok" if rc == EXIT_OK
                else "degraded" if rc == EXIT_DEGRADED
                else "error"
            )
            return rc
        except BaseException as e:
            # Same flush contract as run_tool: a crash mid-execution (or
            # the injected wave kill) must still emit the report — the
            # journal forensics need the spans most on exactly those runs.
            error = e
            raise
        finally:
            try:
                report = obs.build_report(
                    run, status=status, mode=mode,
                    argv=list(argv) if argv is not None else sys.argv[1:],
                    error=error,
                )
                obs.emit_report(report, report_path)
            except Exception as e:
                print(f"obs: could not emit run report: {e}",
                      file=sys.stderr)


def _dispatch_execute(args) -> int:
    """Plan load → backend open → engine drive → exit-code mapping."""
    from .exec.engine import PlanExecutor, load_plan_file
    from .utils.env import env_choice, env_str

    plan, topic_order = load_plan_file(
        args.plan, section="current" if args.rollback else "new"
    )
    # A rollback is a DIFFERENT plan (different canonical bytes, different
    # journal identity): every DEFAULT journal source — the plan-derived
    # path AND the KA_EXEC_JOURNAL knob — gets a rollback-specific name, so
    # a forward run's journal is never refused or clobbered. Only an
    # explicit --journal takes the operator's path verbatim.
    if args.journal:
        journal_path = args.journal
    else:
        env_journal = env_str("KA_EXEC_JOURNAL")
        if env_journal:
            journal_path = env_journal + (
                ".rollback" if args.rollback else ""
            )
        else:
            journal_path = args.plan + (
                ".rollback.journal" if args.rollback else ".journal"
            )
    policy = args.failure_policy or env_choice("KA_FAILURE_POLICY")
    backend = open_backend(args.zk_string)
    try:
        executor = PlanExecutor(
            backend, plan, topic_order, journal_path,
            failure_policy=policy, resume=args.resume,
            wave_size=args.wave_size, throttle=args.throttle,
            # Journal identity = (cluster, plan sha): the connect spec
            # stamps the journal so the same plan bytes on another cluster
            # can never cross-resume (ISSUE 9 satellite).
            cluster=args.zk_string,
        )
        outcome = executor.execute()
    finally:
        backend.close()
    n_moves = outcome.moves_submitted
    print(
        f"ka-execute: {outcome.waves_run}/{outcome.waves_total} wave(s) "
        f"run ({n_moves} move(s) submitted, {outcome.noops} already in "
        f"place{', resumed' if outcome.resumed else ''})",
        file=sys.stderr,
    )
    if outcome.mismatches:
        for m in outcome.mismatches[:10]:
            print(
                f"ka-execute: VERIFY MISMATCH [{m['kind']}] "
                f"{m['topic']!r}/{m['partition']}: expected "
                f"{m['expected']}, observed {m['observed']}",
                file=sys.stderr,
            )
        extra = len(outcome.mismatches) - 10
        if extra > 0:
            print(f"ka-execute: ... and {extra} more mismatch(es)",
                  file=sys.stderr)
        print(
            f"ka-execute: verify-after-move FAILED "
            f"({len(outcome.mismatches)} mismatch(es)); exiting "
            f"{EXIT_VERIFY}",
            file=sys.stderr,
        )
        return EXIT_VERIFY
    if outcome.skipped:
        print(
            f"ka-execute: degraded success: {len(set(outcome.skipped))} "
            f"move(s) skipped under best-effort; exiting {EXIT_DEGRADED}",
            file=sys.stderr,
        )
        return EXIT_DEGRADED
    print("ka-execute: verify-after-move OK: cluster state is "
          "byte-identical to the plan", file=sys.stderr)
    return EXIT_OK


def execute(argv: Optional[List[str]] = None) -> int:
    """:func:`run_execute` with the documented exit-code mapping — the
    process entry point (and the chaos harness) call this; anything
    unrecognized (including the injected wave-boundary kill) propagates
    with its traceback, never laundered into a documented code."""
    from .errors import ExecuteError, IngestError

    try:
        return run_execute(argv)
    except ExecuteError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_EXECUTE
    except IngestError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_INGEST
    except BrokenPipeError:
        raise
    except (ZkWireError, OSError) as e:
        print(f"error: metadata ingest failed: {e}", file=sys.stderr)
        return EXIT_INGEST
    except (ValueError, KeyError) as e:
        # Includes JournalError (corrupt/mismatched journal) and plan-file
        # validation failures.
        print(f"error: {e}", file=sys.stderr)
        return EXIT_VALIDATION


def execute_main() -> None:
    """Console entry point for ``ka-execute`` (pyproject.toml)."""
    sys.exit(execute())


def run(argv: Optional[List[str]] = None) -> int:
    """:func:`run_tool` with the documented exit-code mapping: the process
    entry point (and the chaos soak) call this; library callers keep calling
    ``run_tool`` and receive the raw typed exceptions.

    Mapping (README "Failure model"): phase-tagged errors from the pipeline
    (``errors.py``) plus the raw transport/validation classes that can
    escape before tagging. Anything unrecognized propagates with its
    traceback — an undocumented crash must stay loud, not be laundered into
    a documented code.
    """
    try:
        return run_tool(argv)
    except IngestError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_INGEST
    except SolveError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_SOLVE
    except BrokenPipeError:
        # stdout's consumer went away (| head, killed pager) AFTER the work
        # succeeded — not an ingest failure; keep Python's loud default.
        raise
    except (ZkWireError, OSError) as e:
        # Connect/read failures raised before the pipeline tags them
        # (backend open, broker listing, modes 1/2/4 metadata reads).
        print(f"error: metadata ingest failed: {e}", file=sys.stderr)
        return EXIT_INGEST
    except (ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_VALIDATION


def main() -> None:
    # Opt-in wedged-accelerator self-defense for the console entry point
    # (KA_DEVICE_WATCHDOG_S=<seconds>): a wedged TPU tunnel hangs backend
    # init forever, even under JAX_PLATFORMS=cpu while the plugin's site dir
    # is importable (see utils/deviceprobe.py). Probe in a subprocess and
    # fall back to the CPU backend — results are identical, just slower.
    # Default off: on a healthy chip the probe would double backend init
    # (~20-40s). Library callers (run_tool) are never probed.
    import os

    from .utils.env import env_bool, env_float

    watchdog = env_float("KA_DEVICE_WATCHDOG_S")
    if watchdog > 0 and not env_bool("KA_CLI_CPU_FALLBACK"):
        from .utils.deviceprobe import probe_device_count, virtual_cpu_env

        # allow_cpu: the watchdog exists to detect a WEDGED accelerator, not
        # to re-exec on a healthy CPU-only environment (which initializes
        # fine and would otherwise pay interpreter+JAX startup twice).
        if probe_device_count(watchdog, allow_cpu=True) < 1:
            print(
                "WARNING: accelerator backend failed to initialize within "
                f"{watchdog:.0f}s (wedged tunnel?); continuing on the CPU "
                "backend — output is identical, solve is slower.",
                file=sys.stderr,
            )
            env = virtual_cpu_env()
            env["KA_CLI_CPU_FALLBACK"] = "1"
            os.execve(sys.executable, [sys.executable, "-m",
                                       "kafka_assigner_tpu.cli"] + sys.argv[1:],
                      env)
    sys.exit(run())


if __name__ == "__main__":
    main()
