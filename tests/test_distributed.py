"""Real multi-process coverage: multiple OS processes form a global mesh
over jax.distributed (the DCN-analogue on CPU), shard a what-if sweep across
it, and must reproduce the single-process results exactly — at 2 processes
and at 4 (VERDICT r3 item 9: ``put_sharded``'s ``make_array_from_callback``
path beyond 2 processes).

The reference has no multi-process story at all (one JVM, one thread —
``KafkaAssignmentGenerator.java:301-303``); this is the framework's
fleet-scale execution path (SURVEY.md §2 parallelism checklist)."""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from kafka_assigner_tpu.parallel.whatif import evaluate_removal_scenarios

from .test_invariants import make_cluster

_WORKER = textwrap.dedent(
    """
    import json, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid, n_procs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    n_brokers, n_topics, n_scenarios = map(int, sys.argv[4:7])
    jax.distributed.initialize(
        f"localhost:{port}", num_processes=n_procs, process_id=pid
    )

    import numpy as np
    from kafka_assigner_tpu.parallel.mesh import build_mesh
    from kafka_assigner_tpu.parallel.whatif import evaluate_removal_scenarios
    from tests.test_invariants import make_cluster

    current, live, rack_map = make_cluster(0, n_brokers, 32, 3, 4)
    topics = {f"t{i}": current for i in range(n_topics)}
    scenarios = [[100 + i] for i in range(n_scenarios)]
    mesh = build_mesh()  # all global devices on the scenarios axis
    t0 = time.perf_counter()
    results = evaluate_removal_scenarios(topics, live, rack_map, scenarios, 3, mesh=mesh)
    elapsed = time.perf_counter() - t0
    payload = [[list(r.removed), r.moved_replicas, r.feasible, r.max_node_load]
               for r in results]
    print("RESULT:" + json.dumps(
        {"pid": pid, "elapsed_s": round(elapsed, 1), "results": payload}
    ), flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_multi_process_sweep(
    tmp_path, n_procs, n_brokers, n_topics, n_scenarios, devs_per_proc,
    timeout_s,
):
    """Launch ``n_procs`` workers, return their parsed RESULT payloads."""
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devs_per_proc}"
    )
    env["PYTHONPATH"] = os.getcwd()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i), str(n_procs),
             str(n_brokers), str(n_topics), str(n_scenarios)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(n_procs)
    ]
    outs = []
    try:
        for proc in procs:
            out, err = proc.communicate(timeout=timeout_s)
            assert proc.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(out)
    finally:
        # Never leak a worker blocked in the distributed barrier: if one side
        # failed or timed out, kill the rest.
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    got = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT:")][-1]
        got.append(json.loads(line[len("RESULT:"):]))
    return got


def _expected_payload(n_brokers, n_topics, n_scenarios):
    current, live, rack_map = make_cluster(0, n_brokers, 32, 3, 4)
    topics = {f"t{i}": current for i in range(n_topics)}
    scenarios = [[100 + i] for i in range(n_scenarios)]
    expected = evaluate_removal_scenarios(topics, live, rack_map, scenarios, 3)
    return [
        [list(r.removed), r.moved_replicas, r.feasible, r.max_node_load]
        for r in expected
    ]


@pytest.mark.slow
def test_two_process_mesh_matches_single_process(tmp_path):
    expected = _expected_payload(16, 2, 4)
    for got in _run_multi_process_sweep(tmp_path, 2, 16, 2, 4, 2, 150):
        assert got["results"] == expected, got


@pytest.mark.slow
def test_two_process_fleet_scale(tmp_path):
    # Fleet-scale evidence (VERDICT round 1 weakness 6): 2 processes x 4
    # devices each (8 global, the DCN-analogue layout), 32 scenarios over a
    # 128-broker cluster, 8 topics — every process must agree with the
    # single-process result bit-for-bit, all scenarios feasible.
    expected = _expected_payload(128, 8, 32)
    assert all(row[2] for row in expected)  # all feasible
    for got in _run_multi_process_sweep(tmp_path, 2, 128, 8, 32, 4, 300):
        assert got["results"] == expected, got


@pytest.mark.slow
def test_four_process_mesh_matches_single_process(tmp_path):
    # VERDICT r3 item 9: the make_array_from_callback feeding path beyond 2
    # processes — 4 processes x 2 devices (8 global), 16 scenarios over a
    # 64-broker cluster; every process must agree with the single-process
    # result bit-for-bit.
    expected = _expected_payload(64, 4, 16)
    got_all = _run_multi_process_sweep(tmp_path, 4, 64, 4, 16, 2, 420)
    assert len(got_all) == 4
    for got in got_all:
        assert got["results"] == expected, got
