"""Real multi-process coverage: two OS processes form a global mesh over
jax.distributed (the DCN-analogue on CPU), shard a what-if sweep across it,
and must reproduce the single-process results exactly.

The reference has no multi-process story at all (one JVM, one thread —
``KafkaAssignmentGenerator.java:301-303``); this is the framework's
fleet-scale execution path (SURVEY.md §2 parallelism checklist)."""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from kafka_assigner_tpu.parallel.whatif import evaluate_removal_scenarios

from .test_invariants import make_cluster

_WORKER = textwrap.dedent(
    """
    import json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=pid)

    import numpy as np
    from kafka_assigner_tpu.parallel.mesh import build_mesh
    from kafka_assigner_tpu.parallel.whatif import evaluate_removal_scenarios
    from tests.test_invariants import make_cluster

    current, live, rack_map = make_cluster(0, 16, 32, 3, 4)
    topics = {f"t{i}": current for i in range(2)}
    scenarios = [[100 + i] for i in range(4)]
    mesh = build_mesh()  # all global devices on the scenarios axis
    results = evaluate_removal_scenarios(topics, live, rack_map, scenarios, 3, mesh=mesh)
    payload = [[list(r.removed), r.moved_replicas, r.feasible, r.max_node_load]
               for r in results]
    print("RESULT:" + json.dumps({"pid": pid, "results": payload}), flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_mesh_matches_single_process(tmp_path):
    current, live, rack_map = make_cluster(0, 16, 32, 3, 4)
    topics = {f"t{i}": current for i in range(2)}
    scenarios = [[100 + i] for i in range(4)]
    expected = evaluate_removal_scenarios(topics, live, rack_map, scenarios, 3)
    expected_payload = [
        [list(r.removed), r.moved_replicas, r.feasible, r.max_node_load]
        for r in expected
    ]

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.getcwd()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for proc in procs:
            out, err = proc.communicate(timeout=150)
            assert proc.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(out)
    finally:
        # Never leak a worker blocked in the distributed barrier: if one side
        # failed or timed out, kill the rest.
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT:")][-1]
        got = json.loads(line[len("RESULT:"):])
        assert got["results"] == expected_payload, got
