"""Sinkhorn relaxation + fresh-assignment tests, including sharded execution
of the blockwise row/col normalizations over the partition axis."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assigner_tpu.ops.sinkhorn import (
    capacity_sinkhorn,
    movement_estimate,
    topk_candidates,
)
from kafka_assigner_tpu.solvers.tpu import TpuSolver

from .helpers import verify_full_invariants


def test_sinkhorn_marginals():
    rng = np.random.default_rng(0)
    p, n, rf = 32, 16, 3
    cost = jnp.asarray(rng.uniform(size=(p, n)).astype(np.float32))
    row_target = jnp.full((p,), float(rf))
    cap = float(np.ceil(p * rf / n))
    col_cap = jnp.full((n,), cap)
    x = capacity_sinkhorn(cost, row_target, col_cap, iters=128)
    np.testing.assert_allclose(np.asarray(x.sum(1)), rf, rtol=1e-3)
    assert (np.asarray(x.sum(0)) <= cap * (1 + 1e-3)).all()
    assert (np.asarray(x) >= 0).all()


def test_sinkhorn_respects_forbidden_cells():
    p, n = 8, 8
    cost = jnp.zeros((p, n)).at[:, 0].set(jnp.inf)
    x = capacity_sinkhorn(cost, jnp.full((p,), 2.0), jnp.full((n,), 4.0), iters=64)
    assert float(x[:, 0].sum()) == 0.0


def test_sinkhorn_prefers_cheap_cells():
    # Two nodes, one clearly cheaper: mass should concentrate up to capacity.
    cost = jnp.array([[0.0, 1.0]] * 4)
    x = capacity_sinkhorn(
        cost, jnp.full((4,), 1.0), jnp.asarray([2.0, 4.0]), eps=0.02, iters=256
    )
    # cheap column saturates its cap of 2; the rest overflows to column 1
    assert float(x[:, 0].sum()) == pytest.approx(2.0, rel=1e-2)
    assert float(x[:, 1].sum()) == pytest.approx(2.0, rel=1e-2)


def test_movement_estimate_zero_when_sticky_feasible():
    p, n, rf = 8, 8, 2
    sticky = np.zeros((p, n), dtype=bool)
    for i in range(p):
        sticky[i, i % n] = True
        sticky[i, (i + 1) % n] = True
    cost = jnp.where(jnp.asarray(sticky), 0.0, 1.0)
    x = capacity_sinkhorn(
        cost, jnp.full((p,), float(rf)), jnp.full((n,), float(rf)), eps=0.02,
        iters=256,
    )
    lb = float(movement_estimate(x, jnp.asarray(sticky), jnp.full((p,), float(rf))))
    assert lb == pytest.approx(0.0, abs=0.1)


def test_topk_candidates_shape():
    x = jnp.asarray(np.random.default_rng(1).uniform(size=(4, 10)).astype(np.float32))
    idx, vals = topk_candidates(x, 3)
    assert idx.shape == (4, 3) and vals.shape == (4, 3)
    assert (np.asarray(vals[:, 0]) >= np.asarray(vals[:, 1])).all()


def test_sharded_sinkhorn_matches_unsharded():
    # Partition-axis sharding (the SP analogue): same plan, collectives
    # inserted by XLA for the column reductions.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(-1), ("part",))
    rng = np.random.default_rng(2)
    p, n = 64, 16
    cost = rng.uniform(size=(p, n)).astype(np.float32)
    row_target = np.full((p,), 3.0, np.float32)
    col_cap = np.full((n,), float(np.ceil(p * 3 / n)), np.float32)

    base = capacity_sinkhorn(
        jnp.asarray(cost), jnp.asarray(row_target), jnp.asarray(col_cap)
    )
    sharded_cost = jax.device_put(
        jnp.asarray(cost), NamedSharding(mesh, PartitionSpec("part", None))
    )
    sharded_rows = jax.device_put(
        jnp.asarray(row_target), NamedSharding(mesh, PartitionSpec("part"))
    )
    out = jax.jit(capacity_sinkhorn)(sharded_cost, sharded_rows, jnp.asarray(col_cap))
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), rtol=1e-4, atol=1e-5)


def test_fresh_assignment_where_greedy_dead_ends():
    # 50 partitions x RF=3 over 10 brokers / 5 racks: the reference's greedy
    # first-fit provably cannot place this from scratch (verified in round-1
    # analysis); the capacity-greedy balance waves must.
    brokers = set(range(100, 110))
    racks = {b: f"rack{b % 5}" for b in brokers}
    solver = TpuSolver()
    out = solver.fresh_assignment("fresh", 50, brokers, racks, 3)
    assert set(out) == set(range(50))
    verify_full_invariants(out, racks, sorted(brokers), 3)


def test_fresh_assignment_balances_load():
    brokers = set(range(20))
    racks = {b: f"r{b % 4}" for b in brokers}
    out = TpuSolver().fresh_assignment("t", 40, brokers, racks, 2)
    loads = {}
    for r in out.values():
        for b in r:
            loads[b] = loads.get(b, 0) + 1
    # cap = ceil(80/20) = 4; perfect balance respects the cap everywhere
    assert max(loads.values()) <= 4
    assert min(loads.values()) >= 2


def test_reassignment_succeeds_where_reference_strands():
    # Rack-unaware 10 -> 8 broker decommission of a striped cluster: the
    # reference's first-fit strands ("Partition 49 could not be fully
    # assigned!"); the tpu solver's balance fallback completes it with
    # exactly minimal movement (only the dead brokers' replicas).
    from kafka_assigner_tpu.assigner import TopicAssigner
    from .helpers import moved_replicas

    n, p, rf = 10, 50, 3
    base = list(range(n))
    cur = {q: [base[(q + i) % n] for i in range(rf)] for q in range(p)}
    live = set(base[2:])
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("greedy").generate_assignment("t", cur, live, {}, -1)
    new = TopicAssigner("tpu").generate_assignment("t", cur, live, {}, -1)
    verify_full_invariants(new, {}, sorted(live), rf)
    lost = sum(1 for r in cur.values() for b in r if b not in live)
    assert moved_replicas(cur, new) == lost  # minimal movement


def test_relaxed_estimates_rank_scenarios():
    # Relaxed estimates must track exact movement ordering: removing a loaded
    # broker costs more than removing an idle one.
    from kafka_assigner_tpu.parallel.whatif import (
        estimate_removal_scenarios,
        evaluate_removal_scenarios,
    )
    from .test_invariants import make_cluster

    current, live, rack_map = make_cluster(0, 16, 32, 3, 4)
    topics = {"t": current}
    idle = max(live) + 1
    live2 = set(live) | {idle}
    rack_map2 = dict(rack_map); rack_map2[idle] = "rack0"
    scenarios = [[], [idle], [min(live)]]
    est = estimate_removal_scenarios(topics, live2, rack_map2, scenarios, 3)
    exact = evaluate_removal_scenarios(topics, live2, rack_map2, scenarios, 3)
    # ordering: no-op <= idle-removal < loaded-removal
    assert est[0][1] <= est[1][1] + 1e-3
    assert est[1][1] < est[2][1]
    assert exact[1].moved_replicas <= exact[2].moved_replicas
