"""Partition-axis sharding of the EXACT solver (VERDICT round 1 #7).

The ``part`` mesh axis is the long-axis analogue of sequence parallelism
(SURVEY.md §5): the (P × N) eligibility tensors of one giant topic are
sharded across devices and XLA/GSPMD inserts the collectives the wave
auction's cross-partition reductions need. These tests pin that the sharded
exact solve is bit-identical to the unsharded one — on the kernel directly
and through the production ``TpuSolver(mesh=...)`` path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from kafka_assigner_tpu.ops.assignment import solve_batched, solve_batched_jit
from kafka_assigner_tpu.parallel.mesh import build_mesh

from __graft_entry__ import _example_problem


@pytest.fixture(scope="module")
def part_mesh():
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    return build_mesh(1, 8)  # every device on the part axis


def _solve_plain(enc0, currents, jhashes, p_reals, counters):
    return jax.device_get(
        solve_batched_jit(
            jnp.asarray(currents),
            jnp.asarray(enc0.rack_idx),
            jnp.asarray(counters),
            jnp.asarray(jhashes),
            jnp.asarray(p_reals),
            n=enc0.n,
            rf=enc0.rf,
        )
    )


def _solve_part_sharded(mesh, enc0, currents, jhashes, p_reals, counters):
    shard_p = NamedSharding(mesh, PartitionSpec(None, "part", None))
    repl = NamedSharding(mesh, PartitionSpec())
    fn = jax.jit(
        functools.partial(solve_batched, n=enc0.n, rf=enc0.rf),
        in_shardings=(shard_p, repl, repl, repl, repl),
    )
    return jax.device_get(
        fn(
            jax.device_put(jnp.asarray(currents), shard_p),
            jax.device_put(jnp.asarray(enc0.rack_idx), repl),
            jax.device_put(jnp.asarray(counters), repl),
            jax.device_put(jnp.asarray(jhashes), repl),
            jax.device_put(jnp.asarray(p_reals), repl),
        )
    )


def test_partition_sharded_exact_solve_matches_unsharded(part_mesh):
    enc0, currents, jhashes, p_reals, counters = _example_problem(
        n_topics=4, p=256, n=64, rf=3
    )
    plain = _solve_plain(enc0, currents, jhashes, p_reals, counters)
    sharded = _solve_part_sharded(
        part_mesh, enc0, currents, jhashes, p_reals, counters
    )
    for a, b in zip(plain, sharded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_partition_sharded_giant_single_topic(part_mesh):
    # One 1024-partition topic — the "one giant topic" long-axis case.
    enc0, currents, jhashes, p_reals, counters = _example_problem(
        n_topics=1, p=1024, n=64, rf=3
    )
    plain = _solve_plain(enc0, currents, jhashes, p_reals, counters)
    sharded = _solve_part_sharded(
        part_mesh, enc0, currents, jhashes, p_reals, counters
    )
    ordered_p, _, infeasible_p, _, _ = plain
    ordered_s, _, infeasible_s, _, _ = sharded
    assert not np.asarray(infeasible_p).any()
    np.testing.assert_array_equal(np.asarray(ordered_p), np.asarray(ordered_s))


def test_tpu_solver_mesh_option_is_identical(part_mesh):
    # Production path: TpuSolver(mesh=...) shards the partition axis via data
    # placement; assignments must be byte-identical to the unsharded solver.
    from kafka_assigner_tpu.assigner import TopicAssigner
    from kafka_assigner_tpu.solvers.tpu import TpuSolver

    from .test_invariants import make_cluster

    current, live, rack_map = make_cluster(3, 12, 64, 3, 4)
    topics = [(f"t{i}", current) for i in range(3)]
    plain = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    sharded = TopicAssigner(TpuSolver(mesh=part_mesh)).generate_assignments(
        topics, live, rack_map, -1
    )
    assert plain == sharded
