"""The persistent AOT program store and the ingest-overlapped warm-up
(ISSUE 6): round-trip byte-identity, fingerprint hygiene, corruption
tolerance, write atomicity, the LRU size cap, the bucket-shape contract
(runtime half of kalint KA009), and warm-up failure degradation."""
from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_assigner_tpu.obs import run_capture
from kafka_assigner_tpu.solvers.base import Context
from kafka_assigner_tpu.solvers.tpu import TpuSolver
from kafka_assigner_tpu.utils import programstore
from kafka_assigner_tpu.utils.programstore import (
    BucketContract,
    StoredJit,
    wrap_jit,
)

_uniq = iter(range(10**6))


@pytest.fixture(autouse=True)
def _fresh_store(tmp_path, monkeypatch):
    """Every test gets its own store directory and empty in-memory caches
    (the wrapper registry is process-global by design)."""
    from kafka_assigner_tpu.generator import join_warmup_threads

    monkeypatch.setenv("KA_PROGRAM_STORE_DIR", str(tmp_path / "store"))
    monkeypatch.setenv("KA_PROGRAM_STORE", "1")
    join_warmup_threads()
    programstore.clear_memory()
    programstore._reset_fingerprint_cache()
    yield
    join_warmup_threads()
    programstore.clear_memory()
    programstore._reset_fingerprint_cache()


def _toy_wrapper(contract=None) -> StoredJit:
    """A fresh store-backed wrapper around a trivial jitted function (unique
    name per call: the wrapper registry is keyed by name)."""

    def f(x, n):
        return x * n + 1

    return StoredJit(
        f"toy_{next(_uniq)}", jax.jit(f, static_argnames=("n",)), ("n",),
        contract,
    )


def _exe_files(tmp_path):
    root = tmp_path / "store"
    if not root.exists():
        return []
    return sorted(p for p in root.rglob("*.exe"))


# --- store lifecycle ---------------------------------------------------------

def test_round_trip_is_byte_identical_and_hits(tmp_path):
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    with run_capture() as cold:
        r1 = np.asarray(w(x, n=3))
    assert cold.counters.get("compile.store.misses") == 1
    assert len(_exe_files(tmp_path)) == 1

    # Fresh wrapper over the same entry = a fresh process's view.
    w2 = StoredJit(w.name, w._jit, ("n",))
    with run_capture() as warm:
        r2 = np.asarray(w2(x, n=3))
    assert warm.counters.get("compile.store.hits") == 1
    assert "compile.store.loads_ms" in warm.hists
    np.testing.assert_array_equal(r1, r2)


def test_distinct_signatures_get_distinct_entries(tmp_path):
    w = _toy_wrapper()
    w(jnp.asarray(np.arange(8, dtype=np.int32)), n=3)
    w(jnp.asarray(np.arange(16, dtype=np.int32)), n=3)  # new shape
    w(jnp.asarray(np.arange(8, dtype=np.int32)), n=4)   # new static
    assert len(_exe_files(tmp_path)) == 3


def test_fingerprint_mismatch_is_a_clean_miss(tmp_path, monkeypatch):
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    r1 = np.asarray(w(x, n=3))
    # A different process-stable fingerprint (jax/device/version change) =
    # a different compatibility class: the old entry must not load.
    monkeypatch.setattr(programstore, "STORE_SCHEMA_VERSION", 999)
    programstore._reset_fingerprint_cache()
    w2 = StoredJit(w.name, w._jit, ("n",))
    with run_capture() as run:
        r2 = np.asarray(w2(x, n=3))
    assert run.counters.get("compile.store.misses") == 1
    assert not run.counters.get("compile.store.hits")
    np.testing.assert_array_equal(r1, r2)
    # Two fingerprint directories now coexist.
    fp_dirs = [p for p in (tmp_path / "store").iterdir() if p.is_dir()]
    assert len(fp_dirs) == 2


def test_trace_time_knob_change_rekeys_immediately(tmp_path, monkeypatch):
    """The boundary tests' contract (tests/test_wave_boundaries.py): a
    mid-process `KA_DENSE_MASK_BUDGET` flip bracketed by
    ``jax.clear_caches()`` must never be served a program traced under the
    old value — the knob is part of the entry key, read per dispatch, so
    the SAME wrapper re-keys without any cache reset."""
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    w(x, n=3)
    assert len(_exe_files(tmp_path)) == 1
    monkeypatch.setenv("KA_DENSE_MASK_BUDGET", "4096")
    with run_capture() as run:
        w(x, n=3)
    assert run.counters.get("compile.store.misses") == 1  # re-keyed
    assert len(_exe_files(tmp_path)) == 2
    monkeypatch.delenv("KA_DENSE_MASK_BUDGET")
    with run_capture() as run:
        w(x, n=3)  # original key again: in-memory, no traffic
    assert not run.counters.get("compile.store.misses")
    assert not run.counters.get("compile.store.hits")


def test_corrupted_entry_falls_back_with_warning(tmp_path, capsys):
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    r1 = np.asarray(w(x, n=3))
    (entry,) = _exe_files(tmp_path)
    entry.write_bytes(b"definitely not a pickled executable")
    w2 = StoredJit(w.name, w._jit, ("n",))
    with run_capture() as run:
        r2 = np.asarray(w2(x, n=3))
    np.testing.assert_array_equal(r1, r2)
    assert run.counters.get("compile.store.misses") == 1
    assert "dropping corrupted entry" in capsys.readouterr().err
    # The bad file was replaced by the fresh compile's entry.
    assert len(_exe_files(tmp_path)) == 1


def test_concurrent_writers_never_torch_the_store(tmp_path):
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    compiled = w._jit.lower(x, n=2).compile()
    store = programstore.get_store()
    errs = []

    def _write(i):
        try:
            for _ in range(5):
                store.save("shared-key", compiled)
        except Exception as e:  # save() must never raise, let alone corrupt
            errs.append(e)

    threads = [threading.Thread(target=_write, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == []
    exe = store.load("shared-key")
    assert exe is not None
    np.testing.assert_array_equal(np.asarray(exe(x)), np.arange(8) * 2 + 1)
    # No temp-file debris survived the os.replace dance.
    assert not [p for p in (tmp_path / "store").rglob("*.tmp.*")]


def test_lru_cap_evicts_oldest(tmp_path, monkeypatch):
    store = programstore.get_store()
    d = tmp_path / "store" / "somefp"
    d.mkdir(parents=True)
    for i, name in enumerate(["old.exe", "mid.exe", "new.exe"]):
        p = d / name
        p.write_bytes(b"x" * 600_000)
        os.utime(p, (1_000_000 + i, 1_000_000 + i))
    monkeypatch.setenv("KA_PROGRAM_STORE_MAX_MB", "1")
    store._evict()
    left = {p.name for p in d.glob("*.exe")}
    assert "new.exe" in left and "old.exe" not in left


def test_store_disabled_is_plain_jit(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_PROGRAM_STORE", "0")
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    with run_capture() as run:
        r = np.asarray(w(x, n=3))
    np.testing.assert_array_equal(r, np.arange(8) * 3 + 1)
    assert not run.counters  # no store traffic at all
    assert _exe_files(tmp_path) == []


# --- bucket contract (runtime half of KA009) ---------------------------------

def test_bucket_contract_flags_unbucketed_axes():
    c = BucketContract(((("b", "p", None)), ("n",)))
    ok = [np.zeros((8, 16, 3)), np.zeros(24)]
    assert c.violations(ok) == ()
    bad = c.violations([np.zeros((3, 7, 3)), np.zeros(5)])
    assert len(bad) == 3  # batch not pow2, partition not %8, node not %8


def test_unbucketed_call_dispatches_plain_jit_and_does_not_persist(
    tmp_path, capsys
):
    w = _toy_wrapper(contract=BucketContract((("b",),)))
    x = jnp.asarray(np.arange(5, dtype=np.int32))  # 5 is not a power of two
    with run_capture() as run:
        r = np.asarray(w(x, n=2))
    np.testing.assert_array_equal(r, np.arange(5) * 2 + 1)
    assert run.counters.get("compile.store.unbucketed") == 1
    assert "unbucketed shapes" in capsys.readouterr().err
    assert _exe_files(tmp_path) == []  # ad-hoc shapes never persist


def test_warm_makes_the_signature_resident(tmp_path):
    w = _toy_wrapper()
    x = jnp.asarray(np.arange(8, dtype=np.int32))
    assert w.warm(x, n=5) == "warmed"
    assert len(_exe_files(tmp_path)) == 1
    with run_capture() as run:
        r = np.asarray(w(x, n=5))
    np.testing.assert_array_equal(r, np.arange(8) * 5 + 1)
    # Resident: the call neither hit disk nor compiled.
    assert not run.counters.get("compile.store.hits")
    assert not run.counters.get("compile.store.misses")
    assert w.warm(x, n=5) == "hit"


# --- the real solver through the store ---------------------------------------

def _cluster():
    racks = {100 + i: f"r{i % 3}" for i in range(6)}
    topics = [
        (
            f"t{i}",
            {p: [100 + (p + i + r) % 6 for r in range(3)] for p in range(8)},
        )
        for i in range(4)
    ]
    return topics, racks, set(racks)


def test_solver_round_trip_through_the_store():
    # Doubles as the XLA-cache-interaction regression: the suite's
    # persistent compile cache (conftest) is usually WARM for this
    # signature, and a store entry serialized from a cache-rehydrated
    # executable would fail every load with "Symbols not found" — the
    # store's miss-compile must bypass that cache (_aot_compile).
    topics, racks, nodes = _cluster()
    with run_capture() as cold:
        out1 = TpuSolver().assign_many(topics, racks, nodes, 3, Context())
    assert cold.counters.get("compile.store.misses", 0) >= 1
    programstore.clear_memory()  # fresh-process stand-in
    with run_capture() as warm:
        out2 = TpuSolver().assign_many(topics, racks, nodes, 3, Context())
    assert warm.counters.get("compile.store.hits", 0) >= 1
    assert out1 == out2  # byte-identical decode either way


def test_solver_output_identical_with_store_off(monkeypatch):
    topics, racks, nodes = _cluster()
    out_on = TpuSolver().assign_many(topics, racks, nodes, 3, Context())
    monkeypatch.setenv("KA_PROGRAM_STORE", "0")
    out_off = TpuSolver().assign_many(topics, racks, nodes, 3, Context())
    assert out_on == out_off


# --- warm-up thread: prediction, overlap, degradation ------------------------

@pytest.fixture()
def snapshot(tmp_path):
    cluster = {
        "brokers": [
            {"id": 100 + i, "host": f"h{i}", "port": 9092, "rack": f"r{i % 3}"}
            for i in range(6)
        ],
        "topics": {
            f"topic-{t}": {
                str(p): [100 + (p + t + r) % 6 for r in range(3)]
                for p in range(8)
            }
            for t in range(5)
        },
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(cluster))
    return str(path)


def _run_cli(snapshot, capsys, extra=()):
    from kafka_assigner_tpu.cli import run

    rc = run([
        "--zk_string", f"file://{snapshot}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu", *extra,
    ])
    out = capsys.readouterr()
    return rc, out.out


def test_warmup_predicts_the_real_signature(snapshot, tmp_path):
    """The warm-up thread's predicted program key must equal the solve's:
    one miss total (the warm-up's), zero extra compiles, and a ``warmup``
    span in the report."""
    from kafka_assigner_tpu.cli import run

    report = tmp_path / "report.json"
    rc = run([
        "--zk_string", f"file://{snapshot}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--report-json", str(report),
    ])
    assert rc == 0
    rep = json.loads(report.read_text())
    counters = rep["metrics"]["counters"]
    assert counters.get("compile.store.misses", 0) == 1
    # "warmed" when the thread won the race to the program, "hit" when the
    # solve got there first — either way the prediction matched the key.
    assert (
        counters.get("warmup.warmed", 0) + counters.get("warmup.hit", 0) == 1
    )
    if counters.get("warmup.warmed"):
        # The thread that warmed the program records its span before the
        # solve's per-key lock releases, so it is always in the report; on
        # the (rare) hit path the span write can race report emission.
        warm_spans = [s for s in rep["spans"] if s["name"] == "warmup"]
        assert warm_spans and warm_spans[0]["status"] == "ok"


def test_warmup_crash_degrades_to_cold_path(snapshot, capsys):
    """The injected ``warmup:0=crash`` fault (chaos-matrix class): the solve
    must proceed on the cold path with byte-identical stdout and exit 0."""
    from kafka_assigner_tpu import faults
    from kafka_assigner_tpu.faults.inject import FaultInjector, parse_spec

    faults.reset()
    try:
        rc_base, out_base = _run_cli(snapshot, capsys)
        assert rc_base == 0
        faults.install(FaultInjector(parse_spec("warmup:0=crash")))
        rc, out = _run_cli(snapshot, capsys)
        assert rc == 0
        assert out == out_base
    finally:
        faults.reset()


def test_warmup_crash_is_not_retried_by_the_tail_site(
    snapshot, tmp_path, monkeypatch
):
    """One start attempt per run: when the injected crash consumes the
    in-loop start site (chunk=1 forces it), the tail-chunk site must NOT
    quietly launch a real warm-up — the faulted run stays cold."""
    from kafka_assigner_tpu import faults
    from kafka_assigner_tpu.cli import run
    from kafka_assigner_tpu.faults.inject import FaultInjector, parse_spec

    monkeypatch.setenv("KA_ZK_INGEST_CHUNK", "1")
    faults.install(FaultInjector(parse_spec("warmup:0=crash")))
    try:
        report = tmp_path / "report.json"
        rc = run([
            "--zk_string", f"file://{snapshot}",
            "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
            "--report-json", str(report),
        ])
    finally:
        faults.reset()
    assert rc == 0
    counters = json.loads(report.read_text())["metrics"]["counters"]
    assert counters.get("warmup.failures") == 1
    assert not counters.get("warmup.warmed")
    assert not counters.get("warmup.hit")


def test_warmup_kill_switch(snapshot, tmp_path, monkeypatch):
    monkeypatch.setenv("KA_WARMUP", "0")
    from kafka_assigner_tpu.cli import run

    report = tmp_path / "report.json"
    rc = run([
        "--zk_string", f"file://{snapshot}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--report-json", str(report),
    ])
    assert rc == 0
    rep = json.loads(report.read_text())
    assert not any(s["name"] == "warmup" for s in rep["spans"])
    assert not any(
        k.startswith("warmup.") for k in rep["metrics"]["counters"]
    )


# --- ka-warm -----------------------------------------------------------------

def test_ka_warm_seeds_store_for_snapshot(snapshot, tmp_path, capsys):
    from kafka_assigner_tpu.cli import run_warm

    rc = run_warm(["--zk_string", f"file://{snapshot}"])
    assert rc == 0
    assert "store seeded" in capsys.readouterr().err
    assert len(_exe_files(tmp_path)) >= 1
    # The seeded signature is the one the real solve uses: a fresh-process
    # CLI run must hit, not compile.
    programstore.clear_memory()
    report = tmp_path / "report.json"
    from kafka_assigner_tpu.cli import run

    rc = run([
        "--zk_string", f"file://{snapshot}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--report-json", str(report),
    ])
    assert rc == 0
    rep = json.loads(report.read_text())
    assert rep["metrics"]["counters"].get("compile.store.hits", 0) >= 1
    assert rep["metrics"]["counters"].get("compile.store.misses", 0) == 0


def test_ka_warm_buckets_mode(tmp_path, capsys):
    from kafka_assigner_tpu.cli import run_warm

    rc = run_warm(["--buckets", "8,16,3,12,3"])
    assert rc == 0
    assert len(_exe_files(tmp_path)) >= 1


def test_ka_warm_usage_errors(capsys):
    from kafka_assigner_tpu.cli import run_warm

    assert run_warm([]) == 1
    assert run_warm(["--buckets", "not,numbers"]) == 1
