"""RF-decrease bug-compat mode (``KA_RF_DECREASE_COMPAT=1``, VERDICT r3
item 6): the reference's sticky fill has no per-partition replica limit
(``KafkaAssignmentStrategy.java:320-324``), so lowering the replication
factor retains every current replica that passes the node/rack/capacity
gates and the emitted lists go non-uniform. By default the tpu and native
backends clamp retention to the requested RF (documented divergence); the
compat env var lifts the clamp so all three backends can be differentially
pinned on RF-decrease inputs too.

Contracts (mirroring the general tpu-vs-greedy contract in
``tests/test_tpu_parity.py``):
- native == greedy BYTE-for-byte under compat, including error behavior —
  ``--solver native`` is the byte-equal drop-in replacement on every input
  class, RF decreases now included;
- tpu == greedy BYTE-for-byte under compat too (round 5): compat defaults
  the wave chain to the ``seq`` leg — the reference's ``assignOrphans``
  verbatim — so even decreases that leave orphans place them identically
  (VERDICT r4 item 7). An explicit ``KA_WAVE_MODE`` opts back into the
  auction legs' movement-parity contract;
- without the env var, the default clamp stands: uniform lists at the
  requested RF.
"""
from __future__ import annotations

import random

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .helpers import moved_replicas


def _solve(solver, topics, brokers, racks, rf):
    try:
        return (
            TopicAssigner(solver).generate_assignments(
                topics, brokers, racks, rf
            ),
            None,
        )
    except ValueError as e:
        return None, str(e)


def _random_decrease_case(rng):
    n = rng.choice([8, 12, 16])
    brokers = set(range(1, n + 1))
    racks = {b: f"r{b % 4}" for b in brokers}
    old_rf = rng.randint(3, 4)
    new_rf = rng.randint(1, old_rf - 1)
    p = rng.randint(3, 9)
    topics = [
        (
            f"t{t}",
            {q: rng.sample(sorted(brokers), old_rf) for q in range(p)},
        )
        for t in range(rng.randint(1, 3))
    ]
    return topics, brokers, racks, new_rf


def test_default_mode_still_clamps_to_rf(monkeypatch):
    monkeypatch.delenv("KA_RF_DECREASE_COMPAT", raising=False)
    rng = random.Random(11)
    topics, brokers, racks, new_rf = _random_decrease_case(rng)
    for solver in ("tpu", "native"):
        out, err = _solve(solver, topics, brokers, racks, new_rf)
        if out is None:
            continue  # infeasible decrease: error path, nothing to clamp
        for _, a in out:
            assert all(len(r) == new_rf for r in a.values()), (solver, a)


def test_compat_emits_reference_nonuniform_lists(monkeypatch):
    # The signature reference behavior: partitions retain MORE than the
    # requested RF. Crafted so every current replica survives (each broker
    # appears in exactly cap=2 lists, all lists rack-diverse): no orphans,
    # so tpu (any wave mode) must ALSO match greedy byte-for-byte.
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    brokers = set(range(1, 7))
    racks = {b: f"r{b % 3}" for b in brokers}
    cur = {
        0: [1, 2, 3],
        1: [4, 5, 6],
        2: [1, 5, 6],
        3: [2, 3, 4],
    }
    topics = [("t0", cur)]
    gre, _ = _solve("greedy", topics, brokers, racks, 2)
    tpu, _ = _solve("tpu", topics, brokers, racks, 2)
    nat, _ = _solve("native", topics, brokers, racks, 2)
    assert gre is not None
    assert all(len(r) == 3 for r in gre[0][1].values())  # all retained
    assert tpu == gre == nat  # steady decrease: exact output parity


@pytest.mark.parametrize("seed", range(6))
def test_compat_three_backend_differential(monkeypatch, seed):
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    rng = random.Random(100 + seed)
    topics, brokers, racks, new_rf = _random_decrease_case(rng)

    gre = _solve("greedy", topics, brokers, racks, new_rf)
    nat = _solve("native", topics, brokers, racks, new_rf)
    assert nat == gre  # byte parity incl. error behavior

    # Compat defaults the tpu wave chain to the seq leg (the reference's
    # assignOrphans verbatim), so all THREE backends are byte-equal —
    # orphaned decreases included (VERDICT r4 item 7).
    tpu = _solve("tpu", topics, brokers, racks, new_rf)
    assert tpu == gre

    # The documented opt-out: an explicit auction KA_WAVE_MODE restores the
    # movement-parity contract (byte-level freedom in orphan node choice,
    # counts and error behavior still pinned).
    monkeypatch.setenv("KA_WAVE_MODE", "auto")
    auc, aerr = _solve("tpu", topics, brokers, racks, new_rf)
    monkeypatch.delenv("KA_WAVE_MODE")
    if gre[0] is None or auc is None:
        assert aerr == gre[1]
        return
    by = dict(topics)
    m_g = sum(moved_replicas(by[t], a) for t, a in gre[0])
    m_a = sum(moved_replicas(by[t], a) for t, a in auc)
    assert m_g == m_a
    for (tg, ag), (tt, at) in zip(gre[0], auc):
        assert {q: len(r) for q, r in ag.items()} == {
            q: len(r) for q, r in at.items()
        }, (tg, tt)


def test_compat_is_noop_without_decrease(monkeypatch):
    # Same historical and requested RF: the compat flag must not change the
    # program or the output (width stays None -> identical jit signature).
    brokers = set(range(1, 13))
    racks = {b: f"r{b % 4}" for b in brokers}
    rng = random.Random(5)
    topics = [
        ("t0", {q: rng.sample(sorted(brokers), 3) for q in range(8)})
    ]
    monkeypatch.delenv("KA_RF_DECREASE_COMPAT", raising=False)
    base = _solve("tpu", topics, brokers, racks, -1)
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    compat = _solve("tpu", topics, brokers, racks, -1)
    assert base == compat


def test_compat_single_topic_assign_path(monkeypatch):
    # TpuSolver.assign (non-batched) and NativeGreedySolver.assign must honor
    # compat identically to the greedy oracle.
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    brokers = set(range(1, 13))
    racks = {b: f"r{b % 4}" for b in brokers}
    rng = random.Random(9)
    cur = {q: rng.sample(sorted(brokers), 4) for q in range(5)}
    g = TopicAssigner("greedy").generate_assignment("t", cur, brokers, racks, 2)
    n = TopicAssigner("native").generate_assignment("t", cur, brokers, racks, 2)
    assert g == n
    from kafka_assigner_tpu.solvers.tpu import TpuSolver
    from kafka_assigner_tpu.solvers.base import Context

    # The single-topic assign path threads compat's seq default through
    # solve_assignment_jit, so it too is byte-equal with the oracle.
    t = TpuSolver().assign("t", cur, racks, brokers, set(cur), 2, Context())
    assert t == g


def test_compat_byte_parity_with_orphans(monkeypatch):
    """A decrease that LEAVES ORPHANS (retention collides with capacity so
    some replicas drop and must be re-placed): the previously-open byte-
    parity gap. Compat's seq default closes it; an explicit auction
    KA_WAVE_MODE keeps the old movement-parity contract."""
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    brokers = set(range(1, 9))
    racks = {b: f"r{b % 4}" for b in brokers}
    # 8 brokers, 6 partitions x RF4 = 24 replicas; cap at RF2 request is
    # ceil(12/8) = 2, so retention (3 per broker average) must shed
    # replicas -> orphans exist whenever a partition falls below RF 2.
    rng = random.Random(42)
    cur = {q: rng.sample(sorted(brokers), 4) for q in range(6)}
    topics = [("t0", cur)]

    gre, gerr = _solve("greedy", topics, brokers, racks, 2)
    tpu = _solve("tpu", topics, brokers, racks, 2)
    nat = _solve("native", topics, brokers, racks, 2)
    assert tpu == (gre, gerr) == nat
    if gre is not None:
        # The case is only meaningful if the decrease actually orphaned
        # something: at least one replica moved somewhere new.
        assert sum(moved_replicas(cur, a) for _, a in gre) > 0
