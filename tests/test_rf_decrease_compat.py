"""RF-decrease bug-compat mode (``KA_RF_DECREASE_COMPAT=1``, VERDICT r3
item 6): the reference's sticky fill has no per-partition replica limit
(``KafkaAssignmentStrategy.java:320-324``), so lowering the replication
factor retains every current replica that passes the node/rack/capacity
gates and the emitted lists go non-uniform. By default the tpu and native
backends clamp retention to the requested RF (documented divergence); the
compat env var lifts the clamp so all three backends can be differentially
pinned on RF-decrease inputs too.

Contracts (mirroring the general tpu-vs-greedy contract in
``tests/test_tpu_parity.py``):
- native == greedy BYTE-for-byte under compat, including error behavior —
  ``--solver native`` is the byte-equal drop-in replacement on every input
  class, RF decreases now included;
- tpu == greedy on moved-replica count, per-partition replica counts, and
  error behavior (the wave auction may pick a different eligible node for
  an orphan under multi-orphan contention — the same documented freedom as
  on non-decrease inputs, solvers/tpu.py header);
- tpu == greedy byte-for-byte when the decrease leaves no orphans (sticky
  retention is bit-faithful, and with no wave there is no freedom);
- without the env var, the default clamp stands: uniform lists at the
  requested RF.
"""
from __future__ import annotations

import random

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .helpers import moved_replicas


def _solve(solver, topics, brokers, racks, rf):
    try:
        return (
            TopicAssigner(solver).generate_assignments(
                topics, brokers, racks, rf
            ),
            None,
        )
    except ValueError as e:
        return None, str(e)


def _random_decrease_case(rng):
    n = rng.choice([8, 12, 16])
    brokers = set(range(1, n + 1))
    racks = {b: f"r{b % 4}" for b in brokers}
    old_rf = rng.randint(3, 4)
    new_rf = rng.randint(1, old_rf - 1)
    p = rng.randint(3, 9)
    topics = [
        (
            f"t{t}",
            {q: rng.sample(sorted(brokers), old_rf) for q in range(p)},
        )
        for t in range(rng.randint(1, 3))
    ]
    return topics, brokers, racks, new_rf


def test_default_mode_still_clamps_to_rf(monkeypatch):
    monkeypatch.delenv("KA_RF_DECREASE_COMPAT", raising=False)
    rng = random.Random(11)
    topics, brokers, racks, new_rf = _random_decrease_case(rng)
    for solver in ("tpu", "native"):
        out, err = _solve(solver, topics, brokers, racks, new_rf)
        if out is None:
            continue  # infeasible decrease: error path, nothing to clamp
        for _, a in out:
            assert all(len(r) == new_rf for r in a.values()), (solver, a)


def test_compat_emits_reference_nonuniform_lists(monkeypatch):
    # The signature reference behavior: partitions retain MORE than the
    # requested RF. Crafted so every current replica survives (each broker
    # appears in exactly cap=2 lists, all lists rack-diverse): no orphans,
    # so tpu (any wave mode) must ALSO match greedy byte-for-byte.
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    brokers = set(range(1, 7))
    racks = {b: f"r{b % 3}" for b in brokers}
    cur = {
        0: [1, 2, 3],
        1: [4, 5, 6],
        2: [1, 5, 6],
        3: [2, 3, 4],
    }
    topics = [("t0", cur)]
    gre, _ = _solve("greedy", topics, brokers, racks, 2)
    tpu, _ = _solve("tpu", topics, brokers, racks, 2)
    nat, _ = _solve("native", topics, brokers, racks, 2)
    assert gre is not None
    assert all(len(r) == 3 for r in gre[0][1].values())  # all retained
    assert tpu == gre == nat  # steady decrease: exact output parity


@pytest.mark.parametrize("seed", range(6))
def test_compat_three_backend_differential(monkeypatch, seed):
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    rng = random.Random(100 + seed)
    topics, brokers, racks, new_rf = _random_decrease_case(rng)

    gre = _solve("greedy", topics, brokers, racks, new_rf)
    nat = _solve("native", topics, brokers, racks, new_rf)
    assert nat == gre  # byte parity incl. error behavior

    tpu, terr = _solve("tpu", topics, brokers, racks, new_rf)
    if gre[0] is None or tpu is None:
        assert terr == gre[1]
        return
    by = dict(topics)
    m_g = sum(moved_replicas(by[t], a) for t, a in gre[0])
    m_t = sum(moved_replicas(by[t], a) for t, a in tpu)
    assert m_g == m_t
    # Sticky retention is bit-faithful, so per-partition replica counts
    # match even where the orphan node choice differs.
    for (tg, ag), (tt, at) in zip(gre[0], tpu):
        assert {q: len(r) for q, r in ag.items()} == {
            q: len(r) for q, r in at.items()
        }, (tg, tt)


def test_compat_is_noop_without_decrease(monkeypatch):
    # Same historical and requested RF: the compat flag must not change the
    # program or the output (width stays None -> identical jit signature).
    brokers = set(range(1, 13))
    racks = {b: f"r{b % 4}" for b in brokers}
    rng = random.Random(5)
    topics = [
        ("t0", {q: rng.sample(sorted(brokers), 3) for q in range(8)})
    ]
    monkeypatch.delenv("KA_RF_DECREASE_COMPAT", raising=False)
    base = _solve("tpu", topics, brokers, racks, -1)
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    compat = _solve("tpu", topics, brokers, racks, -1)
    assert base == compat


def test_compat_single_topic_assign_path(monkeypatch):
    # TpuSolver.assign (non-batched) and NativeGreedySolver.assign must honor
    # compat identically to the greedy oracle.
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "1")
    brokers = set(range(1, 13))
    racks = {b: f"r{b % 4}" for b in brokers}
    rng = random.Random(9)
    cur = {q: rng.sample(sorted(brokers), 4) for q in range(5)}
    g = TopicAssigner("greedy").generate_assignment("t", cur, brokers, racks, 2)
    n = TopicAssigner("native").generate_assignment("t", cur, brokers, racks, 2)
    assert g == n
    from kafka_assigner_tpu.solvers.tpu import TpuSolver
    from kafka_assigner_tpu.solvers.base import Context

    t = TpuSolver().assign("t", cur, racks, brokers, set(cur), 2, Context())
    assert {p: len(r) for p, r in t.items()} == {
        p: len(r) for p, r in g.items()
    }
    m_t = sum(1 for p, r in t.items() for b in r if b not in cur[p])
    m_g = sum(1 for p, r in g.items() for b in r if b not in cur[p])
    assert m_t == m_g
