"""ISSUE 14: the request-coalescing batched solve dispatcher
(``daemon/dispatch.py``).

Three layers under test:

- the ``SolveDispatcher`` mechanics alone — gather window vs. size trigger,
  compatibility-keyed packing, per-batch crash containment, identical-plan
  dedup, flush-on-close;
- the daemon integration — coalesced ``/plan`` + ``/whatif`` responses
  byte-identical to solo runs, cross-cluster packing on bucketed programs
  with zero warm recompiles, the ``KA_DISPATCH=0`` kill-switch restoring
  the shared-lock regime, drain flushing the queue, and per-job fallback
  isolation under the ``dispatch:i=crash`` seam;
- the compatibility key itself (content-hashed shared operands).
"""
import contextlib
import http.client
import io
import json
import threading
import time

import numpy as np
import pytest

from kafka_assigner_tpu.cli import run
from kafka_assigner_tpu.daemon.service import AssignerDaemon
from kafka_assigner_tpu.daemon.dispatch import (
    SolveDispatcher,
    active_broker,
    batch_key,
    dispatch_scope,
)
from kafka_assigner_tpu.faults import inject as faults
from kafka_assigner_tpu.obs import promtext

from .jute_server import JuteZkServer, cluster_tree


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _daemon_env(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.5")


@pytest.fixture()
def server():
    s = JuteZkServer(cluster_tree())
    s.start()
    yield s
    s.shutdown()


@contextlib.contextmanager
def running_daemon(spec_or_port, **kwargs):
    kwargs.setdefault("solver", "greedy")
    if isinstance(spec_or_port, int):
        d = AssignerDaemon(f"127.0.0.1:{spec_or_port}", **kwargs)
    elif isinstance(spec_or_port, dict):
        d = AssignerDaemon(clusters=spec_or_port, **kwargs)
    else:
        d = AssignerDaemon(spec_or_port, **kwargs)
    d.start()
    try:
        yield d
    finally:
        d.shutdown()


def fresh_cli(port_or_path, *extra):
    zk = (
        port_or_path if isinstance(port_or_path, str)
        else f"127.0.0.1:{port_or_path}"
    )
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run(["--zk_string", zk, "--mode", "PRINT_REASSIGNMENT",
                  *extra])
    assert rc == 0, err.getvalue()
    return out.getvalue()


def fresh_cli_whatif(port_or_path, *extra):
    zk = (
        port_or_path if isinstance(port_or_path, str)
        else f"127.0.0.1:{port_or_path}"
    )
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run(["--zk_string", zk, "--mode", "RANK_DECOMMISSION", *extra])
    assert rc == 0, err.getvalue()
    return out.getvalue()


def req(port, method, path, payload=None, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        body = None if payload is None else json.dumps(payload)
        conn.request(method, path, body=body)
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, dict(resp.getheaders())
    finally:
        conn.close()


def req_json(port, method, path, payload=None, timeout=60.0):
    status, raw, headers = req(port, method, path, payload, timeout)
    return status, json.loads(raw), headers


def scrape(port):
    status, raw, _ = req(port, "GET", "/metrics")
    assert status == 200
    return promtext.parse(raw.decode("utf-8"))


def counter_total(families, fam):
    data = families.get(fam)
    if data is None:
        return 0.0
    return sum(v for _n, _labels, v in data["samples"])


# --- SolveDispatcher unit mechanics -----------------------------------------


def _rows_job(dispatcher, key, values, calls, results, idx,
              call=None, entry="unit"):
    rows = {"x": np.asarray(values, dtype=np.int64)}

    def default_call(padded):
        calls.append(len(padded["x"]))
        return (np.asarray(padded["x"]) * 2,)

    def pad(k):
        return {"x": np.zeros(k, dtype=np.int64)}

    out = dispatcher.submit_rows(
        entry, key, rows, len(values), pad, call or default_call
    )
    results[idx] = out


def test_compatible_jobs_pack_into_one_dispatch(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "250")
    d = SolveDispatcher(err=io.StringIO())
    try:
        calls, results = [], {}
        threads = [
            threading.Thread(
                target=_rows_job,
                args=(d, "k1", [10 * i + 1, 10 * i + 2], calls, results, i),
            )
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(calls) == 1, "3 compatible jobs must share ONE dispatch"
        # 3 jobs x 2 rows = 6 real rows -> the power-of-two bucket (8).
        assert calls[0] == 8
        for i in range(3):
            (out,) = results[i]
            assert list(out) == [2 * (10 * i + 1), 2 * (10 * i + 2)]
    finally:
        d.close()


def test_incompatible_keys_never_pack(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "250")
    d = SolveDispatcher(err=io.StringIO())
    try:
        calls, results = [], {}
        threads = [
            threading.Thread(
                target=_rows_job,
                args=(d, f"k{i}", [i + 1], calls, results, i),
            )
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(calls) == 2
        for i in range(2):
            (out,) = results[i]
            assert list(out) == [2 * (i + 1)]
    finally:
        d.close()


def test_window_trigger_dispatches_a_singleton(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "50")
    d = SolveDispatcher(err=io.StringIO())
    try:
        calls, results = [], {}
        t0 = time.perf_counter()
        _rows_job(d, "k", [7], calls, results, 0)
        elapsed = time.perf_counter() - t0
        assert list(results[0][0]) == [14]
        # The gather window must have been waited out, but nothing more.
        assert 0.04 <= elapsed < 5.0
    finally:
        d.close()


def test_size_trigger_beats_the_window(monkeypatch):
    # A window far longer than the test budget: only the size trigger can
    # release these jobs in time.
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "30000")
    monkeypatch.setenv("KA_DISPATCH_MAX_BATCH", "2")
    d = SolveDispatcher(err=io.StringIO())
    try:
        calls, results = [], {}
        threads = [
            threading.Thread(
                target=_rows_job, args=(d, "k", [i], calls, results, i)
            )
            for i in range(2)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        assert time.perf_counter() - t0 < 15.0
        assert results[0] is not None and results[1] is not None
        assert len(calls) == 1
    finally:
        d.close()


def test_batch_crash_fails_only_that_batch(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "250")
    d = SolveDispatcher(err=io.StringIO())
    try:
        calls, results, errors = [], {}, {}

        def crashing(padded):
            raise RuntimeError("batch boom")

        def crash_job():
            try:
                _rows_job(d, "bad", [1], calls, results, 0, call=crashing)
            except RuntimeError as e:
                errors[0] = e

        threads = [
            threading.Thread(target=crash_job),
            threading.Thread(
                target=_rows_job, args=(d, "good", [5], calls, results, 1)
            ),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert isinstance(errors.get(0), RuntimeError)
        assert list(results[1][0]) == [10], \
            "the other compatibility class must be untouched"
    finally:
        d.close()


def test_close_flushes_queued_jobs_and_refuses_new_ones(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "30000")
    d = SolveDispatcher(err=io.StringIO())
    calls, results = [], {}
    t = threading.Thread(
        target=_rows_job, args=(d, "k", [3], calls, results, 0)
    )
    t.start()
    time.sleep(0.2)  # let the job reach the queue (window is 30 s)
    t0 = time.perf_counter()
    d.close()
    t.join(timeout=20)
    assert time.perf_counter() - t0 < 10.0, "close() must flush, not wait"
    assert list(results[0][0]) == [6]
    assert d.submit_rows(
        "unit", "k", {"x": np.zeros(1, dtype=np.int64)}, 1,
        lambda k: {"x": np.zeros(k, dtype=np.int64)},
        lambda rows: (rows["x"],),
    ) is None


def test_plan_dedup_one_leader_serves_all(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "5")
    d = SolveDispatcher(err=io.StringIO())
    try:
        ran = []
        gate = threading.Event()

        def fn(out):
            ran.append(1)
            gate.wait(10)  # hold the leader until every follower joined
            out.write("PLAN-BYTES")
            return False

        outs = [io.StringIO() for _ in range(4)]
        results = {}

        def one(i):
            results[i] = d.run_job("key", fn, outs[i])

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # followers enqueue behind the held leader
        gate.set()
        for t in threads:
            t.join(timeout=30)
        assert len(ran) == 1, "identical concurrent plans must run ONCE"
        for i in range(4):
            degraded, _coalesced = results[i]
            assert degraded is False
            assert outs[i].getvalue() == "PLAN-BYTES"
        assert sum(1 for i in range(4) if results[i][1]) == 3
    finally:
        d.close()


def test_plan_leader_crash_isolates_followers(monkeypatch):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "5")
    d = SolveDispatcher(err=io.StringIO())
    try:
        attempts = []
        gate = threading.Event()

        def fn(out):
            attempts.append(threading.current_thread().name)
            if len(attempts) == 1:
                gate.wait(10)
                raise RuntimeError("leader boom")
            out.write("RECOVERED")
            return True

        outs = [io.StringIO() for _ in range(2)]
        results, errors = {}, {}

        def one(i):
            try:
                results[i] = d.run_job("key", fn, outs[i])
            except RuntimeError as e:
                errors[i] = e

        threads = [
            threading.Thread(target=one, args=(i,), name=f"w{i}")
            for i in range(2)
        ]
        threads[0].start()
        time.sleep(0.2)
        threads[1].start()
        time.sleep(0.2)
        gate.set()
        for t in threads:
            t.join(timeout=30)
        # The leader's crash is the leader's; the follower re-ran solo.
        assert len(errors) == 1
        assert len(results) == 1
        (i,) = results
        assert outs[i].getvalue() == "RECOVERED"
        assert results[i][0] is True
        assert len(attempts) == 2
    finally:
        d.close()


def test_run_job_version_change_splits_followers(monkeypatch):
    # ISSUE 19 bugfix: the dedup entry is stamped with the cache version
    # observed at the leader's admission. An arrival that already observes
    # a NEWER live version must never be served the stale leader's bytes —
    # it waits the stale entry out and re-enters admission, while
    # same-version arrivals keep deduping among themselves.
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "5")
    d = SolveDispatcher(err=io.StringIO())
    try:
        version = {"v": 1}
        gate_v1, gate_v2 = threading.Event(), threading.Event()
        ran = []

        def make_fn(tag, gate):
            def fn(out):
                ran.append(tag)
                if ran.count(tag) == 1:
                    gate.wait(10)  # hold the first (leader) run
                out.write(tag)
                return False
            return fn

        fn_v1 = make_fn("V1", gate_v1)
        fn_v2 = make_fn("V2", gate_v2)
        outs = {i: io.StringIO() for i in range(4)}
        results = {}

        def one(i, fn):
            results[i] = d.run_job(
                "key", fn, outs[i], version=lambda: version["v"]
            )

        t0 = threading.Thread(target=one, args=(0, fn_v1))
        t0.start()
        time.sleep(0.2)  # leader admitted @v1, held at its gate
        t1 = threading.Thread(target=one, args=(1, fn_v1))
        t1.start()  # same-version arrival: joins the in-flight leader
        time.sleep(0.2)
        version["v"] = 2  # the resync lands mid-flight
        t2 = threading.Thread(target=one, args=(2, fn_v2))
        t3 = threading.Thread(target=one, args=(3, fn_v2))
        t2.start()
        t3.start()
        time.sleep(0.3)
        assert ran == ["V1"], \
            "post-resync arrivals must not piggyback on the stale leader"
        gate_v1.set()
        time.sleep(0.3)  # both v2 arrivals re-admit under a fresh entry
        gate_v2.set()
        for t in (t0, t1, t2, t3):
            t.join(timeout=30)
        assert ran == ["V1", "V2"], \
            "the v2 arrivals must dedup among themselves (one run)"
        assert outs[0].getvalue() == "V1"
        assert outs[1].getvalue() == "V1"
        assert outs[2].getvalue() == "V2"
        assert outs[3].getvalue() == "V2"
        assert results[1] == (False, True)  # same-version follower
        assert sorted(results[i][1] for i in (2, 3)) == [False, True], \
            "one fresh leader + one follower under the NEW entry"
    finally:
        d.close()


def test_batch_key_fingerprints_content_and_statics():
    a = np.arange(12, dtype=np.int32).reshape(3, 4)
    b = np.arange(12, dtype=np.int32).reshape(3, 4)
    assert batch_key("e", (a,), (1, 2)) == batch_key("e", (b,), (1, 2))
    b2 = b.copy()
    b2[0, 0] = 99
    assert batch_key("e", (a,), (1, 2)) != batch_key("e", (b2,), (1, 2))
    assert batch_key("e", (a,), (1, 2)) != batch_key("e", (a,), (1, 3))
    assert batch_key("e", (a,), (1, 2)) != batch_key("f", (a,), (1, 2))
    assert batch_key("e", (a,), (1, 2)) != \
        batch_key("e", (a.astype(np.int64),), (1, 2))


def test_dispatch_scope_is_thread_local():
    d = SolveDispatcher(err=io.StringIO())
    try:
        assert active_broker() is None
        seen = {}

        def other():
            seen["other"] = active_broker()

        with dispatch_scope(d):
            assert active_broker() is d
            t = threading.Thread(target=other)
            t.start()
            t.join(timeout=10)
        assert seen["other"] is None
        assert active_broker() is None
    finally:
        d.close()


# --- daemon integration ------------------------------------------------------


def test_coalesced_plan_and_whatif_byte_identical_to_solo(
    server, monkeypatch
):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "150")
    base_plan = fresh_cli(server.port, "--solver", "greedy")
    base_whatif = fresh_cli_whatif(server.port, "--solver", "greedy")
    with running_daemon(server.port) as d:
        assert d.dispatcher is not None
        port = d.http_port
        results = {}

        def one(i, path):
            results[(path, i)] = req_json(port, "POST", path, {})

        threads = [
            threading.Thread(target=one, args=(i, p))
            for i in range(4) for p in ("/plan", "/whatif")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        seen_ids = set()
        for (path, i), (status, body, headers) in results.items():
            assert status == 200, (path, i, body)
            assert body["status"] == "ok"
            base = base_plan if path == "/plan" else base_whatif
            assert body["result"]["stdout"] == base, (path, i)
            # Coalescing must not blur request identity: every response
            # keeps ITS OWN correlation id, in header and envelope.
            rid = headers["X-Request-Id"]
            assert body["result"]["request_id"] == rid
            seen_ids.add(rid)
        assert len(seen_ids) == len(results)
        fams = scrape(port)
        assert counter_total(fams, "ka_dispatch_jobs_total") >= 8
        # The queue-wait histogram is separated from solve time.
        assert "ka_daemon_solve_queue_ms" in fams
        assert "ka_dispatch_batch_size" in fams
    # The whatif rows of >= 2 overlapping requests must have coalesced at
    # least once under a 150 ms window.
    assert counter_total(fams, "ka_dispatch_batches_total") >= 1


def test_cross_cluster_packing_zero_warm_recompiles(tmp_path, monkeypatch):
    # Two clusters from the SAME snapshot: byte-identical encodings, so
    # their what-if rows share a compatibility class and pack together.
    snap = {
        "brokers": [
            {"id": i, "host": f"b{i}", "port": 9092, "rack": f"r{i % 2}"}
            for i in range(4)
        ],
        "topics": {
            "events": {str(p): [p % 4, (p + 1) % 4] for p in range(8)},
            "logs": {str(p): [(p + 2) % 4, (p + 3) % 4] for p in range(3)},
        },
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(snap))
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "300")
    base = fresh_cli_whatif(str(path), "--solver", "greedy")

    def round_of_whatifs(port):
        results = {}
        barrier = threading.Barrier(2)

        def one(name):
            barrier.wait(timeout=30)
            results[name] = req_json(
                port, "POST", f"/clusters/{name}/whatif", {}
            )

        threads = [
            threading.Thread(target=one, args=(n,)) for n in ("a", "b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        return results

    with running_daemon({"a": str(path), "b": str(path)},
                        solver="tpu") as d:
        port = d.http_port
        # Warm round: compiles (or store-loads) the coalesced batch
        # bucket's programs.
        first = round_of_whatifs(port)
        fams0 = scrape(port)
        misses0 = counter_total(fams0, "ka_compile_store_misses_total")
        batches0 = counter_total(fams0, "ka_dispatch_batches_total")
        # Warm, coalesced round: same bucket, zero fresh compiles.
        second = round_of_whatifs(port)
        fams1 = scrape(port)
        misses1 = counter_total(fams1, "ka_compile_store_misses_total")
        batches1 = counter_total(fams1, "ka_dispatch_batches_total")
        for results in (first, second):
            for name, (status, body, _h) in results.items():
                assert status == 200, (name, body)
                assert body["result"]["stdout"] == base, name
        assert batches1 > batches0, \
            "the two clusters' rows must have coalesced"
        assert misses1 == misses0, \
            "a warm coalesced dispatch must not recompile"


# --- ISSUE 19: row-packable plans --------------------------------------------


_PACK_SNAP = {
    "brokers": [
        {"id": i, "host": f"b{i}", "port": 9092, "rack": f"r{i % 2}"}
        for i in range(4)
    ],
    "topics": {
        "events": {str(p): [p % 4, (p + 1) % 4] for p in range(8)},
        "logs": {str(p): [(p + 2) % 4, (p + 3) % 4] for p in range(3)},
    },
}


def _barrier_round(port, names, path="/plan", timeout=300.0):
    results = {}
    barrier = threading.Barrier(len(names))

    def one(name):
        barrier.wait(timeout=60)
        results[name] = req_json(
            port, "POST", f"/clusters/{name}{path}", {}, timeout=timeout
        )

    threads = [threading.Thread(target=one, args=(n,)) for n in names]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert len(results) == len(names), "request(s) hung"
    return results


def test_cross_cluster_plan_rows_pack_and_stay_byte_identical(
    tmp_path, monkeypatch
):
    # Two DISTINCT plans (different clusters -> different dedup keys, so
    # body dedup cannot merge them) whose placement encodings are
    # compatible: their placement rows must share ONE device dispatch
    # while each response stays byte-identical to its solo CLI baseline,
    # with zero fresh compiles on the warm coalesced round.
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(_PACK_SNAP))
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "300")
    base = fresh_cli(str(path), "--solver", "tpu")

    with running_daemon({"a": str(path), "b": str(path)},
                        solver="tpu") as d:
        port = d.http_port
        # Warm round: compiles (or store-loads) the coalesced bucket.
        first = _barrier_round(port, ("a", "b"))
        fams0 = scrape(port)
        second = _barrier_round(port, ("a", "b"))
        fams1 = scrape(port)
        for results in (first, second):
            for name, (status, body, _h) in results.items():
                assert status == 200, (name, body)
                assert body["result"]["stdout"] == base, name
        assert (counter_total(fams1, "ka_dispatch_batches_total")
                > counter_total(fams0, "ka_dispatch_batches_total")), \
            "the two plans' placement rows must have shared a dispatch"
        assert (counter_total(fams1, "ka_compile_store_misses_total")
                == counter_total(fams0, "ka_compile_store_misses_total")), \
            "a warm coalesced plan dispatch must not recompile"


def test_incompatible_plan_statics_never_pack(tmp_path, monkeypatch):
    # Clusters with different broker counts encode different placement
    # statics: their rows share no compatibility class, so nothing may
    # coalesce — each plan dispatches its own solo group and the bytes
    # still match each cluster's own baseline.
    snap_b = {
        "brokers": [
            {"id": i, "host": f"b{i}", "port": 9092, "rack": f"r{i % 3}"}
            for i in range(6)
        ],
        "topics": {
            "events": {str(p): [p % 6, (p + 1) % 6] for p in range(8)},
            "logs": {str(p): [(p + 2) % 6, (p + 3) % 6] for p in range(3)},
        },
    }
    path_a = tmp_path / "a.json"
    path_b = tmp_path / "b.json"
    path_a.write_text(json.dumps(_PACK_SNAP))
    path_b.write_text(json.dumps(snap_b))
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "300")
    base_a = fresh_cli(str(path_a), "--solver", "tpu")
    base_b = fresh_cli(str(path_b), "--solver", "tpu")

    with running_daemon({"a": str(path_a), "b": str(path_b)},
                        solver="tpu") as d:
        port = d.http_port
        results = _barrier_round(port, ("a", "b"))
        for name, base in (("a", base_a), ("b", base_b)):
            status, body, _h = results[name]
            assert status == 200, (name, body)
            assert body["result"]["stdout"] == base, name
        fams = scrape(port)
        assert counter_total(fams, "ka_dispatch_jobs_total") >= 2
        assert counter_total(fams, "ka_dispatch_batches_total") == 0, \
            "incompatible placement statics must never share a dispatch"


def test_plan_batch_crash_degrades_only_that_batch(tmp_path, monkeypatch):
    # A crash inside the coalesced placement dispatch costs retries,
    # never responses: every job in the crashed batch re-runs its own
    # rows solo and still serves bytes identical to the solo baseline,
    # and the dispatcher thread survives for later requests.
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(_PACK_SNAP))
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "300")
    faults.install(faults.FaultInjector(faults.parse_spec(
        "dispatch:0=crash"
    )))
    base = fresh_cli(str(path), "--solver", "tpu")

    with running_daemon({"a": str(path), "b": str(path)},
                        solver="tpu") as d:
        port = d.http_port
        results = _barrier_round(port, ("a", "b"))
        inj = faults.active_injector()
        assert [str(e) for e in inj.fired] == ["dispatch:0=crash"]
        for name, (status, body, _h) in results.items():
            assert status == 200, (name, body)
            assert body["result"]["stdout"] == base, name
        fams = scrape(port)
        assert counter_total(fams, "ka_dispatch_solo_fallbacks_total") >= 2
        # The dispatcher thread survived: a later plan keeps working.
        status, body, _h = req_json(
            port, "POST", "/clusters/a/plan", {}, timeout=300
        )
        assert status == 200 and body["result"]["stdout"] == base


def test_kill_switch_plan_parity_under_tpu(tmp_path, monkeypatch):
    # KA_DISPATCH=0 with --solver tpu: no broker is installed, so plans
    # take the fused (unsplit) solve path under the shared lock — and
    # must serve exactly the same bytes the routed plane serves.
    monkeypatch.setenv("KA_DISPATCH", "0")
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(_PACK_SNAP))
    base = fresh_cli(str(path), "--solver", "tpu")

    with running_daemon({"a": str(path), "b": str(path)},
                        solver="tpu") as d:
        assert d.dispatcher is None
        port = d.http_port
        results = _barrier_round(port, ("a", "b"))
        for name, (status, body, _h) in results.items():
            assert status == 200, (name, body)
            assert body["result"]["stdout"] == base, name
        fams = scrape(port)
        assert counter_total(fams, "ka_dispatch_jobs_total") == 0
        assert counter_total(fams, "ka_dispatch_batches_total") == 0


def test_kill_switch_restores_lock_semantics(server, monkeypatch):
    monkeypatch.setenv("KA_DISPATCH", "0")
    base_plan = fresh_cli(server.port, "--solver", "greedy")
    base_whatif = fresh_cli_whatif(server.port, "--solver", "greedy")
    with running_daemon(server.port) as d:
        assert d.dispatcher is None
        assert d.supervisor()._dispatcher is None
        port = d.http_port
        results = {}

        def one(i, path):
            results[(path, i)] = req_json(port, "POST", path, {})

        threads = [
            threading.Thread(target=one, args=(i, p))
            for i in range(3) for p in ("/plan", "/whatif")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for (path, i), (status, body, _h) in results.items():
            assert status == 200
            assert body["status"] == "ok"
            base = base_plan if path == "/plan" else base_whatif
            assert body["result"]["stdout"] == base
        fams = scrape(port)
        assert counter_total(fams, "ka_dispatch_jobs_total") == 0
        assert counter_total(fams, "ka_dispatch_batches_total") == 0


def test_dispatch_crash_degrades_per_job_not_per_daemon(
    server, monkeypatch
):
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "150")
    faults.install(faults.FaultInjector(faults.parse_spec(
        "dispatch:0=crash"
    )))
    base_whatif = fresh_cli_whatif(server.port, "--solver", "greedy")
    with running_daemon(server.port) as d:
        port = d.http_port
        results = {}

        def one(i):
            results[i] = req_json(port, "POST", "/whatif", {})

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        inj = faults.active_injector()
        assert [str(e) for e in inj.fired] == ["dispatch:0=crash"]
        # Every job in the crashed batch re-ran solo: all requests still
        # serve 200, byte-identical — the crash cost retries, never
        # responses, and the dispatcher thread survived (later requests
        # keep working).
        for i, (status, body, _h) in results.items():
            assert status == 200, (i, body)
            assert body["result"]["stdout"] == base_whatif
        status, body, _h = req_json(port, "POST", "/whatif", {})
        assert status == 200 and body["result"]["stdout"] == base_whatif
        fams = scrape(port)
        assert counter_total(fams, "ka_dispatch_solo_fallbacks_total") >= 1


def test_shutdown_flushes_the_gather_queue(server, monkeypatch):
    # A gather window far beyond the drain budget: only the drain's
    # flush-on-close can complete the in-flight request in time.
    monkeypatch.setenv("KA_DISPATCH_WINDOW_MS", "30000")
    monkeypatch.setenv("KA_DAEMON_DRAIN_TIMEOUT", "1.0")
    base_whatif = fresh_cli_whatif(server.port, "--solver", "greedy")
    d = AssignerDaemon(f"127.0.0.1:{server.port}", solver="greedy")
    d.start()
    port = d.http_port
    result = {}

    def one():
        result["r"] = req_json(port, "POST", "/whatif", {}, timeout=120)

    t = threading.Thread(target=one)
    t.start()
    time.sleep(0.5)  # the request is now parked in the gather window
    t0 = time.perf_counter()
    d.shutdown()
    t.join(timeout=60)
    elapsed = time.perf_counter() - t0
    status, body, _h = result["r"]
    assert status == 200
    assert body["result"]["stdout"] == base_whatif
    assert elapsed < 20.0, \
        "shutdown must flush the queue, not sit out the 30 s window"
