"""Differential tests: the C boundary codec (native/hostcodec.c) against the
numpy reference paths in models/problem.py. Every property the solver relies
on at the dict<->tensor boundary — sorted partition rows, dead-broker -1
mapping, ragged-list fills, incomplete-row decode — must be byte-identical
between the two implementations (KA_HOSTCODEC=0 selects numpy)."""
from __future__ import annotations

import random

import numpy as np
import pytest

from kafka_assigner_tpu.models.problem import (
    decode_assignments_batched,
    encode_topic_group,
)

try:
    from kafka_assigner_tpu.native.build import build_hostcodec, load_hostcodec

    # The load path is dlopen-only since ISSUE 14 (no compiler may run
    # under the daemon's solve queue); tests are a startup site, so build
    # explicitly first — the same split the CLI/daemon entry points use.
    build_hostcodec()
    load_hostcodec()
    HAVE_CODEC = True
except Exception:  # toolchain-less environment: numpy path only
    HAVE_CODEC = False

pytestmark = pytest.mark.skipif(
    not HAVE_CODEC, reason="hostcodec unbuildable in this environment"
)


def _random_group(rng, n_topics, max_p, brokers, ragged=False):
    topics = []
    for i in range(n_topics):
        p = rng.randint(0, max_p)
        cur = {}
        # shuffled, sparse partition ids: the codec must sort them
        pids = rng.sample(range(max_p * 3), p)
        for pid in pids:
            w = rng.randint(0, 4) if ragged else 3
            # include ids outside the live set (dead brokers -> -1)
            cur[pid] = [
                rng.choice(list(brokers) + [99999, -5]) for _ in range(w)
            ]
        topics.append((f"topic-{i:03d}", cur))
    return topics


def _encode_both(monkeypatch, topics, racks, brokers, rf):
    # an ambient KA_HOSTCODEC=0 would silently make this numpy-vs-numpy
    monkeypatch.delenv("KA_HOSTCODEC", raising=False)
    out_c = encode_topic_group(topics, racks, brokers, rf)
    monkeypatch.setenv("KA_HOSTCODEC", "0")
    out_np = encode_topic_group(topics, racks, brokers, rf)
    monkeypatch.delenv("KA_HOSTCODEC")
    return out_c, out_np


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("ragged", [False, True])
def test_encode_matches_numpy(monkeypatch, seed, ragged):
    rng = random.Random(seed)
    brokers = set(range(10, 40))
    racks = {b: f"r{b % 4}" for b in brokers}
    topics = _random_group(rng, 9, 12, brokers, ragged=ragged)
    (encs_c, cur_c, jh_c, pr_c), (encs_n, cur_n, jh_n, pr_n) = _encode_both(
        monkeypatch, topics, racks, brokers, 3
    )
    np.testing.assert_array_equal(cur_c, cur_n)
    np.testing.assert_array_equal(jh_c, jh_n)
    np.testing.assert_array_equal(pr_c, pr_n)
    assert len(encs_c) == len(encs_n)
    for ec, en in zip(encs_c, encs_n):
        assert ec.topic == en.topic and ec.p == en.p and ec.jhash == en.jhash
        assert ec.p_pad == en.p_pad and ec.rf == en.rf
        np.testing.assert_array_equal(ec.partition_ids, en.partition_ids)
        np.testing.assert_array_equal(ec.current, en.current)


def test_decode_matches_numpy(monkeypatch):
    monkeypatch.delenv("KA_HOSTCODEC", raising=False)
    rng = random.Random(3)
    brokers = set(range(1, 25))
    racks = {b: f"r{b % 5}" for b in brokers}
    topics = _random_group(rng, 7, 10, brokers)
    encs, currents, _, _ = encode_topic_group(topics, racks, brokers, 3)
    # synthesize an "ordered" tensor with complete, partial and empty rows
    ordered = np.full((len(encs), encs[0].p_pad, 3), -1, dtype=np.int32)
    n = encs[0].n
    for i, e in enumerate(encs):
        for row in range(e.p):
            kind = rng.randint(0, 3)
            if kind == 0:
                continue  # empty row
            picks = rng.sample(range(n), 3 if kind > 1 else 2)
            ordered[i, row, : len(picks)] = picks
    out_c = decode_assignments_batched(encs, ordered)
    monkeypatch.setenv("KA_HOSTCODEC", "0")
    out_np = decode_assignments_batched(encs, ordered)
    monkeypatch.delenv("KA_HOSTCODEC")
    assert out_c == out_np


def test_codec_error_paths():
    codec = load_hostcodec()
    with pytest.raises(TypeError):
        codec.scan_dims("not a list")
    with pytest.raises(TypeError):
        codec.scan_dims([1])
    brokers = np.arange(4, dtype=np.int64)
    cur = np.full((1, 2, 2), -1, np.int32)
    pre = np.zeros(1, np.int32)
    pid = np.full((1, 2), -1, np.int64)
    with pytest.raises(ValueError):
        # replica list longer than width
        codec.encode_rows([{0: [1, 2, 3]}], brokers, cur, pre, pid)
    with pytest.raises(ValueError):
        # more partitions than p_pad
        codec.encode_rows([{0: [1], 1: [2], 2: [3]}], brokers, cur, pre, pid)
    with pytest.raises(TypeError):
        # non-int replica entry
        codec.encode_rows([{0: ["x"]}], brokers, cur, pre, pid)


def test_numpy_int_keys_and_values(monkeypatch):
    # np.int64 partition keys and replica ids flow through PyNumber_Index
    brokers = set(range(1, 9))
    racks = {b: "r1" for b in brokers}
    cur = {np.int64(3): [np.int64(1), np.int64(2)], np.int64(0): [3, 4]}
    topics = [("t", cur)]
    (encs_c, cur_c, _, _), (encs_n, cur_n, _, _) = _encode_both(
        monkeypatch, topics, racks, brokers, 2
    )
    np.testing.assert_array_equal(cur_c, cur_n)
    np.testing.assert_array_equal(
        encs_c[0].partition_ids, encs_n[0].partition_ids
    )


def test_decode_rows_rejects_out_of_range_p_reals():
    codec = load_hostcodec()
    brokers = np.arange(4, dtype=np.int64)
    ordered = np.zeros((1, 2, 2), np.int32)
    pid = np.zeros((1, 2), np.int64)
    with pytest.raises(ValueError):
        codec.decode_rows(
            ordered, brokers, pid, np.array([1000000], np.int32), 1
        )
    with pytest.raises(ValueError):
        codec.decode_rows(ordered, brokers, pid, np.array([-1], np.int32), 1)


def test_decode_rows_rejects_out_of_range_broker_index():
    # A solver bug emitting a broker index past the broker table must fail
    # as loudly as the numpy decode path (IndexError there), not be masked
    # as a silently shorter replica list (ADVICE r3). idx == -1 stays the
    # legitimate padding skip.
    codec = load_hostcodec()
    brokers = np.arange(4, dtype=np.int64)
    ordered = np.full((1, 2, 2), -1, np.int32)
    ordered[0, 0] = [0, 4]  # 4 >= n_brokers
    pid = np.zeros((1, 2), np.int64)
    with pytest.raises(ValueError, match="broker index 4 out of range"):
        codec.decode_rows(ordered, brokers, pid, np.array([2], np.int32), 1)


def test_non_dict_mapping_takes_numpy_path(monkeypatch):
    # MappingProxyType currents must keep working whether or not the C codec
    # is buildable (the codec only accepts real dicts).
    from types import MappingProxyType

    monkeypatch.delenv("KA_HOSTCODEC", raising=False)
    brokers = set(range(1, 9))
    racks = {b: f"r{b % 3}" for b in brokers}
    cur = MappingProxyType({0: [1, 2], 1: [2, 3]})
    out = encode_topic_group([("t", cur)], racks, brokers, 2)
    monkeypatch.setenv("KA_HOSTCODEC", "0")
    ref = encode_topic_group([("t", dict(cur))], racks, brokers, 2)
    np.testing.assert_array_equal(out[1], ref[1])
