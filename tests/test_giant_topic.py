"""Long-axis story at HEADLINE scale (VERDICT r3 item 3): one 200k-partition
topic over a 5.1k-broker cluster, partition-sharded 8 ways on the virtual
mesh, pinned bit-identical to the unsharded solve and movement-par with the
native oracle.

Instance design:
- The *expansion* instance (5000 -> 5100 brokers, nothing removed) is
  greedy-feasible: capacity drops 120 -> 118, every broker sheds 2 replicas,
  ~10k orphans flow to the new brokers with slack — so the oracle parity leg
  is meaningful.
- The *replace-100* instance (brokers 0..99 -> 5000..5099) is EXACTLY
  saturated (orphans == free slots) and the reference's first-fit provably
  dead-ends on it ("Partition 196691 could not be fully assigned!",
  KafkaAssignmentStrategy.java:29-30 caveat at headline scale) while the
  balance wave solves it — executed evidence in BASELINE.md (giant-topic
  section); re-running that 6-minute instance here would double an already
  compile-heavy test.

Marked slow: the 200k-partition program costs minutes of XLA CPU compile on
a small box (the persistent compile cache makes reruns cheap). The same
sharded shape AOT-compiles for real v5e ICI in scripts/tpu_aot_multichip.py
(multichip3 stage).
"""
from __future__ import annotations

import time

import jax
import pytest

from kafka_assigner_tpu.assigner import TopicAssigner
from kafka_assigner_tpu.models.synthetic import rack_striped_cluster
from kafka_assigner_tpu.parallel.mesh import build_mesh
from kafka_assigner_tpu.solvers.tpu import TpuSolver

from .helpers import moved_replicas


def _moved(topics, pairs):
    cur = dict(topics)
    return sum(moved_replicas(cur[t], a) for t, a in pairs)


@pytest.mark.slow
def test_giant_saturated_replace100_solves_at_full_scale():
    """The showcase instance (VERDICT r4 item 4): exactly saturated
    replace-100 at 200k partitions — the reference's first-fit provably
    dead-ends here; round 5's balance_quota hybrid solves it in ~41 waves
    (~3 s warm on the 1-core box, vs ~107-133 s via the round-4
    strand-then-rescue path). Pinned at FULL scale: completion + optimal
    movement (exactly the replaced brokers' replicas)."""
    topic_map, _, racks = rack_striped_cluster(
        5000, 1, 200000, 3, 10, name_fmt="giant-{:04d}", extra_brokers=100
    )
    topics = list(topic_map.items())
    live = set(range(100, 5100))  # brokers 0..99 -> 5000..5099
    rack_map = {b: racks[b] for b in live}
    TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )  # compile
    t0 = time.perf_counter()
    pairs = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    warm_s = time.perf_counter() - t0
    assert _moved(topics, pairs) == 12000  # optimal
    # The quota hybrid solves this in ~3-9 s warm; the strand-then-rescue
    # path it replaced takes 100-140 s (QUOTA_TUNING_r05.json: neighboring
    # knob values strand). 60 s separates the two robustly even under heavy
    # box contention — this guards the DEFAULT's fast path, not just
    # completion (the rescue also completes with optimal movement).
    assert warm_s < 60, f"saturated giant took {warm_s:.0f}s (rescue path?)"


@pytest.mark.slow
def test_giant_topic_part_sharded_equality_and_oracle_parity():
    assert len(jax.devices()) == 8
    topic_map, _, racks = rack_striped_cluster(
        5000, 1, 200000, 3, 10, name_fmt="giant-{:04d}", extra_brokers=100
    )
    topics = list(topic_map.items())
    live = set(range(5100))  # expansion: +100 brokers, nothing removed
    rack_map = {b: racks[b] for b in live}

    unsharded = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    mesh = build_mesh(1, 8)  # all 8 devices on the partition axis
    sharded = TopicAssigner(TpuSolver(mesh=mesh)).generate_assignments(
        topics, live, rack_map, -1
    )
    assert sharded == unsharded  # bit-identical across the 8-way part axis

    native = TopicAssigner("native").generate_assignments(
        topics, live, rack_map, -1
    )
    m_t, m_n = _moved(topics, unsharded), _moved(topics, native)
    assert m_t == m_n and m_t > 0
