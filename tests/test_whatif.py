"""What-if fleet tests on the virtual 8-device CPU mesh: scenario metrics
must agree with individually-run solves, and sharding across the mesh must
not change results."""
from __future__ import annotations

import jax
import pytest

from kafka_assigner_tpu.assigner import TopicAssigner
from kafka_assigner_tpu.parallel.mesh import build_mesh, scenario_sharding
from kafka_assigner_tpu.parallel.whatif import (
    evaluate_removal_scenarios,
    rank_decommission_candidates,
)

from .helpers import moved_replicas
from .test_invariants import make_cluster


@pytest.fixture(scope="module")
def cluster():
    current, live, rack_map = make_cluster(0, 16, 32, 3, 4)
    topics = {f"t{i}": current for i in range(3)}
    return topics, live, rack_map


def test_whatif_matches_individual_solves(cluster):
    topics, live, rack_map = cluster
    scenarios = [[], [100], [101], [100, 104]]
    results = evaluate_removal_scenarios(topics, live, rack_map, scenarios, 3)
    assert len(results) == 4

    for res, removed in zip(results, scenarios):
        live_s = set(live) - set(removed)
        rack_s = {b: r for b, r in rack_map.items() if b in live_s}
        assigner = TopicAssigner("tpu")
        try:
            pairs = assigner.generate_assignments(topics, live_s, rack_s, 3)
            moved = sum(
                moved_replicas(topics[t], a) for t, a in pairs
            )
            assert res.feasible, res
            assert res.moved_replicas == moved, (removed, res.moved_replicas, moved)
        except ValueError:
            assert not res.feasible


def test_whatif_empty_scenario_moves_nothing(cluster):
    topics, live, rack_map = cluster
    (res,) = evaluate_removal_scenarios(topics, live, rack_map, [[]], 3)
    assert res.feasible and res.moved_replicas == 0


def test_whatif_sharded_equals_unsharded(cluster):
    topics, live, rack_map = cluster
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    mesh = build_mesh()  # 8x1: scenarios axis across all devices
    scenarios = [[100 + i] for i in range(8)]
    unsharded = evaluate_removal_scenarios(topics, live, rack_map, scenarios, 3)
    sharded = evaluate_removal_scenarios(
        topics, live, rack_map, scenarios, 3, mesh=mesh
    )
    assert unsharded == sharded


def test_rank_decommission_candidates(cluster):
    topics, live, rack_map = cluster
    ranked = rank_decommission_candidates(topics, live, rack_map, None, 3)
    assert len(ranked) == len(live)
    # Results are sorted: feasible before infeasible, then by movement.
    feas = [r.feasible for r in ranked]
    assert feas == sorted(feas, reverse=True)
    moves = [r.moved_replicas for r in ranked if r.feasible]
    assert moves == sorted(moves)


def test_unknown_broker_in_scenario(cluster):
    topics, live, rack_map = cluster
    with pytest.raises(ValueError, match="unknown broker"):
        evaluate_removal_scenarios(topics, live, rack_map, [[999999]], 3)


def test_whatif_nonuniform_rf_raises(cluster):
    # ADVICE round 1: the sweep must apply the assigner's RF-uniformity
    # assertion instead of keying off an arbitrary first partition.
    topics, live, rack_map = cluster
    bad = dict(topics)
    bad["ragged"] = {0: [100, 101, 102], 1: [100, 101]}
    with pytest.raises(ValueError, match="unexpected replication factor"):
        evaluate_removal_scenarios(bad, live, rack_map, [[]], -1)


def test_whatif_scenario_chunking_matches_unchunked(cluster, monkeypatch):
    # Memory chunking of the dense sweep's scenario axis (round 4: the
    # giant-topic shape makes one (S, B, P_pad, RF) dispatch multi-GB):
    # forcing a tiny budget splits the sweep into many fixed-size blocks,
    # which must be bit-identical to the single-dispatch sweep — including
    # through a mesh (blocks stay mesh-tileable).
    topics, live, rack_map = cluster
    scenarios = [[b] for b in sorted(live)[:12]]
    monkeypatch.setenv("KA_WHATIF_INCREMENTAL", "0")  # pin the dense path
    expected = evaluate_removal_scenarios(
        topics, live, rack_map, scenarios, 3
    )
    monkeypatch.setenv("KA_WHATIF_MEMBUDGET", "1")  # 1 scenario per block
    chunked = evaluate_removal_scenarios(
        topics, live, rack_map, scenarios, 3
    )
    assert chunked == expected
    mesh = build_mesh()
    chunked_mesh = evaluate_removal_scenarios(
        topics, live, rack_map, scenarios, 3, mesh=mesh
    )
    assert chunked_mesh == expected
