"""Consumer-group workload family (ISSUE 13): encode invariants, the
device↔host packing parity pin on randomized instances, the one-dispatch
autoscale sweep, the CLI surface, backend hooks (snapshot section, loud
refusal, explicit synthetic), and the daemon endpoints with crash
fallback."""
from __future__ import annotations

import contextlib
import io
import json
import random

import numpy as np
import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import run_groups
from kafka_assigner_tpu.errors import IngestError, SolveError
from kafka_assigner_tpu.groups.encode import decode_plan, encode_group
from kafka_assigner_tpu.groups.model import (
    GROUPS_SCHEMA_VERSION,
    synthetic_group_state,
    validate_groups_plan,
    validate_groups_sweep,
)
from kafka_assigner_tpu.groups.solve import (
    default_counts,
    group_plan_envelope,
    group_sweep_envelope,
    load_group_states,
)
from kafka_assigner_tpu.io.base import ConsumerGroupState, GroupMember
from kafka_assigner_tpu.io.snapshot import SnapshotBackend, write_snapshot
from kafka_assigner_tpu.solvers.greedypack import (
    pack_consumers,
    scale_weights,
)


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


def _state(rng, n_topics=2, max_parts=6, n_members=3, owned=0.8):
    topics = {
        f"t{i}": list(range(rng.randint(1, max_parts)))
        for i in range(n_topics)
    }
    members = tuple(
        GroupMember(f"m{i:02d}", float(rng.choice([0, 40, 200, 900])))
        for i in range(n_members)
    )
    ids = [m.member_id for m in members]
    assignment, lags = {}, {}
    for t, parts in topics.items():
        for p in parts:
            if rng.random() < owned:
                assignment.setdefault(t, {})[p] = rng.choice(ids + [None])
            lags.setdefault(t, {})[p] = rng.choice(
                [0, 1, 7, 120, 5000, 10**6]
            )
    return ConsumerGroupState("g", members, assignment, lags)


def _host(enc, alive, scale=100):
    w = scale_weights([int(x) for x in enc.weights], scale, enc.p)
    return pack_consumers(
        w, [int(x) for x in enc.capacities],
        [int(x) for x in enc.current], [int(x) for x in enc.proc_order],
        [bool(x) for x in alive], enc.p,
    )


# --- encode -------------------------------------------------------------------

def test_encode_buckets_and_weights():
    st = _state(random.Random(0))
    enc = encode_group(st, max_consumers=10, max_scale_pct=400)
    assert enc.p_pad % 8 == 0 and enc.p_pad >= enc.p
    assert enc.c_pad % 8 == 0 and enc.c_pad >= 10
    # Real rows carry weight >= 1 (an owned partition always costs);
    # padding rows are inert.
    assert (enc.weights[: enc.p] >= 1).all()
    assert (enc.weights[enc.p:] == 0).all()
    # proc_order visits every row once, descending weight over real rows.
    assert sorted(enc.proc_order.tolist()) == list(range(enc.p_pad))
    real = enc.proc_order[: enc.p]
    ws = [int(enc.weights[r]) for r in real]
    assert ws == sorted(ws, reverse=True)


def test_encode_overflow_guard_shifts_the_domain():
    st = ConsumerGroupState(
        "big", (GroupMember("m0", 0.0), GroupMember("m1", 0.0)),
        {"t": {0: "m0", 1: "m1"}},
        {"t": {0: 2**30, 1: 2**29}},
    )
    enc = encode_group(st, max_scale_pct=800)
    assert enc.shift > 0
    total = int(enc.weights.astype(np.int64).sum())
    assert total * 8 < 2**30  # the largest sweep scale stays int32-exact


def test_encode_rejects_unknown_weight_kind():
    st = _state(random.Random(1))
    with pytest.raises(ValueError, match="weight column"):
        encode_group(st, weight="entropy")
    with pytest.raises(ValueError, match="weight_values"):
        encode_group(st, weight="throughput")


# --- the host oracle's semantics ---------------------------------------------

def test_oracle_sticky_keeps_fitting_owners():
    # Two partitions on m0 fit (10+10 <= 25); the third overflows the
    # prefix and moves to m1 (first-fit-decreasing, max headroom).
    res = pack_consumers(
        weights=[10, 10, 10, 0],
        capacities=[25, 100],
        current=[0, 0, 0, -1],
        proc_order=[0, 1, 2, 3],
        alive=[True, True],
        p_real=3,
    )
    assert res.assigned[:3] == [0, 0, 1]
    assert res.load == [20, 10]
    assert res.moved == 1 and res.feasible


def test_oracle_overflow_counts_not_crashes():
    res = pack_consumers(
        weights=[50, 50], capacities=[60], current=[-1, -1],
        proc_order=[0, 1], alive=[True], p_real=2,
    )
    assert res.assigned == [0, 0]
    assert res.overflowed == 1 and not res.feasible
    assert res.load == [100]


def test_oracle_dead_consumer_orphans_its_partitions():
    res = pack_consumers(
        weights=[5, 5], capacities=[100, 100], current=[1, 1],
        proc_order=[0, 1], alive=[True, False], p_real=2,
    )
    assert res.assigned == [0, 0] and res.moved == 2


# --- the parity pin -----------------------------------------------------------

@pytest.mark.parametrize("seed", range(8))
def test_device_matches_oracle_randomized(seed):
    from kafka_assigner_tpu.parallel.whatif import pack_group_on_device

    rng = random.Random(seed)
    # Skewed lag, heterogeneous capacities, consumers > partitions and
    # vice versa (the satellite's explicit instance classes).
    n_members = rng.choice([1, 2, 5, 12])
    st = _state(
        rng, n_topics=rng.randint(1, 3), max_parts=rng.choice([2, 9]),
        n_members=n_members, owned=rng.choice([0.3, 0.95]),
    )
    enc = encode_group(st, max_consumers=2 * n_members, max_scale_pct=300)
    alive = enc.alive(enc.real_members)
    dev = pack_group_on_device(
        enc.weights, enc.capacities, enc.current, enc.proc_order,
        alive, enc.p,
    )
    host = _host(enc, alive)
    assert [int(x) for x in dev[0]] == host.assigned
    assert [int(x) for x in dev[1]] == host.load
    assert int(dev[2]) == host.moved
    assert int(dev[3]) == host.overflowed
    assert bool(dev[4]) == (not host.feasible)


@pytest.mark.parametrize("seed", range(4))
def test_sweep_matches_oracle_per_candidate(seed):
    from kafka_assigner_tpu.parallel.whatif import (
        evaluate_group_candidates,
    )

    rng = random.Random(100 + seed)
    st = _state(rng, n_members=rng.choice([2, 4]))
    enc = encode_group(st, max_consumers=8, max_scale_pct=300)
    cand = [(s, k) for s in (100, 150, 300) for k in (1, 2, 4, 8)]
    alive = np.zeros((len(cand), enc.c_pad), dtype=bool)
    for i, (_s, k) in enumerate(cand):
        alive[i, :k] = True
    scales = [s for s, _k in cand]
    moved, over, infeas, load = evaluate_group_candidates(
        enc.weights, enc.capacities, enc.current, enc.proc_order,
        alive, scales, enc.p,
    )
    for i, (s, k) in enumerate(cand):
        host = _host(enc, alive[i], scale=s)
        assert int(moved[i]) == host.moved, (s, k)
        assert int(over[i]) == host.overflowed, (s, k)
        assert [int(x) for x in load[i]] == host.load, (s, k)


def test_sweep_64_candidates_is_one_dispatch():
    from kafka_assigner_tpu import obs

    st = _state(random.Random(42), n_members=4)
    enc = encode_group(st, max_consumers=8, max_scale_pct=800)
    counts = [1, 2, 3, 4, 5, 6, 7, 8]
    scales = [100, 125, 150, 200, 300, 400, 600, 800]
    with obs.run_capture() as run:
        body, degraded = group_sweep_envelope(
            enc, counts, scales, groups_real=True,
        )
    assert not degraded
    assert len(body["candidates"]) == 64
    assert run.counters["groups.candidates"] == 64
    # The acceptance bar: ONE batched device fan-out, not 64 solves.
    assert run.counters["groups.dispatches"] == 1
    assert validate_groups_sweep(body) == []


def test_sweep_monotone_feasibility_and_recommendation():
    # Uniform weights, exact capacities: k consumers of capacity C pack
    # k*C of weight, so feasibility is monotone in k and the recommended
    # count is the true knee.
    members = tuple(GroupMember(f"m{i}", 100.0) for i in range(8))
    st = ConsumerGroupState(
        "g", members,
        {"t": {p: None for p in range(12)}},
        {"t": {p: 49 for p in range(12)}},  # weight 50 each, total 600
    )
    enc = encode_group(st, max_consumers=8, max_scale_pct=100)
    body, _ = group_sweep_envelope(
        enc, [1, 2, 3, 4, 5, 6, 7, 8], [100], groups_real=True,
    )
    feas = {c["consumers"]: c["feasible"] for c in body["candidates"]}
    assert body["recommended_consumers"] == 6  # 600 weight / 100 cap
    for k in range(1, 9):
        assert feas[k] == (k >= 6)


def test_sweep_rejects_counts_beyond_the_bucket():
    st = _state(random.Random(3), n_members=2)
    enc = encode_group(st, max_consumers=4, max_scale_pct=100)
    with pytest.raises(ValueError, match="usable consumer columns"):
        # Even counts inside the PAD range (c < k <= c_pad) must refuse:
        # pad columns have capacity 0 and no member behind them.
        group_sweep_envelope(enc, [enc.c + 1], [100], True)


def test_default_counts_respects_the_candidate_budget():
    counts = default_counts(real_members=10, n_scales=3, max_candidates=12)
    assert counts == [1, 2, 3, 4]
    assert default_counts(0, 1, 256)[:4] == [1, 2, 3, 4]


# --- plan envelopes + crash fallback -----------------------------------------

def test_plan_envelope_schema_and_stability():
    st = _state(random.Random(5))
    enc = encode_group(st)
    body1, d1 = group_plan_envelope(enc, groups_real=True)
    body2, d2 = group_plan_envelope(enc, groups_real=True)
    assert not d1 and not d2
    assert json.dumps(body1, sort_keys=True) \
        == json.dumps(body2, sort_keys=True)
    assert validate_groups_plan(body1) == []
    # Every real partition row decodes to an owner.
    decoded = decode_plan(enc, [
        enc.members.index(body1["plan"][t][str(p)])
        for t, p in enc.rows
    ])
    assert decoded == {
        t: {int(p): m for p, m in per.items()}
        for t, per in body1["plan"].items()
    }


def test_plan_device_crash_falls_back_to_oracle_bytes(monkeypatch):
    st = _state(random.Random(6))
    enc = encode_group(st)
    base, _ = group_plan_envelope(enc, groups_real=True)

    monkeypatch.setenv("KA_FAULTS_SPEC", "solve:0=crash")
    faults.reset()
    body, degraded = group_plan_envelope(
        enc, groups_real=True, fallback="greedy",
    )
    assert degraded and body["solver"] == "greedy-fallback"
    strip = lambda b: {k: v for k, v in b.items() if k != "solver"}  # noqa: E731
    assert strip(body) == strip(base)  # the parity pin, end to end

    faults.reset()
    with pytest.raises(SolveError):
        group_plan_envelope(enc, groups_real=True, fallback="raise")


# --- backend hooks ------------------------------------------------------------

def _snapshot_file(tmp_path, with_groups=True):
    snap = {
        "brokers": [
            {"id": i, "host": f"b{i}", "port": 9092} for i in range(3)
        ],
        "topics": {"events": {str(p): [0, 1] for p in range(4)}},
    }
    if with_groups:
        snap["groups"] = {"g": {
            "members": {"c-0": 90.0, "c-1": None},
            "assignment": {"events": {"0": "c-0", "1": "c-1"}},
            "lag": {"events": {str(p): 10 * (p + 1) for p in range(4)}},
        }}
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(snap), encoding="utf-8")
    return str(path)


def test_snapshot_groups_section_parses(tmp_path):
    b = SnapshotBackend(_snapshot_file(tmp_path))
    assert b.supports_groups()
    states = b.fetch_consumer_groups()
    st = states["g"]
    assert [m.member_id for m in st.members] == ["c-0", "c-1"]
    assert st.members[0].capacity == 90.0
    assert st.members[1].capacity == 0.0  # null = unknown
    assert st.assignment["events"][1] == "c-1"
    assert st.lags["events"][3] == 40
    with pytest.raises(KeyError, match="not in snapshot"):
        b.fetch_consumer_groups(["nope"])


def test_snapshot_without_section_refuses_loudly(tmp_path):
    b = SnapshotBackend(_snapshot_file(tmp_path, with_groups=False))
    assert not b.supports_groups()
    with pytest.raises(IngestError, match="groups"):
        b.fetch_consumer_groups()


def test_base_protocol_default_refuses(tmp_path):
    class Duck:
        pass

    from kafka_assigner_tpu.io.base import MetadataBackend

    class Sub(MetadataBackend):
        def brokers(self):
            return []

        def all_topics(self):
            return []

        def partition_assignment(self, topics):
            return {}

    with pytest.raises(IngestError, match="cannot read consumer groups"):
        Sub().fetch_consumer_groups()
    assert Sub().supports_groups() is False


def test_write_snapshot_roundtrips_groups(tmp_path):
    path = str(tmp_path / "rt.json")
    groups_raw = {"g": {
        "members": {"c-0": 5.0},
        "assignment": {"t": {"0": "c-0"}},
        "lag": {"t": {"0": 3}},
    }}
    write_snapshot(
        path, [], {"t": {0: [1]}}, groups=groups_raw,
    )
    b = SnapshotBackend(path)
    assert b.supports_groups()
    assert b.fetch_consumer_groups()["g"].lags == {"t": {0: 3}}


def test_load_group_states_synthetic_is_explicit_and_marked(tmp_path):
    b = SnapshotBackend(_snapshot_file(tmp_path, with_groups=False))
    parts = {"events": [0, 1, 2, 3]}
    with pytest.raises(IngestError):
        load_group_states(b, parts)
    states, real = load_group_states(b, parts, synthetic=True)
    assert not real and set(states) == {"synthetic"}
    st = states["synthetic"]
    # Deterministic: the same inputs rebuild the identical state.
    st2 = synthetic_group_state("synthetic", parts)
    assert st == st2
    # Capacities stay UNKNOWN (0): the encoder's fair-share default then
    # derives them from whichever weight column the run packs, so the
    # synthetic family is coherent for lag AND throughput weights.
    assert all(m.capacity == 0 for m in st.members)


# --- the CLI surface ----------------------------------------------------------

def _run_cli(argv):
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run_groups(argv)
    return rc, out.getvalue(), err.getvalue()


def test_cli_plan_byte_stable_and_valid(tmp_path):
    path = _snapshot_file(tmp_path)
    rc1, out1, _ = _run_cli(["--zk_string", path, "--mode", "plan"])
    rc2, out2, _ = _run_cli(["--zk_string", path, "--mode", "plan"])
    assert rc1 == rc2 == 0 and out1 == out2
    body = json.loads(out1)
    assert validate_groups_plan(body) == []
    assert body["groups_real"] is True


def test_cli_sweep_64_candidates_byte_stable(tmp_path):
    path = _snapshot_file(tmp_path)
    argv = ["--zk_string", path, "--mode", "sweep",
            "--counts", "1,2,3,4,5,6,7,8",
            "--scales", "100,125,150,200,300,400,600,800"]
    rc1, out1, _ = _run_cli(argv)
    rc2, out2, _ = _run_cli(argv)
    assert rc1 == rc2 == 0 and out1 == out2
    body = json.loads(out1)
    assert validate_groups_sweep(body) == []
    assert len(body["candidates"]) == 64


def test_cli_refusal_and_synthetic(tmp_path):
    path = _snapshot_file(tmp_path, with_groups=False)
    rc, out, err = _run_cli(["--zk_string", path, "--mode", "plan"])
    assert rc == 1 and out == ""
    assert "--synthetic" in err
    rc, out, _ = _run_cli(
        ["--zk_string", path, "--mode", "plan", "--synthetic"]
    )
    assert rc == 0
    body = json.loads(out)
    assert body["groups_real"] is False


def test_cli_crash_fallback_policies(tmp_path, monkeypatch):
    path = _snapshot_file(tmp_path)
    rc, base_out, _ = _run_cli(["--zk_string", path, "--mode", "plan"])
    assert rc == 0

    monkeypatch.setenv("KA_FAULTS_SPEC", "solve:0=crash")
    faults.reset()
    with pytest.raises(SolveError):
        _run_cli(["--zk_string", path, "--mode", "plan",
                  "--failure-policy", "strict"])

    faults.reset()
    rc, out, err = _run_cli(
        ["--zk_string", path, "--mode", "plan",
         "--failure-policy", "best-effort"]
    )
    assert rc == 6 and "degraded" in err
    strip = lambda b: {k: v for k, v in b.items() if k != "solver"}  # noqa: E731
    assert strip(json.loads(out)) == strip(json.loads(base_out))


def test_cli_greedy_solver_matches_device(tmp_path):
    path = _snapshot_file(tmp_path)
    _rc, dev, _ = _run_cli(["--zk_string", path, "--mode", "plan"])
    _rc, host, _ = _run_cli(
        ["--zk_string", path, "--mode", "plan", "--solver", "greedy"]
    )
    strip = lambda raw: {  # noqa: E731
        k: v for k, v in json.loads(raw).items() if k != "solver"
    }
    assert strip(dev) == strip(host)


def test_cli_throughput_weight_column(tmp_path):
    path = _snapshot_file(tmp_path)
    rc, out, _ = _run_cli(
        ["--zk_string", path, "--mode", "plan", "--weight", "throughput"]
    )
    assert rc == 0
    assert json.loads(out)["weight"] == "throughput"


# --- the daemon endpoints -----------------------------------------------------

def _daemon(tmp_path, with_groups=True):
    from kafka_assigner_tpu.daemon import AssignerDaemon

    d = AssignerDaemon(
        _snapshot_file(tmp_path, with_groups=with_groups), solver="greedy",
    )
    d.start()
    return d


def _req(port, method, path, payload=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request(
            method, path,
            body=None if payload is None else json.dumps(payload),
        )
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_daemon_groups_plan_get_post_identical(tmp_path):
    d = _daemon(tmp_path)
    try:
        s1, raw1 = _req(d.http_port, "GET", "/groups/plan")
        s2, raw2 = _req(d.http_port, "POST", "/groups/plan", {})
        assert s1 == s2 == 200 and raw1 == raw2
        env = json.loads(raw1)
        assert env["schema_version"] == GROUPS_SCHEMA_VERSION
        assert env["kind"] == "groups-plan"
        assert validate_groups_plan(env["groups"]["g"]) == []
        assert d.supervisor().counters()["groups.plans"] == 2
    finally:
        d.shutdown()


def test_daemon_groups_sweep_params_and_counters(tmp_path):
    d = _daemon(tmp_path)
    try:
        s, raw = _req(d.http_port, "POST", "/groups/sweep", {
            "counts": [1, 2, 3, 4, 5, 6, 7, 8],
            "scales": [100, 150, 200, 300, 400, 500, 600, 800],
        })
        assert s == 200
        body = json.loads(raw)["groups"]["g"]
        assert validate_groups_sweep(body) == []
        assert len(body["candidates"]) == 64
        counters = d.supervisor().counters()
        assert counters["groups.sweeps"] == 1
        # GET query form with CSV lists
        s, raw = _req(
            d.http_port, "GET", "/groups/sweep?counts=1,2&scales=100"
        )
        assert s == 200
        assert len(json.loads(raw)["groups"]["g"]["candidates"]) == 2
    finally:
        d.shutdown()


def test_daemon_groups_refusal_and_synthetic(tmp_path):
    d = _daemon(tmp_path, with_groups=False)
    try:
        s, raw = _req(d.http_port, "GET", "/groups/plan")
        assert s == 400 and b"synthetic" in raw
        assert d.supervisor().counters()["groups.refusals"] == 1
        s, raw = _req(d.http_port, "GET", "/groups/plan?synthetic=1")
        assert s == 200
        body = json.loads(raw)
        assert body["groups_real"] is False
        assert validate_groups_plan(body["groups"]["synthetic"]) == []
    finally:
        d.shutdown()


def test_daemon_groups_solver_crash_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_FAULTS_SPEC", "daemon:0=solver-crash")
    faults.reset()
    d = _daemon(tmp_path)
    try:
        s, raw = _req(d.http_port, "GET", "/groups/plan")
        assert s == 200
        env = json.loads(raw)
        assert env["degraded"] is True
        assert env["groups"]["g"]["solver"] == "greedy-fallback"
        counters = d.supervisor().counters()
        assert counters["groups.solve_fallbacks"] == 1
        # The next request (fault exhausted) serves clean and the packing
        # content matches the degraded one — the parity pin, live.
        s, raw2 = _req(d.http_port, "GET", "/groups/plan")
        clean = json.loads(raw2)
        assert clean["degraded"] is False
        strip = lambda b: {  # noqa: E731
            k: v for k, v in b["groups"]["g"].items() if k != "solver"
        }
        assert strip(clean) == strip(env)
    finally:
        d.shutdown()


def test_daemon_groups_bad_params_are_400(tmp_path):
    d = _daemon(tmp_path)
    try:
        s, raw = _req(
            d.http_port, "POST", "/groups/sweep", {"counts": "x,y"}
        )
        assert s == 400 and b"bad groups request" in raw
        s, raw = _req(
            d.http_port, "POST", "/groups/plan", {"group": ["g", 3]}
        )
        assert s == 400
        s, raw = _req(
            d.http_port, "POST", "/groups/plan", {"group": "nope"}
        )
        assert s == 400  # unknown group: KeyError from the snapshot
    finally:
        d.shutdown()


# --- validators (negative space) ---------------------------------------------

def test_validators_catch_missing_fields():
    assert validate_groups_plan({}) != []
    assert validate_groups_plan("nope") != []
    good_sweepish = {
        "schema_version": GROUPS_SCHEMA_VERSION, "kind": "groups-sweep",
        "group": "g", "groups_real": True, "weight": "lag",
        "candidates": [{}], "recommended_consumers": None,
    }
    probs = validate_groups_sweep(good_sweepish)
    assert any("consumers" in p for p in probs)
    assert validate_groups_sweep(
        {**good_sweepish, "candidates": []}
    ) != []


# --- review-hardening regressions --------------------------------------------

def test_partition_universe_widens_to_subscribed_topics():
    from kafka_assigner_tpu.groups.solve import group_partition_universe

    st = ConsumerGroupState(
        "g", (GroupMember("c-0", 100.0),),
        {"events": {0: "c-0"}},          # group only mentions partition 0
        {"events": {0: 5}},
    )
    part_map = {"events": [0, 1, 2, 3], "unrelated": [0, 1]}
    universe = group_partition_universe(st, part_map)
    # Subscribed topic widens to the cluster's full partition list;
    # unsubscribed topics stay out of the packing problem.
    assert universe == {"events": [0, 1, 2, 3]}
    enc = encode_group(st, partitions=universe)
    assert enc.rows == [("events", 0), ("events", 1), ("events", 2),
                        ("events", 3)]
    body, _ = group_plan_envelope(enc, groups_real=True)
    assert set(body["plan"]["events"]) == {"0", "1", "2", "3"}


def test_cli_plan_covers_cluster_partitions_of_subscribed_topics(tmp_path):
    snap = {
        "brokers": [{"id": 0, "host": "b0", "port": 9092}],
        "topics": {
            "events": {str(p): [0] for p in range(6)},
            "other": {"0": [0]},
        },
        "groups": {"g": {
            "members": {"c-0": 1000.0},
            "assignment": {"events": {"0": "c-0"}},  # partial coverage
            "lag": {"events": {"0": 3}},
        }},
    }
    path = tmp_path / "partial.json"
    path.write_text(json.dumps(snap), encoding="utf-8")
    rc, out, _ = _run_cli(["--zk_string", str(path), "--mode", "plan"])
    assert rc == 0
    body = json.loads(out)
    assert set(body["plan"]) == {"events"}  # "other" is unsubscribed
    assert set(body["plan"]["events"]) == {str(p) for p in range(6)}


def test_daemon_get_single_value_counts_and_scales(tmp_path):
    # ?counts=1 must stay the string "1", not coerce to boolean True
    # (the query normalization is keyed to the known boolean params).
    d = _daemon(tmp_path)
    try:
        s, raw = _req(
            d.http_port, "GET", "/groups/sweep?counts=1&scales=100"
        )
        assert s == 200, raw
        cands = json.loads(raw)["groups"]["g"]["candidates"]
        assert [(c["consumers"], c["scale_pct"]) for c in cands] \
            == [(1, 100)]
    finally:
        d.shutdown()


def test_daemon_groups_counters_not_double_fed(tmp_path):
    # One request, one group => exactly one groups.plans increment in the
    # cumulative registry (the envelope builders do not also count).
    from kafka_assigner_tpu.obs import promtext

    d = _daemon(tmp_path)
    try:
        s, _raw = _req(d.http_port, "GET", "/groups/plan")
        assert s == 200
        s, m = _req(d.http_port, "GET", "/metrics")
        fams = promtext.parse(m.decode("utf-8"))
        plans = sum(
            v for _n, _labels, v in
            fams["ka_groups_plans_total"]["samples"]
        )
        assert plans == 1.0
    finally:
        d.shutdown()


def test_groups_ingest_happens_outside_the_solve_lock(tmp_path):
    # A slow backend group fetch must not serialize behind (or hold) the
    # shared solve lock: with the lock HELD by another thread, the fetch
    # still runs; the request only blocks at the dispatch stage.
    import threading
    import time as time_mod

    from kafka_assigner_tpu.daemon import AssignerDaemon

    d = AssignerDaemon(_snapshot_file(tmp_path), solver="greedy")
    d.start()
    try:
        sup = d.supervisor()
        fetched = threading.Event()
        orig_fetch = sup.backend.fetch_consumer_groups

        def marking_fetch(groups=None):
            fetched.set()
            return orig_fetch(groups)

        sup.backend.fetch_consumer_groups = marking_fetch
        with d._solve_lock:  # simulate another cluster's long solve
            t = threading.Thread(
                target=sup.groups_request, args=("plan", {}), daemon=True,
            )
            t.start()
            deadline = time_mod.monotonic() + 10
            while not fetched.is_set() \
                    and time_mod.monotonic() < deadline:
                time_mod.sleep(0.01)
            # The ingest completed while the solve lock was held.
            assert fetched.is_set()
        t.join(timeout=30)
        assert not t.is_alive()
    finally:
        d.shutdown()


def test_capacity_default_is_fair_share_times_headroom():
    # Mixed declared/unknown capacities: the undeclared member gets the
    # fair share of total weight times the headroom factor — the
    # KA_GROUPS_CAPACITY_HEADROOM contract — NOT the declared members'
    # average (which would leave the knob silently dead).
    st = ConsumerGroupState(
        "g",
        (GroupMember("c-0", 400.0), GroupMember("c-1", 400.0),
         GroupMember("c-2", 0.0)),
        {"t": {p: None for p in range(3)}},
        {"t": {p: 99 for p in range(3)}},  # weight 100 each, total 300
    )
    enc1 = encode_group(st, capacity_headroom=1.0)
    enc2 = encode_group(st, capacity_headroom=2.0)
    assert int(enc1.capacities[0]) == int(enc2.capacities[0]) == 400
    assert int(enc1.capacities[2]) == 100   # ceil(300 * 1.0 / 3)
    assert int(enc2.capacities[2]) == 200   # the knob is live


def test_parse_int_list_forgives_trailing_commas():
    from kafka_assigner_tpu.groups.solve import parse_int_list

    assert parse_int_list("100,150,") == [100, 150]
    assert parse_int_list(None, "1,2") == [1, 2]
    assert parse_int_list(None) is None
    assert parse_int_list([3, "4"]) == [3, 4]
    with pytest.raises(ValueError):
        parse_int_list(True)
    with pytest.raises(ValueError):
        parse_int_list("x,y")


def test_cli_forgives_trailing_comma_in_scales(tmp_path):
    path = _snapshot_file(tmp_path)
    rc, out, _ = _run_cli(
        ["--zk_string", path, "--mode", "sweep",
         "--counts", "1,2,", "--scales", "100,"]
    )
    assert rc == 0
    assert len(json.loads(out)["candidates"]) == 2


def test_synthetic_throughput_weights_are_coherent(tmp_path):
    # --synthetic --weight throughput: capacities derive from the SAME
    # byte-rate column as the weights (fair share x headroom), so the
    # default packing is feasible — not lag-unit capacities against
    # byte-unit weights.
    path = _snapshot_file(tmp_path, with_groups=False)
    rc, out, _ = _run_cli(
        ["--zk_string", path, "--mode", "plan", "--synthetic",
         "--weight", "throughput"]
    )
    assert rc == 0
    body = json.loads(out)
    assert body["weight"] == "throughput"
    assert body["feasible"] is True and body["overflowed"] == 0


def test_daemon_synthetic_string_false_is_not_an_opt_in(tmp_path):
    d = _daemon(tmp_path, with_groups=False)
    try:
        s, raw = _req(
            d.http_port, "POST", "/groups/plan", {"synthetic": "false"}
        )
        assert s == 400 and b"synthetic" in raw  # the refusal, not a plan
        s, raw = _req(
            d.http_port, "POST", "/groups/plan", {"synthetic": "junk"}
        )
        assert s == 400 and b"must be a boolean" in raw
        s, raw = _req(
            d.http_port, "POST", "/groups/plan", {"synthetic": "true"}
        )
        assert s == 200
        assert json.loads(raw)["groups_real"] is False
    finally:
        d.shutdown()


def test_daemon_backend_blackout_is_503_not_refusal(tmp_path):
    d = _daemon(tmp_path)
    try:
        sup = d.supervisor()
        real_backend = sup.backend
        sup.backend = None  # the mid-reopen window of a quorum blackout
        try:
            code, body, headers = sup.groups_request("plan", {})
        finally:
            sup.backend = real_backend
        assert code == 503
        assert "unavailable" in body["error"]
        assert headers.get("Retry-After")
        assert "groups.refusals" not in sup.counters()
    finally:
        d.shutdown()
