"""Differential pinning of the incremental what-if sweep against the dense
sweep oracle (KA_WHATIF_INCREMENTAL=0). The incremental path skips topics it
can PROVE reproduce their input; these tests feed it the inputs that could
break that proof — duplicate replicas, dead/unknown brokers, rack
collisions, over-capacity topics, short rows, multi-broker removals, mixed
RF — and require bit-equal ScenarioResults.

Every cluster here is sized so the profitability gate actually ADMITS the
incremental path, and a probe asserts it ran — a mostly-dirty or tiny
cluster silently declines to the dense sweep and the comparison becomes
vacuous (an earlier revision of this file did exactly that)."""
from __future__ import annotations

import random

import pytest

import kafka_assigner_tpu.parallel.whatif as whatif_mod
from kafka_assigner_tpu.parallel.whatif import (
    evaluate_removal_scenarios,
    rank_decommission_candidates,
)


def _both(monkeypatch, *args, expect_incremental=True, **kwargs):
    """Run incremental-enabled vs dense-forced; assert the incremental path
    genuinely executed (returned results rather than declining)."""
    taken = {}
    orig = whatif_mod._evaluate_incremental

    def probe(*a, **k):
        r = orig(*a, **k)
        taken["ran"] = r is not None
        return r

    monkeypatch.setattr(whatif_mod, "_evaluate_incremental", probe)
    monkeypatch.delenv("KA_WHATIF_INCREMENTAL", raising=False)
    inc = evaluate_removal_scenarios(*args, **kwargs)
    if expect_incremental:
        assert taken.get("ran"), (
            "incremental path declined — this differential test is vacuous"
        )
    monkeypatch.setenv("KA_WHATIF_INCREMENTAL", "0")
    full = evaluate_removal_scenarios(*args, **kwargs)
    monkeypatch.delenv("KA_WHATIF_INCREMENTAL")
    return inc, full


def _rack_groups(brokers, racks):
    groups = {}
    for b in sorted(brokers):
        groups.setdefault(racks[b], []).append(b)
    return [groups[r] for r in sorted(groups)]


def _clean_topic(groups, topic_idx, p, rf):
    """Rack-diverse, duplicate-free rows with NO broker reused across rows —
    per-node load 1, safely under any cap >= 1."""
    n_racks = len(groups)
    cur = {}
    for pid in range(p):
        row = []
        for r in range(rf):
            g = groups[(topic_idx + pid + r) % n_racks]
            # coprime stride de-clusters which broker each topic lands on
            # (a straight topic_idx index made single brokers host 2x the
            # pigeonhole-expected topic count, tripping the profitability
            # gate these tests must pass)
            row.append(g[(topic_idx * 7 + pid * rf + r) % len(g)])
        if len(set(row)) != rf:  # same group revisited: shift the collision
            row = [groups[(topic_idx + pid + r) % n_racks][
                (topic_idx * 7 + pid * rf + r * 2 + 1) % len(
                    groups[(topic_idx + pid + r) % n_racks]
                )
            ] for r in range(rf)]
        cur[pid] = row
    return cur


def _dirty_row(rng, brokers, racks):
    kind = rng.random()
    pool = sorted(brokers)
    if kind < 0.25:  # duplicate broker in a row
        b0 = rng.choice(pool)
        return [b0, b0, rng.choice(pool)]
    if kind < 0.50:  # dead/unknown broker
        return [99999, *rng.sample(pool, 2)]
    if kind < 0.75:  # short row (under-replicated)
        return rng.sample(pool, 2)
    base = rng.choice(pool)  # rack collision
    twin = next(
        (b for b in pool if b != base and racks[b] == racks[base]), base
    )
    return [base, twin, rng.choice(pool)]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_clusters_match_dense_sweep(monkeypatch, seed):
    # Mostly-clean cluster, large enough that the gate admits the
    # incremental path; a dirty minority of topics must be re-solved.
    rng = random.Random(seed)
    brokers = set(range(1, 97))
    racks = {b: f"r{b % 6}" for b in brokers}
    groups = _rack_groups(brokers, racks)
    topics = {}
    for i in range(160):
        p = rng.randint(1, 3)
        cur = _clean_topic(groups, i, p, 3)
        if rng.random() < 0.06:  # dirty minority
            cur[rng.randrange(p)] = _dirty_row(rng, brokers, racks)
        topics[f"t{i:03d}"] = cur
    scenarios = [
        rng.sample(sorted(brokers), rng.randint(0, 2)) for _ in range(12)
    ]
    inc, full = _both(monkeypatch, topics, brokers, racks, scenarios, 3)
    assert inc == full


def test_over_capacity_topic_not_skipped(monkeypatch):
    # A skewed topic whose max per-node load exceeds the scenario cap must
    # be re-solved even when it hosts none of the removed brokers; the rest
    # of the cluster is clean so the gate admits the incremental path.
    brokers = set(range(1, 31))
    racks = {b: f"r{b % 5}" for b in brokers}
    groups = _rack_groups(brokers, racks)
    topics = {
        f"bg{i:02d}": _clean_topic(groups, i, 2, 2) for i in range(64)
    }
    # hot: brokers 1-2 hold 3-4 replicas each; cap for 8 partitions x RF2
    # over 29-30 brokers is 1 -> over-cap, re-solved in EVERY scenario
    topics["hot"] = {p: [1 + p % 2, 3 + p % 6] for p in range(8)}
    scenarios = [[b] for b in sorted(brokers)[:10]]
    inc, full = _both(monkeypatch, topics, brokers, racks, scenarios, -1)
    assert inc == full
    # the hot topic makes every scenario move replicas (cap eviction)
    assert all(r.moved_replicas > 0 for r in inc)


def test_mixed_rf_matches(monkeypatch):
    brokers = set(range(1, 49))
    racks = {b: f"r{b % 4}" for b in brokers}
    groups = _rack_groups(brokers, racks)
    topics = {}
    for i in range(64):
        topics[f"rf2-{i}"] = _clean_topic(groups, i, 2, 2)
    for i in range(64):
        topics[f"rf3-{i}"] = _clean_topic(groups, i + 7, 2, 3)
    scenarios = [[b] for b in sorted(brokers)[:8]]
    inc, full = _both(monkeypatch, topics, brokers, racks, scenarios, -1)
    assert inc == full


def test_rank_decommission_matches(monkeypatch):
    brokers = set(range(1, 33))
    racks = {b: f"r{b % 4}" for b in brokers}
    groups = _rack_groups(brokers, racks)
    topics = {
        f"t{i:02d}": _clean_topic(groups, i, 3, 2) for i in range(48)
    }
    monkeypatch.delenv("KA_WHATIF_INCREMENTAL", raising=False)
    inc = rank_decommission_candidates(topics, brokers, racks)
    monkeypatch.setenv("KA_WHATIF_INCREMENTAL", "0")
    full = rank_decommission_candidates(topics, brokers, racks)
    assert inc == full


def test_small_cluster_declines_to_dense(monkeypatch):
    # Tiny clusters are mostly-affected: the gate must decline and the dense
    # sweep must serve the result (decline correctness, not a differential).
    brokers = set(range(1, 9))
    racks = {b: f"r{b % 4}" for b in brokers}
    topics = {"t": {p: [1 + p % 8, 1 + (p + 3) % 8] for p in range(5)}}
    inc, full = _both(
        monkeypatch, topics, brokers, racks, [[1], [2]], -1,
        expect_incremental=False,
    )
    assert inc == full
