"""Mixed-RF batched solving: one device dispatch for a topic list whose
replication factors interleave, with output identical to solving the topics
serially through the same shared Context (the reference's topic loop,
``KafkaAssignmentGenerator.java:173-176`` + ``KafkaTopicAssigner.java:19-23``).
Before round 3 the assigner split the batch at every RF change."""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner
from kafka_assigner_tpu.solvers.base import Context
from kafka_assigner_tpu.solvers.tpu import TpuSolver


def _cluster():
    brokers = set(range(1, 25))
    racks = {b: f"r{b % 4}" for b in brokers}
    topics = []
    for i in range(6):
        rf = 2 if i % 2 == 0 else 3  # interleaved RFs
        cur = {
            p: [1 + (p + i + r * 5) % 24 for r in range(rf)]
            for p in range(4 + i % 3)
        }
        topics.append((f"t{i}", cur))
    return topics, brokers, racks


def test_batched_mixed_rf_equals_serial_context_evolution():
    topics, brokers, racks = _cluster()
    batched = TopicAssigner("tpu").generate_assignments(
        topics, brokers, racks, -1
    )

    solver = TpuSolver()
    ctx = Context()
    serial = []
    for topic, cur in topics:
        rf = len(next(iter(cur.values())))
        serial.append(
            (topic, solver.assign(topic, cur, racks, set(brokers), set(cur),
                                  rf, ctx))
        )
    assert batched == serial


def test_mixed_rf_one_dispatch(monkeypatch):
    # The assigner must NOT split the mixed batch into per-RF runs.
    topics, brokers, racks = _cluster()
    calls = []
    orig = TpuSolver.assign_many

    def spy(self, named_currents, *a, **k):
        calls.append(len(named_currents))
        return orig(self, named_currents, *a, **k)

    monkeypatch.setattr(TpuSolver, "assign_many", spy)
    TopicAssigner("tpu").generate_assignments(topics, brokers, racks, -1)
    assert calls == [len(topics)], calls


def test_mixed_rf_device_leadership_agrees(monkeypatch):
    topics, brokers, racks = _cluster()
    monkeypatch.delenv("KA_LEADERSHIP", raising=False)
    default = TopicAssigner("tpu").generate_assignments(
        topics, brokers, racks, -1
    )
    monkeypatch.setenv("KA_LEADERSHIP", "device")
    device = TopicAssigner("tpu").generate_assignments(
        topics, brokers, racks, -1
    )
    assert default == device


def test_mixed_rf_movement_parity_with_greedy():
    topics, brokers, racks = _cluster()
    tpu = TopicAssigner("tpu").generate_assignments(topics, brokers, racks, -1)
    gre = TopicAssigner("greedy").generate_assignments(
        topics, brokers, racks, -1
    )
    by = dict(topics)
    m_t = sum(
        1 for t, a in tpu for p, r in a.items() for b in r if b not in by[t][p]
    )
    m_g = sum(
        1 for t, a in gre for p, r in a.items() for b in r if b not in by[t][p]
    )
    assert m_t == m_g
